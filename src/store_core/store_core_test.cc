// Unit tests for the native store core (assert-based; no gtest in the
// image).  Covers the invariants the Python suite can't see from outside
// the C ABI: free-list reuse, neighbor coalescing, bump retreat,
// fragmentation behavior, capacity accounting, and index lifecycle.
// `make test` runs them under AddressSanitizer (the plasma component is
// where memory bugs corrupt user payloads — reference keeps its
// eviction/alloc under sanitizers the same way).

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <atomic>
#include <string>
#include <thread>
#include <vector>

#include <unistd.h>

extern "C" {
void* rtpu_store_create(const char* path, uint64_t capacity);
int rtpu_store_put(void* h, const uint8_t* oid, uint64_t size, uint64_t* off);
int rtpu_store_seal(void* h, const uint8_t* oid);
int rtpu_store_get(void* h, const uint8_t* oid, uint64_t* off, uint64_t* size,
                   int* sealed);
int rtpu_store_delete(void* h, const uint8_t* oid);
uint64_t rtpu_store_bytes_used(void* h);
uint64_t rtpu_store_capacity(void* h);
uint64_t rtpu_store_num_objects(void* h);
uint64_t rtpu_store_num_free_blocks(void* h);
void rtpu_store_close(void* h, int unlink_file);
void* rtpu_refs_create();
void rtpu_refs_ensure(void* h, const uint8_t* oids, int64_t n,
                      int32_t reason);
int rtpu_refs_contains(void* h, const uint8_t* oid);
void rtpu_refs_add(void* h, const uint8_t* oids, int64_t n, int32_t reason,
                   int64_t delta);
int64_t rtpu_refs_remove(void* h, const uint8_t* oids, int64_t n,
                         int32_t reason, int64_t delta, uint8_t* dead_out);
int rtpu_refs_seal(void* h, const uint8_t* oid);
int rtpu_refs_unseal(void* h, const uint8_t* oid);
int rtpu_refs_erase(void* h, const uint8_t* oid);
int rtpu_refs_get(void* h, const uint8_t* oid, int64_t* count_out,
                  int32_t* sealed_out, int32_t* pins_out);
void rtpu_refs_get_batch(void* h, const uint8_t* oids, int64_t n,
                         int64_t* counts, int32_t* pins);
uint64_t rtpu_refs_size(void* h);
int rtpu_refs_set_origin(void* h, const uint8_t* oid, int32_t slot);
int rtpu_refs_add_replica(void* h, const uint8_t* oid, int32_t slot);
int rtpu_refs_pop_replica(void* h, const uint8_t* oid);
int rtpu_refs_num_replicas(void* h, const uint8_t* oid);
void rtpu_refs_drop_slot(void* h, int32_t slot);
void rtpu_refs_locate(void* h, const uint8_t* oids, int64_t n,
                      int32_t prefer_slot, int32_t* out);
void rtpu_refs_clear(void* h);
}

namespace {

constexpr uint64_t kAlign = 64;

struct Oid {
  uint8_t b[16];
  explicit Oid(int i) {
    std::memset(b, 0, sizeof(b));
    std::memcpy(b, &i, sizeof(i));
  }
};

std::string tmp_path() {
  static int n = 0;
  return "/tmp/rtpu-store-test-" + std::to_string(::getpid()) + "-" +
         std::to_string(n++);
}

void test_create_put_get_seal_delete() {
  auto p = tmp_path();
  void* h = rtpu_store_create(p.c_str(), 1 << 20);
  assert(h != nullptr);
  // duplicate create must fail (O_EXCL)
  assert(rtpu_store_create(p.c_str(), 1 << 20) == nullptr);

  Oid a(1);
  uint64_t off = 0, size = 0;
  int sealed = -1;
  assert(rtpu_store_put(h, a.b, 1000, &off) == 0);
  assert(off >= 4096 && off % kAlign == 0);  // data starts after the header
  assert(rtpu_store_put(h, a.b, 1000, &off) == -1);  // dup oid
  assert(rtpu_store_get(h, a.b, &off, &size, &sealed) == 0);
  assert(size == 1000 && sealed == 0);
  assert(rtpu_store_seal(h, a.b) == 0);
  assert(rtpu_store_get(h, a.b, &off, &size, &sealed) == 0 && sealed == 1);
  assert(rtpu_store_num_objects(h) == 1);
  assert(rtpu_store_bytes_used(h) == (1000 + kAlign - 1) / kAlign * kAlign);
  assert(rtpu_store_delete(h, a.b) == 0);
  assert(rtpu_store_delete(h, a.b) == -1);
  assert(rtpu_store_get(h, a.b, &off, &size, &sealed) == -1);
  assert(rtpu_store_num_objects(h) == 0 && rtpu_store_bytes_used(h) == 0);
  rtpu_store_close(h, 1);
  std::puts("  create/put/get/seal/delete OK");
}

void test_free_list_reuse_and_coalescing() {
  auto p = tmp_path();
  void* h = rtpu_store_create(p.c_str(), 1 << 20);
  uint64_t off[4];
  for (int i = 0; i < 4; ++i) {
    Oid o(i);
    assert(rtpu_store_put(h, o.b, 4096, &off[i]) == 0);
  }
  // delete middle neighbors -> ONE coalesced free block
  Oid o1(1), o2(2);
  assert(rtpu_store_delete(h, o1.b) == 0);
  assert(rtpu_store_num_free_blocks(h) == 1);
  assert(rtpu_store_delete(h, o2.b) == 0);
  assert(rtpu_store_num_free_blocks(h) == 1);  // coalesced, not 2
  // a fit into the hole reuses the SAME offset (first-fit recycling)
  Oid o4(4);
  uint64_t off4 = 0;
  assert(rtpu_store_put(h, o4.b, 8192, &off4) == 0);
  assert(off4 == off[1]);
  assert(rtpu_store_num_free_blocks(h) == 0);
  // deleting the LAST object retreats the bump instead of listing
  Oid o3(3);
  assert(rtpu_store_delete(h, o3.b) == 0);
  assert(rtpu_store_num_free_blocks(h) == 0);
  // ...so the next alloc lands exactly where object 3 was
  Oid o5(5);
  uint64_t off5 = 0;
  assert(rtpu_store_put(h, o5.b, 64, &off5) == 0);
  assert(off5 == off[3]);
  rtpu_store_close(h, 1);
  std::puts("  free-list reuse + coalescing OK");
}

void test_fragmentation_and_split() {
  auto p = tmp_path();
  void* h = rtpu_store_create(p.c_str(), 1 << 20);
  uint64_t off[8];
  for (int i = 0; i < 8; ++i) {
    Oid o(i);
    assert(rtpu_store_put(h, o.b, 1024, &off[i]) == 0);
  }
  // checkerboard delete -> 4 disjoint holes
  for (int i = 0; i < 8; i += 2) {
    Oid o(i);
    assert(rtpu_store_delete(h, o.b) == 0);
  }
  assert(rtpu_store_num_free_blocks(h) == 4);
  // small alloc splits a hole, leaving remainder on the list
  Oid s(100);
  uint64_t soff = 0;
  assert(rtpu_store_put(h, s.b, 128, &soff) == 0);
  assert(soff == off[0]);
  assert(rtpu_store_num_free_blocks(h) == 4);  // split kept the remainder
  // an alloc larger than any hole must go to the bump frontier
  Oid big(101);
  uint64_t boff = 0;
  assert(rtpu_store_put(h, big.b, 4096, &boff) == 0);
  assert(boff > off[7]);
  rtpu_store_close(h, 1);
  std::puts("  fragmentation/split OK");
}

void test_capacity_exhaustion() {
  auto p = tmp_path();
  void* h = rtpu_store_create(p.c_str(), 64 << 10);
  Oid a(1), b(2), c(3);
  uint64_t off = 0;
  assert(rtpu_store_put(h, a.b, 40 << 10, &off) == 0);
  assert(rtpu_store_put(h, b.b, 40 << 10, &off) == -2);  // doesn't fit
  // freeing makes room again (recycled, not grown)
  assert(rtpu_store_delete(h, a.b) == 0);
  assert(rtpu_store_put(h, c.b, 40 << 10, &off) == 0);
  // zero-size objects still get a distinct slot
  Oid z(4);
  uint64_t zoff = 0;
  assert(rtpu_store_put(h, z.b, 0, &zoff) == 0);
  uint64_t got_off = 0, got_size = 1;
  int sealed = 0;
  assert(rtpu_store_get(h, z.b, &got_off, &got_size, &sealed) == 0);
  assert(got_size == 0);
  rtpu_store_close(h, 1);
  std::puts("  capacity exhaustion OK");
}

void test_churn_invariants() {
  // randomized churn: used-bytes accounting must track exactly, and all
  // live offsets must stay disjoint (the corruption class ASAN can't see
  // because the arena is one allocation)
  auto p = tmp_path();
  void* h = rtpu_store_create(p.c_str(), 4 << 20);
  std::vector<int> live;
  uint64_t expect_used = 0;
  unsigned seed = 12345;
  auto rnd = [&seed]() { return seed = seed * 1103515245 + 12345; };
  int next_id = 0;
  for (int step = 0; step < 5000; ++step) {
    if (live.empty() || rnd() % 3) {
      int id = next_id++;
      uint64_t sz = 64 + rnd() % 8000;
      Oid o(id);
      uint64_t off = 0;
      int rc = rtpu_store_put(h, o.b, sz, &off);
      if (rc == 0) {
        live.push_back(id);
        expect_used += (sz + kAlign - 1) / kAlign * kAlign;
      } else {
        assert(rc == -2);
      }
    } else {
      int idx = rnd() % live.size();
      int id = live[idx];
      Oid o(id);
      uint64_t off = 0, sz = 0;
      int sealed = 0;
      assert(rtpu_store_get(h, o.b, &off, &sz, &sealed) == 0);
      assert(rtpu_store_delete(h, o.b) == 0);
      expect_used -= (sz + kAlign - 1) / kAlign * kAlign;
      live[idx] = live.back();
      live.pop_back();
    }
    assert(rtpu_store_bytes_used(h) == expect_used);
  }
  // verify all live blocks are disjoint [offset, offset+size)
  std::vector<std::pair<uint64_t, uint64_t>> spans;
  for (int id : live) {
    Oid o(id);
    uint64_t off = 0, sz = 0;
    int sealed = 0;
    assert(rtpu_store_get(h, o.b, &off, &sz, &sealed) == 0);
    spans.emplace_back(off, off + ((sz + kAlign - 1) / kAlign * kAlign));
  }
  for (size_t i = 0; i < spans.size(); ++i)
    for (size_t j = i + 1; j < spans.size(); ++j) {
      bool disjoint = spans[i].second <= spans[j].first ||
                      spans[j].second <= spans[i].first;
      assert(disjoint);
    }
  rtpu_store_close(h, 1);
  std::puts("  churn invariants OK");
}

// Concurrent churn: N threads race put/seal/get/delete on overlapping id
// ranges.  The head's threads (driver puts, thin-client blob readers,
// reaper deletes) hit the C API concurrently with the GIL released, so
// the arena mutex must hold every invariant under contention.  Run under
// TSan (`make test-tsan`) this is the data-race proof; under ASan it
// checks no use-after-free in the index/free-list.
void test_concurrent_churn() {
  std::string path = "/tmp/rtpu_store_test_mt_" + std::to_string(::getpid());
  ::unlink(path.c_str());
  void* h = rtpu_store_create(path.c_str(), 8ull << 20);
  assert(h);
  constexpr int kThreads = 4;
  constexpr int kIters = 4000;
  std::atomic<uint64_t> puts_ok{0}, deletes_ok{0};
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; ++t) {
    ts.emplace_back([&, t]() {
      // overlapping id space: ids collide across threads on purpose
      for (int i = 0; i < kIters; ++i) {
        Oid o((i * 7 + t * 13) % 512);
        uint64_t off = 0;
        int rc = rtpu_store_put(h, o.b, 64 + (i % 1000), &off);
        if (rc == 0) {
          puts_ok.fetch_add(1, std::memory_order_relaxed);
          rtpu_store_seal(h, o.b);
        }
        uint64_t goff = 0, gsz = 0;
        int sealed = 0;
        (void)rtpu_store_get(h, o.b, &goff, &gsz, &sealed);
        if (i % 3 == t % 3) {
          if (rtpu_store_delete(h, o.b) == 0)
            deletes_ok.fetch_add(1, std::memory_order_relaxed);
        }
        (void)rtpu_store_bytes_used(h);
      }
    });
  }
  for (auto& th : ts) th.join();
  // post-conditions single-threaded: accounting consistent, all
  // remaining objects readable, spans disjoint
  uint64_t n = rtpu_store_num_objects(h);
  assert(puts_ok.load() >= n);
  uint64_t accounted = 0;
  std::vector<std::pair<uint64_t, uint64_t>> spans;
  for (int id = 0; id < 512; ++id) {
    Oid o(id);
    uint64_t off = 0, sz = 0;
    int sealed = 0;
    if (rtpu_store_get(h, o.b, &off, &sz, &sealed) == 0) {
      uint64_t alloc = (sz + kAlign - 1) / kAlign * kAlign;
      accounted += alloc ? alloc : kAlign;
      spans.emplace_back(off, off + (alloc ? alloc : kAlign));
    }
  }
  assert(accounted == rtpu_store_bytes_used(h));
  for (size_t i = 0; i < spans.size(); ++i)
    for (size_t j = i + 1; j < spans.size(); ++j)
      assert(spans[i].second <= spans[j].first ||
             spans[j].second <= spans[i].first);
  rtpu_store_close(h, 1);
  std::printf("  concurrent churn OK (%llu puts, %llu deletes, %llu live)\n",
              (unsigned long long)puts_ok.load(),
              (unsigned long long)deletes_ok.load(), (unsigned long long)n);
}

// Close racing readers: one thread calls close() while others spin on
// capacity/bytes_used/get/put.  Under TSan this proves the metric reads
// take the arena mutex (capacity is zeroed BY close under mu — an
// unlocked read would be a data race), and that a put blocked on mu
// during close fails instead of publishing into a closed arena.
void test_close_vs_capacity() {
  std::string path = "/tmp/rtpu_store_test_close_" + std::to_string(::getpid());
  ::unlink(path.c_str());
  void* h = rtpu_store_create(path.c_str(), 8ull << 20);
  assert(h);
  const uint64_t cap0 = rtpu_store_capacity(h);
  assert(cap0 == 8ull << 20);
  std::atomic<bool> closed{false};
  constexpr int kReaders = 3;
  std::vector<std::thread> ts;
  for (int t = 0; t < kReaders; ++t) {
    ts.emplace_back([&, t]() {
      for (int i = 0; i < 20000; ++i) {
        uint64_t cap = rtpu_store_capacity(h);
        // capacity is bimodal: the initial value before close, 0 after
        assert(cap == cap0 || cap == 0);
        (void)rtpu_store_bytes_used(h);
        Oid o(i % 64 + t * 64);
        uint64_t off = 0, sz = 0;
        int sealed = 0;
        (void)rtpu_store_get(h, o.b, &off, &sz, &sealed);
        if (closed.load(std::memory_order_acquire)) {
          // post-close: every put must be rejected (-2, arena full/closed)
          uint64_t poff = 0;
          assert(rtpu_store_put(h, o.b, 128, &poff) == -2);
        } else {
          uint64_t poff = 0;
          (void)rtpu_store_put(h, o.b, 128, &poff);
        }
      }
    });
  }
  std::thread closer([&]() {
    // let the readers get going, then slam the arena shut under them
    for (int i = 0; i < 1000; ++i) (void)rtpu_store_capacity(h);
    rtpu_store_close(h, 1);
    closed.store(true, std::memory_order_release);
  });
  for (auto& th : ts) th.join();
  closer.join();
  assert(rtpu_store_capacity(h) == 0);
  assert(rtpu_store_bytes_used(h) == 0);
  // close is idempotent
  rtpu_store_close(h, 1);
  std::puts("  close vs capacity OK");
}

// -- RefIndex ---------------------------------------------------------------

constexpr int32_t kHandle = 0, kTaskArg = 1, kContained = 2;

// Pack a contiguous oid array for the batch calls.
std::vector<uint8_t> pack_oids(const std::vector<int>& ids) {
  std::vector<uint8_t> out(ids.size() * 16);
  for (size_t i = 0; i < ids.size(); ++i) {
    Oid o(ids[i]);
    std::memcpy(out.data() + i * 16, o.b, 16);
  }
  return out;
}

void test_refs_lifecycle() {
  void* r = rtpu_refs_create();
  auto oids = pack_oids({1, 2, 3});
  rtpu_refs_ensure(r, oids.data(), 3, kHandle);
  // setdefault semantics: re-ensure must not reset counts
  rtpu_refs_add(r, oids.data(), 1, kTaskArg, 2);
  rtpu_refs_ensure(r, oids.data(), 3, kHandle);
  int64_t count = 0;
  int32_t sealed = 0, pins[8] = {0};
  assert(rtpu_refs_get(r, oids.data(), &count, &sealed, pins) == 0);
  assert(count == 3 && sealed == 0 && pins[kHandle] == 1 &&
         pins[kTaskArg] == 2);
  assert(rtpu_refs_size(r) == 3);
  assert(rtpu_refs_contains(r, oids.data()) == 1);

  // add on a missing oid is a no-op, never a resurrection
  auto ghost = pack_oids({99});
  rtpu_refs_add(r, ghost.data(), 1, kHandle, 5);
  assert(rtpu_refs_contains(r, ghost.data()) == 0);

  // remove to zero while UNSEALED: entry lingers (negative ok)
  std::vector<uint8_t> dead(3 * 16);
  auto two = pack_oids({2});
  assert(rtpu_refs_remove(r, two.data(), 1, kHandle, 2, dead.data()) == 0);
  assert(rtpu_refs_get(r, two.data(), &count, &sealed, pins) == 0);
  assert(count == -1 && pins[kHandle] == 0);  // pins clamp at 0
  // seal of the lingering entry reclaims it immediately (returns 1)
  assert(rtpu_refs_seal(r, two.data()) == 1);
  assert(rtpu_refs_contains(r, two.data()) == 0);

  // sealed entry dies atomically with the decrement that zeroed it
  auto one = pack_oids({1});
  assert(rtpu_refs_seal(r, one.data()) == 0);
  assert(rtpu_refs_remove(r, one.data(), 1, kTaskArg, 2, dead.data()) == 0);
  assert(rtpu_refs_remove(r, one.data(), 1, kHandle, 1, dead.data()) == 1);
  assert(std::memcmp(dead.data(), one.data(), 16) == 0);
  assert(rtpu_refs_contains(r, one.data()) == 0);
  // double-remove of the erased oid: no-op
  assert(rtpu_refs_remove(r, one.data(), 1, kHandle, 1, dead.data()) == 0);

  assert(rtpu_refs_erase(r, pack_oids({3}).data()) == 0);
  assert(rtpu_refs_size(r) == 0);
  std::puts("  refs lifecycle OK");
}

void test_refs_locations() {
  void* r = rtpu_refs_create();
  auto o = pack_oids({7});
  rtpu_refs_ensure(r, o.data(), 1, kHandle);
  assert(rtpu_refs_set_origin(r, o.data(), 0) == 0);
  assert(rtpu_refs_num_replicas(r, o.data()) == 0);
  int32_t out = -7;
  rtpu_refs_locate(r, o.data(), 1, -1, &out);
  assert(out == -1);  // no replicas: primary
  assert(rtpu_refs_add_replica(r, o.data(), 2) == 1);
  assert(rtpu_refs_add_replica(r, o.data(), 2) == 0);  // idempotent
  assert(rtpu_refs_add_replica(r, o.data(), 0) == 0);  // origin never a replica
  assert(rtpu_refs_add_replica(r, o.data(), 64) == -2);  // out of mask range
  assert(rtpu_refs_add_replica(r, o.data(), 5) == 1);
  assert(rtpu_refs_num_replicas(r, o.data()) == 2);

  // prefer-own-copy wins regardless of rr state
  rtpu_refs_locate(r, o.data(), 1, 5, &out);
  assert(out == 5);
  rtpu_refs_locate(r, o.data(), 1, 0, &out);
  assert(out == -1);  // consumer IS the origin
  // round-robin covers origin + both replicas over 3 calls
  bool saw_origin = false, saw2 = false, saw5 = false;
  for (int i = 0; i < 3; ++i) {
    rtpu_refs_locate(r, o.data(), 1, -1, &out);
    if (out == -1) saw_origin = true;
    if (out == 2) saw2 = true;
    if (out == 5) saw5 = true;
  }
  assert(saw_origin && saw2 && saw5);

  // node loss: slot drops from every mask; promotion pops the lowest
  rtpu_refs_drop_slot(r, 2);
  assert(rtpu_refs_num_replicas(r, o.data()) == 1);
  assert(rtpu_refs_pop_replica(r, o.data()) == 5);
  assert(rtpu_refs_pop_replica(r, o.data()) == -1);
  // unseal resets the location set for the lineage refill
  assert(rtpu_refs_add_replica(r, o.data(), 3) == 1);
  assert(rtpu_refs_unseal(r, o.data()) == 0);
  assert(rtpu_refs_num_replicas(r, o.data()) == 0);
  rtpu_refs_locate(r, pack_oids({42}).data(), 1, -1, &out);
  assert(out == -2);  // unknown oid
  std::puts("  refs locations OK");
}

// Concurrent refcount churn over the batch API: the head's reader
// threads add/remove borrows while seals and audits race — the exact
// GIL-released contention profile of a submission wave.  TSan run
// (`make test-tsan`) is the data-race proof for the batch refcount API.
void test_refs_concurrent_churn() {
  void* r = rtpu_refs_create();
  constexpr int kIds = 128;
  constexpr int kThreads = 4;
  constexpr int kIters = 4000;
  {
    std::vector<int> ids;
    for (int i = 0; i < kIds; ++i) ids.push_back(i);
    auto all = pack_oids(ids);
    rtpu_refs_ensure(r, all.data(), kIds, kHandle);
  }
  std::atomic<int64_t> deads{0};
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; ++t) {
    ts.emplace_back([&, t]() {
      std::vector<uint8_t> dead(8 * 16);
      for (int i = 0; i < kIters; ++i) {
        // small overlapping batches, mixed reasons
        std::vector<int> ids{(i * 3 + t) % kIds, (i * 5 + t * 7) % kIds,
                             (i + t * 11) % kIds};
        auto oids = pack_oids(ids);
        rtpu_refs_ensure(r, oids.data(), 3, kHandle);
        rtpu_refs_add(r, oids.data(), 3, kTaskArg, 1);
        if (i % 2 == 0) rtpu_refs_seal(r, oids.data());
        deads += rtpu_refs_remove(r, oids.data(), 3, kTaskArg, 1,
                                  dead.data());
        if (i % 7 == t) {
          deads += rtpu_refs_remove(r, oids.data(), 1, kHandle, 1,
                                    dead.data());
        }
        int64_t c = 0;
        int32_t s = 0, pins[8];
        (void)rtpu_refs_get(r, oids.data(), &c, &s, pins);
        (void)rtpu_refs_size(r);
        if (i % 63 == 0) {
          std::vector<int64_t> counts(3);
          std::vector<int32_t> batch_pins(3 * 8);
          rtpu_refs_get_batch(r, oids.data(), 3, counts.data(),
                              batch_pins.data());
        }
      }
    });
  }
  for (auto& th : ts) th.join();
  // single-threaded post-check: every surviving entry readable, pins
  // non-negative, and the task_arg pins all drained (adds == removes)
  uint64_t live = rtpu_refs_size(r);
  for (int i = 0; i < kIds; ++i) {
    Oid o(i);
    int64_t c = 0;
    int32_t s = 0, pins[8];
    if (rtpu_refs_get(r, o.b, &c, &s, pins) == 0) {
      for (int k = 0; k < 8; ++k) assert(pins[k] >= 0);
      assert(pins[kTaskArg] == 0);
    }
  }
  std::printf("  refs concurrent churn OK (%llu live, %lld reclaimed)\n",
              (unsigned long long)live, (long long)deads.load());
}

}  // namespace

int main() {
  test_create_put_get_seal_delete();
  test_free_list_reuse_and_coalescing();
  test_fragmentation_and_split();
  test_capacity_exhaustion();
  test_churn_invariants();
  test_concurrent_churn();
  test_close_vs_capacity();
  test_refs_lifecycle();
  test_refs_locations();
  test_refs_concurrent_churn();
  std::puts("store_core_test: ALL OK");
  return 0;
}
