// Native object-store core: arena allocator + object index.
//
// The plasma-store role of the reference (`src/ray/object_manager/plasma/`:
// one mmap'd shared-memory arena per node with dlmalloc inside,
// `plasma_allocator.h:41`, object index + lifecycle in
// `object_lifecycle_manager.h:101`), reduced to its essential core:
//
//  - one /dev/shm-backed arena file per session; objects are 64-byte
//    aligned [offset, size) slices of it.  Consumers mmap the arena once
//    and read slices zero-copy (the fd-passing/mmap model of plasma,
//    minus the unix-socket hop — the head hands out offsets instead).
//  - a first-fit free list with neighbor coalescing (the dlmalloc slot),
//    so freed object space is recycled: recycled pages skip the
//    fault-and-zero cost that made fresh per-object files ~2x slower.
//  - an oid -> {offset, size, sealed} index with create/seal/get/delete.
//
// Single-PROCESS writer: the head owns allocation/decommit; other
// processes only read (their locations arrive via the control plane), so
// no SHARED-memory locking is needed — the same split as plasma, where
// only the store process mutates the arena.  WITHIN the head, however,
// several threads hit this API concurrently (driver puts, thin-client
// blob reader threads, reaper deletes) and ctypes releases the GIL for
// the duration of each call — so the handle carries its own mutex; every
// exported call serializes on it (the role of plasma's store event loop).
// Uncontended cost is ~20ns against a multi-us allocation.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <map>
#include <vector>
#include <mutex>
#include <string>
#include <unordered_map>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kAlign = 64;
constexpr uint64_t kDataStart = 4096;  // page 0 reserved for a header/magic

inline uint64_t align_up(uint64_t n) { return (n + kAlign - 1) / kAlign * kAlign; }

struct Entry {
  uint64_t offset;
  uint64_t size;       // payload size
  uint64_t allocated;  // aligned block size
  bool sealed;
};

struct Arena {
  std::string path;
  int fd = -1;
  uint64_t capacity = 0;
  uint64_t bump = kDataStart;
  uint64_t used = 0;  // allocated bytes (aligned)
  // free blocks by offset -> size (coalescing needs ordered neighbors)
  std::map<uint64_t, uint64_t> free_blocks;
  std::unordered_map<std::string, Entry> index;
  std::mutex mu;  // serializes all API calls (see header comment)
};

// Every Arena ever created stays reachable here (closed ones included):
// close() cannot delete the struct — a GIL-released call can be blocked
// on its mutex — so this keeps the intentional leak reachable (and
// therefore invisible to LeakSanitizer, which is right: it IS reachable).
std::mutex g_arenas_mu;
std::vector<Arena*>& g_arenas() {
  // heap-allocated and never destroyed: a static vector's destructor
  // would run at exit BEFORE the leak checker, orphaning the arenas it
  // is keeping reachable
  static std::vector<Arena*>* v = new std::vector<Arena*>();
  return *v;
}

std::string oid_key(const uint8_t* oid) {
  return std::string(reinterpret_cast<const char*>(oid), 16);
}

// first-fit over the free list, else bump
int64_t arena_alloc(Arena* a, uint64_t need) {
  for (auto it = a->free_blocks.begin(); it != a->free_blocks.end(); ++it) {
    if (it->second >= need) {
      uint64_t off = it->first;
      uint64_t remain = it->second - need;
      a->free_blocks.erase(it);
      if (remain >= kAlign) a->free_blocks.emplace(off + need, remain);
      a->used += need;
      return static_cast<int64_t>(off);
    }
  }
  if (a->bump + need > a->capacity) return -1;
  uint64_t off = a->bump;
  a->bump += need;
  a->used += need;
  return static_cast<int64_t>(off);
}

void arena_release(Arena* a, uint64_t off, uint64_t alloc_size) {
  a->used -= alloc_size;
  auto next = a->free_blocks.lower_bound(off);
  // coalesce with the following block
  if (next != a->free_blocks.end() && off + alloc_size == next->first) {
    alloc_size += next->second;
    next = a->free_blocks.erase(next);
  }
  // coalesce with the preceding block
  if (next != a->free_blocks.begin()) {
    auto prev = std::prev(next);
    if (prev->first + prev->second == off) {
      prev->second += alloc_size;
      // merged block now adjacent to the bump frontier? retreat the bump
      if (prev->first + prev->second == a->bump) {
        a->bump = prev->first;
        a->free_blocks.erase(prev);
      }
      return;
    }
  }
  if (off + alloc_size == a->bump) {
    a->bump = off;  // retreat instead of listing
    return;
  }
  a->free_blocks.emplace(off, alloc_size);
}

}  // namespace

extern "C" {

// Create the arena file (O_EXCL) sized to `capacity`; returns NULL on error.
void* rtpu_store_create(const char* path, uint64_t capacity) {
  int fd = ::open(path, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (::ftruncate(fd, static_cast<off_t>(capacity)) != 0) {
    ::close(fd);
    ::unlink(path);
    return nullptr;
  }
  auto* a = new Arena();
  a->path = path;
  a->fd = fd;
  a->capacity = capacity;
  // magic header so sweepers can identify arena files
  static const char kMagic[] = "RTPUARENA1";
  (void)!::pwrite(fd, kMagic, sizeof(kMagic), 0);
  {
    std::lock_guard<std::mutex> g(g_arenas_mu);
    g_arenas().push_back(a);
  }
  return a;
}

// Allocate + index an unsealed object. Returns 0 and writes *offset_out,
// -1 if oid exists, -2 if the arena is full.
int rtpu_store_put(void* h, const uint8_t* oid, uint64_t size,
                   uint64_t* offset_out) {
  auto* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->mu);
  auto key = oid_key(oid);
  if (a->index.count(key)) return -1;
  uint64_t need = align_up(size ? size : 1);
  int64_t off = arena_alloc(a, need);
  if (off < 0) return -2;
  a->index.emplace(key, Entry{static_cast<uint64_t>(off), size, need, false});
  *offset_out = static_cast<uint64_t>(off);
  return 0;
}

int rtpu_store_seal(void* h, const uint8_t* oid) {
  auto* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->mu);
  auto it = a->index.find(oid_key(oid));
  if (it == a->index.end()) return -1;
  it->second.sealed = true;
  return 0;
}

// Look up an object: writes offset/size/sealed. Returns 0, or -1 if absent.
int rtpu_store_get(void* h, const uint8_t* oid, uint64_t* offset_out,
                   uint64_t* size_out, int* sealed_out) {
  auto* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->mu);
  auto it = a->index.find(oid_key(oid));
  if (it == a->index.end()) return -1;
  *offset_out = it->second.offset;
  *size_out = it->second.size;
  *sealed_out = it->second.sealed ? 1 : 0;
  return 0;
}

// Delete + reclaim. Returns 0, or -1 if absent.
int rtpu_store_delete(void* h, const uint8_t* oid) {
  auto* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->mu);
  auto it = a->index.find(oid_key(oid));
  if (it == a->index.end()) return -1;
  uint64_t off = it->second.offset, alloc = it->second.allocated;
  a->index.erase(it);
  arena_release(a, off, alloc);
  // Pages stay resident (high-water-mark memory, like plasma's arena):
  // recycling faulted-in pages is what makes repeated puts run at memcpy
  // speed instead of the kernel's fault-and-zero path.  The arena is
  // bounded by its capacity, so residency is the store's memory budget.
  return 0;
}

uint64_t rtpu_store_bytes_used(void* h) {
  auto* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->mu);
  return a->used;
}

uint64_t rtpu_store_capacity(void* h) {
  auto* a = static_cast<Arena*>(h);
  // close() zeroes capacity under mu; an unlocked read here would race it
  std::lock_guard<std::mutex> g(a->mu);
  return a->capacity;
}

uint64_t rtpu_store_num_objects(void* h) {
  auto* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->mu);
  return a->index.size();
}

uint64_t rtpu_store_num_free_blocks(void* h) {
  auto* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->mu);
  return a->free_blocks.size();
}

void rtpu_store_close(void* h, int unlink_file) {
  auto* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->mu);
  if (a->fd >= 0) {
    ::close(a->fd);
    a->fd = -1;  // idempotent: a second close is a no-op
  }
  if (unlink_file && !a->path.empty()) {
    ::unlink(a->path.c_str());
    a->path.clear();
  }
  a->index.clear();
  a->free_blocks.clear();
  // a put that was blocked on mu during this close must FAIL (-2), not
  // publish an object into a closed/unlinked arena: zero the capacity
  // so arena_alloc's bump check rejects everything from now on
  a->capacity = 0;
  a->bump = kDataStart;
  a->used = 0;
  // The Arena struct itself is intentionally NOT deleted: a reaper or
  // blob-reader thread can be blocked on mu right now (ctypes releases
  // the GIL, so shutdown can race an in-flight call), and destroying a
  // held/contended mutex is UB.  One small struct leaks per session at
  // process exit — the price of making every call safe against close.
}

}  // extern "C"
