// Native object-store core: arena allocator + object index.
//
// The plasma-store role of the reference (`src/ray/object_manager/plasma/`:
// one mmap'd shared-memory arena per node with dlmalloc inside,
// `plasma_allocator.h:41`, object index + lifecycle in
// `object_lifecycle_manager.h:101`), reduced to its essential core:
//
//  - one /dev/shm-backed arena file per session; objects are 64-byte
//    aligned [offset, size) slices of it.  Consumers mmap the arena once
//    and read slices zero-copy (the fd-passing/mmap model of plasma,
//    minus the unix-socket hop — the head hands out offsets instead).
//  - a first-fit free list with neighbor coalescing (the dlmalloc slot),
//    so freed object space is recycled: recycled pages skip the
//    fault-and-zero cost that made fresh per-object files ~2x slower.
//  - an oid -> {offset, size, sealed} index with create/seal/get/delete.
//
// Single-PROCESS writer: the head owns allocation/decommit; other
// processes only read (their locations arrive via the control plane), so
// no SHARED-memory locking is needed — the same split as plasma, where
// only the store process mutates the arena.  WITHIN the head, however,
// several threads hit this API concurrently (driver puts, thin-client
// blob reader threads, reaper deletes) and ctypes releases the GIL for
// the duration of each call — so the handle carries its own mutex; every
// exported call serializes on it (the role of plasma's store event loop).
// Uncontended cost is ~20ns against a multi-us allocation.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <map>
#include <vector>
#include <mutex>
#include <string>
#include <unordered_map>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kAlign = 64;
constexpr uint64_t kDataStart = 4096;  // page 0 reserved for a header/magic

inline uint64_t align_up(uint64_t n) { return (n + kAlign - 1) / kAlign * kAlign; }

struct Entry {
  uint64_t offset;
  uint64_t size;       // payload size
  uint64_t allocated;  // aligned block size
  bool sealed;
};

struct Arena {
  std::string path;
  int fd = -1;
  uint64_t capacity = 0;
  uint64_t bump = kDataStart;
  uint64_t used = 0;  // allocated bytes (aligned)
  // free blocks by offset -> size (coalescing needs ordered neighbors)
  std::map<uint64_t, uint64_t> free_blocks;
  std::unordered_map<std::string, Entry> index;
  std::mutex mu;  // serializes all API calls (see header comment)
};

// Every Arena ever created stays reachable here (closed ones included):
// close() cannot delete the struct — a GIL-released call can be blocked
// on its mutex — so this keeps the intentional leak reachable (and
// therefore invisible to LeakSanitizer, which is right: it IS reachable).
std::mutex g_arenas_mu;
std::vector<Arena*>& g_arenas() {
  // heap-allocated and never destroyed: a static vector's destructor
  // would run at exit BEFORE the leak checker, orphaning the arenas it
  // is keeping reachable
  static std::vector<Arena*>* v = new std::vector<Arena*>();
  return *v;
}

std::string oid_key(const uint8_t* oid) {
  return std::string(reinterpret_cast<const char*>(oid), 16);
}

// first-fit over the free list, else bump
int64_t arena_alloc(Arena* a, uint64_t need) {
  for (auto it = a->free_blocks.begin(); it != a->free_blocks.end(); ++it) {
    if (it->second >= need) {
      uint64_t off = it->first;
      uint64_t remain = it->second - need;
      a->free_blocks.erase(it);
      if (remain >= kAlign) a->free_blocks.emplace(off + need, remain);
      a->used += need;
      return static_cast<int64_t>(off);
    }
  }
  if (a->bump + need > a->capacity) return -1;
  uint64_t off = a->bump;
  a->bump += need;
  a->used += need;
  return static_cast<int64_t>(off);
}

void arena_release(Arena* a, uint64_t off, uint64_t alloc_size) {
  a->used -= alloc_size;
  auto next = a->free_blocks.lower_bound(off);
  // coalesce with the following block
  if (next != a->free_blocks.end() && off + alloc_size == next->first) {
    alloc_size += next->second;
    next = a->free_blocks.erase(next);
  }
  // coalesce with the preceding block
  if (next != a->free_blocks.begin()) {
    auto prev = std::prev(next);
    if (prev->first + prev->second == off) {
      prev->second += alloc_size;
      // merged block now adjacent to the bump frontier? retreat the bump
      if (prev->first + prev->second == a->bump) {
        a->bump = prev->first;
        a->free_blocks.erase(prev);
      }
      return;
    }
  }
  if (off + alloc_size == a->bump) {
    a->bump = off;  // retreat instead of listing
    return;
  }
  a->free_blocks.emplace(off, alloc_size);
}

}  // namespace

extern "C" {

// Create the arena file (O_EXCL) sized to `capacity`; returns NULL on error.
void* rtpu_store_create(const char* path, uint64_t capacity) {
  int fd = ::open(path, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (::ftruncate(fd, static_cast<off_t>(capacity)) != 0) {
    ::close(fd);
    ::unlink(path);
    return nullptr;
  }
  auto* a = new Arena();
  a->path = path;
  a->fd = fd;
  a->capacity = capacity;
  // magic header so sweepers can identify arena files
  static const char kMagic[] = "RTPUARENA1";
  (void)!::pwrite(fd, kMagic, sizeof(kMagic), 0);
  {
    std::lock_guard<std::mutex> g(g_arenas_mu);
    g_arenas().push_back(a);
  }
  return a;
}

// Allocate + index an unsealed object. Returns 0 and writes *offset_out,
// -1 if oid exists, -2 if the arena is full.
int rtpu_store_put(void* h, const uint8_t* oid, uint64_t size,
                   uint64_t* offset_out) {
  auto* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->mu);
  auto key = oid_key(oid);
  if (a->index.count(key)) return -1;
  uint64_t need = align_up(size ? size : 1);
  int64_t off = arena_alloc(a, need);
  if (off < 0) return -2;
  a->index.emplace(key, Entry{static_cast<uint64_t>(off), size, need, false});
  *offset_out = static_cast<uint64_t>(off);
  return 0;
}

int rtpu_store_seal(void* h, const uint8_t* oid) {
  auto* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->mu);
  auto it = a->index.find(oid_key(oid));
  if (it == a->index.end()) return -1;
  it->second.sealed = true;
  return 0;
}

// Look up an object: writes offset/size/sealed. Returns 0, or -1 if absent.
int rtpu_store_get(void* h, const uint8_t* oid, uint64_t* offset_out,
                   uint64_t* size_out, int* sealed_out) {
  auto* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->mu);
  auto it = a->index.find(oid_key(oid));
  if (it == a->index.end()) return -1;
  *offset_out = it->second.offset;
  *size_out = it->second.size;
  *sealed_out = it->second.sealed ? 1 : 0;
  return 0;
}

// Delete + reclaim. Returns 0, or -1 if absent.
int rtpu_store_delete(void* h, const uint8_t* oid) {
  auto* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->mu);
  auto it = a->index.find(oid_key(oid));
  if (it == a->index.end()) return -1;
  uint64_t off = it->second.offset, alloc = it->second.allocated;
  a->index.erase(it);
  arena_release(a, off, alloc);
  // Pages stay resident (high-water-mark memory, like plasma's arena):
  // recycling faulted-in pages is what makes repeated puts run at memcpy
  // speed instead of the kernel's fault-and-zero path.  The arena is
  // bounded by its capacity, so residency is the store's memory budget.
  return 0;
}

uint64_t rtpu_store_bytes_used(void* h) {
  auto* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->mu);
  return a->used;
}

uint64_t rtpu_store_capacity(void* h) {
  auto* a = static_cast<Arena*>(h);
  // close() zeroes capacity under mu; an unlocked read here would race it
  std::lock_guard<std::mutex> g(a->mu);
  return a->capacity;
}

uint64_t rtpu_store_num_objects(void* h) {
  auto* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->mu);
  return a->index.size();
}

uint64_t rtpu_store_num_free_blocks(void* h) {
  auto* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->mu);
  return a->free_blocks.size();
}

void rtpu_store_close(void* h, int unlink_file) {
  auto* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->mu);
  if (a->fd >= 0) {
    ::close(a->fd);
    a->fd = -1;  // idempotent: a second close is a no-op
  }
  if (unlink_file && !a->path.empty()) {
    ::unlink(a->path.c_str());
    a->path.clear();
  }
  a->index.clear();
  a->free_blocks.clear();
  // a put that was blocked on mu during this close must FAIL (-2), not
  // publish an object into a closed/unlinked arena: zero the capacity
  // so arena_alloc's bump check rejects everything from now on
  a->capacity = 0;
  a->bump = kDataStart;
  a->used = 0;
  // The Arena struct itself is intentionally NOT deleted: a reaper or
  // blob-reader thread can be blocked on mu right now (ctypes releases
  // the GIL, so shutdown can race an in-flight call), and destroying a
  // held/contended mutex is UB.  One small struct leaks per session at
  // process exit — the price of making every call safe against close.
}

}  // extern "C"

// ---------------------------------------------------------------------------
// RefIndex: the head registry's hot maps, pushed down from Python.
//
// The reference keeps reference counts and object locations in C++
// (`src/ray/core_worker/reference_count.h`, ownership-based object
// directory) precisely because they are touched per task arg; our head
// did both in a Python dict under a Python lock, which serialized every
// submission wave.  This index absorbs exactly the per-oid hot state:
//
//   - ref_count          lifetime source of truth (may go negative while
//                        the producer hasn't sealed yet — same contract
//                        as the Python _Entry)
//   - pins[8]            advisory per-reason counts (handle/task_arg/
//                        contained/lineage/...; Python owns the
//                        reason-name <-> slot mapping)
//   - sealed             the delete-at-zero gate: entries are erased when
//                        count <= 0 AND sealed, atomically with the
//                        decrement that got them there
//   - origin slot +      location SET as small-int node slots (Python
//     replica mask + rr   owns slot <-> node_id/addr); `locate` picks the
//                        pull source per oid (prefer-own-node, else
//                        round-robin over origin+replicas)
//
// All calls take packed arrays of 16-byte oids and run with the GIL
// released (ctypes); one mutex serializes the index — the win over the
// Python path is batch granularity (one lock hop per MESSAGE instead of
// per oid) plus true GIL-free execution, not lock-free cleverness.
// Cold metadata (payload location, owner attribution, sealed Events,
// containment lists) stays in Python, keyed by the same oid, so the
// ownership/memory audits read identical rows.

namespace {

constexpr int kNumReasons = 8;
constexpr int kMaxSlots = 64;  // replica node slots per object (bitmask)
constexpr int kOidLen = 16;

struct RefEntry {
  int64_t count = 1;
  int32_t pins[kNumReasons] = {0};
  uint64_t replicas = 0;  // bit i = node slot i holds a pulled copy
  int16_t origin_slot = -1;
  uint16_t rr = 0;
  bool sealed = false;
};

struct RefIndex {
  std::unordered_map<std::string, RefEntry> map;
  std::mutex mu;
};

// same keep-reachable discipline as the arenas: a GIL-released call can
// be parked on `mu` while Python shuts down, so destroy() never frees
std::mutex g_refs_mu;
std::vector<RefIndex*>& g_refs() {
  static std::vector<RefIndex*>* v = new std::vector<RefIndex*>();
  return *v;
}

inline std::string ref_key(const uint8_t* oids, int64_t i) {
  return std::string(reinterpret_cast<const char*>(oids) + i * kOidLen,
                     kOidLen);
}

}  // namespace

extern "C" {

void* rtpu_refs_create() {
  auto* r = new RefIndex();
  {
    std::lock_guard<std::mutex> g(g_refs_mu);
    g_refs().push_back(r);
  }
  return r;
}

// Create entries for any missing oid with the creator's initial handle
// pin (count=1, pins[reason]=1 — Python passes the "handle" slot).
// Existing entries are untouched (setdefault semantics).
void rtpu_refs_ensure(void* h, const uint8_t* oids, int64_t n,
                      int32_t reason) {
  auto* r = static_cast<RefIndex*>(h);
  if (reason < 0 || reason >= kNumReasons) reason = kNumReasons - 1;
  std::lock_guard<std::mutex> g(r->mu);
  for (int64_t i = 0; i < n; ++i) {
    auto res = r->map.emplace(ref_key(oids, i), RefEntry{});
    if (res.second) res.first->second.pins[reason] = 1;
  }
}

int rtpu_refs_contains(void* h, const uint8_t* oid) {
  auto* r = static_cast<RefIndex*>(h);
  std::lock_guard<std::mutex> g(r->mu);
  return r->map.count(std::string(reinterpret_cast<const char*>(oid),
                                  kOidLen))
             ? 1
             : 0;
}

// Batch increment; missing oids are a no-op (a ref to a deleted object
// is the caller's stale handle, same as the Python path).
void rtpu_refs_add(void* h, const uint8_t* oids, int64_t n, int32_t reason,
                   int64_t delta) {
  auto* r = static_cast<RefIndex*>(h);
  if (reason < 0 || reason >= kNumReasons) reason = kNumReasons - 1;
  std::lock_guard<std::mutex> g(r->mu);
  for (int64_t i = 0; i < n; ++i) {
    auto it = r->map.find(ref_key(oids, i));
    if (it == r->map.end()) continue;
    it->second.count += delta;
    it->second.pins[reason] += static_cast<int32_t>(delta);
  }
}

// Batch decrement.  An entry whose count drops to <= 0 while sealed is
// erased HERE, atomically with the decrement (a concurrent add can then
// never resurrect it — add on a missing key is a no-op), and its oid is
// appended to dead_out (capacity n * 16 bytes).  Returns the dead count;
// Python reaps payload/metadata for exactly those oids.
int64_t rtpu_refs_remove(void* h, const uint8_t* oids, int64_t n,
                         int32_t reason, int64_t delta, uint8_t* dead_out) {
  auto* r = static_cast<RefIndex*>(h);
  if (reason < 0 || reason >= kNumReasons) reason = kNumReasons - 1;
  int64_t dead = 0;
  std::lock_guard<std::mutex> g(r->mu);
  for (int64_t i = 0; i < n; ++i) {
    auto key = ref_key(oids, i);
    auto it = r->map.find(key);
    if (it == r->map.end()) continue;
    RefEntry& e = it->second;
    e.count -= delta;
    int32_t left = e.pins[reason] - static_cast<int32_t>(delta);
    e.pins[reason] = left > 0 ? left : 0;
    if (e.count <= 0 && e.sealed) {
      std::memcpy(dead_out + dead * kOidLen, key.data(), kOidLen);
      ++dead;
      r->map.erase(it);
    }
  }
  return dead;
}

// Mark sealed.  Returns 1 when the entry died at seal time (every handle
// dropped before the producer finished — fire-and-forget reclaim: the
// entry is erased and the caller discards the payload), 0 on a live
// seal, -1 when the entry is missing (concurrent deletion won).
int rtpu_refs_seal(void* h, const uint8_t* oid) {
  auto* r = static_cast<RefIndex*>(h);
  std::lock_guard<std::mutex> g(r->mu);
  auto it = r->map.find(
      std::string(reinterpret_cast<const char*>(oid), kOidLen));
  if (it == r->map.end()) return -1;
  it->second.sealed = true;
  if (it->second.count <= 0) {
    r->map.erase(it);
    return 1;
  }
  return 0;
}

// Node-loss un-seal: the only copy died, lineage will refill the slot.
// The entry survives at its current count; replicas were already dropped
// via rtpu_refs_drop_slot.
int rtpu_refs_unseal(void* h, const uint8_t* oid) {
  auto* r = static_cast<RefIndex*>(h);
  std::lock_guard<std::mutex> g(r->mu);
  auto it = r->map.find(
      std::string(reinterpret_cast<const char*>(oid), kOidLen));
  if (it == r->map.end()) return -1;
  it->second.sealed = false;
  it->second.origin_slot = -1;
  it->second.replicas = 0;
  return 0;
}

int rtpu_refs_erase(void* h, const uint8_t* oid) {
  auto* r = static_cast<RefIndex*>(h);
  std::lock_guard<std::mutex> g(r->mu);
  return r->map.erase(
             std::string(reinterpret_cast<const char*>(oid), kOidLen))
             ? 0
             : -1;
}

// Snapshot one entry (audit path): count, sealed, all pin slots.
int rtpu_refs_get(void* h, const uint8_t* oid, int64_t* count_out,
                  int32_t* sealed_out, int32_t* pins_out /* [8] */) {
  auto* r = static_cast<RefIndex*>(h);
  std::lock_guard<std::mutex> g(r->mu);
  auto it = r->map.find(
      std::string(reinterpret_cast<const char*>(oid), kOidLen));
  if (it == r->map.end()) return -1;
  *count_out = it->second.count;
  *sealed_out = it->second.sealed ? 1 : 0;
  std::memcpy(pins_out, it->second.pins, sizeof(it->second.pins));
  return 0;
}

// Batch snapshot for the memory audit: one mutex hop for the whole table
// page instead of one per row.  Missing oids get count = INT64_MIN.
void rtpu_refs_get_batch(void* h, const uint8_t* oids, int64_t n,
                         int64_t* counts, int32_t* pins /* n*8 */) {
  auto* r = static_cast<RefIndex*>(h);
  std::lock_guard<std::mutex> g(r->mu);
  for (int64_t i = 0; i < n; ++i) {
    auto it = r->map.find(ref_key(oids, i));
    if (it == r->map.end()) {
      counts[i] = INT64_MIN;
      continue;
    }
    counts[i] = it->second.count;
    std::memcpy(pins + i * kNumReasons, it->second.pins,
                sizeof(it->second.pins));
  }
}

uint64_t rtpu_refs_size(void* h) {
  auto* r = static_cast<RefIndex*>(h);
  std::lock_guard<std::mutex> g(r->mu);
  return r->map.size();
}

// -- location sets ---------------------------------------------------------

int rtpu_refs_set_origin(void* h, const uint8_t* oid, int32_t slot) {
  auto* r = static_cast<RefIndex*>(h);
  if (slot < -1 || slot >= kMaxSlots) return -2;
  std::lock_guard<std::mutex> g(r->mu);
  auto it = r->map.find(
      std::string(reinterpret_cast<const char*>(oid), kOidLen));
  if (it == r->map.end()) return -1;
  it->second.origin_slot = static_cast<int16_t>(slot);
  return 0;
}

// Record a pulled copy.  1 = added, 0 = already present / is the origin,
// -1 = missing entry, -2 = slot out of mask range (callers just skip:
// the location set is a pull-spreading optimization, not correctness).
int rtpu_refs_add_replica(void* h, const uint8_t* oid, int32_t slot) {
  auto* r = static_cast<RefIndex*>(h);
  if (slot < 0 || slot >= kMaxSlots) return -2;
  std::lock_guard<std::mutex> g(r->mu);
  auto it = r->map.find(
      std::string(reinterpret_cast<const char*>(oid), kOidLen));
  if (it == r->map.end()) return -1;
  RefEntry& e = it->second;
  if (slot == e.origin_slot) return 0;
  uint64_t bit = 1ULL << slot;
  if (e.replicas & bit) return 0;
  e.replicas |= bit;
  return 1;
}

// Remove and return the lowest replica slot (node-loss promotion picks a
// survivor); -1 when the entry has no replicas or is missing.
int rtpu_refs_pop_replica(void* h, const uint8_t* oid) {
  auto* r = static_cast<RefIndex*>(h);
  std::lock_guard<std::mutex> g(r->mu);
  auto it = r->map.find(
      std::string(reinterpret_cast<const char*>(oid), kOidLen));
  if (it == r->map.end() || it->second.replicas == 0) return -1;
  int slot = __builtin_ctzll(it->second.replicas);
  it->second.replicas &= it->second.replicas - 1;
  return slot;
}

// The raw replica slot mask (0 for missing entries) — Python decodes the
// bits back to node ids for `replica_nodes`/broadcast planning.
uint64_t rtpu_refs_replica_mask(void* h, const uint8_t* oid) {
  auto* r = static_cast<RefIndex*>(h);
  std::lock_guard<std::mutex> g(r->mu);
  auto it = r->map.find(
      std::string(reinterpret_cast<const char*>(oid), kOidLen));
  return it == r->map.end() ? 0 : it->second.replicas;
}

// Spill path: the shm segment is leaving; every pulled copy of it gets
// unlinked, so the location set empties without touching sealed state.
int rtpu_refs_clear_replicas(void* h, const uint8_t* oid) {
  auto* r = static_cast<RefIndex*>(h);
  std::lock_guard<std::mutex> g(r->mu);
  auto it = r->map.find(
      std::string(reinterpret_cast<const char*>(oid), kOidLen));
  if (it == r->map.end()) return -1;
  it->second.replicas = 0;
  return 0;
}

int rtpu_refs_num_replicas(void* h, const uint8_t* oid) {
  auto* r = static_cast<RefIndex*>(h);
  std::lock_guard<std::mutex> g(r->mu);
  auto it = r->map.find(
      std::string(reinterpret_cast<const char*>(oid), kOidLen));
  if (it == r->map.end()) return -1;
  return __builtin_popcountll(it->second.replicas);
}

// A node died: clear its slot bit from every location set (cold path —
// full scan, like the Python mark_node_lost scan it replaces).
void rtpu_refs_drop_slot(void* h, int32_t slot) {
  auto* r = static_cast<RefIndex*>(h);
  if (slot < 0 || slot >= kMaxSlots) return;
  uint64_t mask = ~(1ULL << slot);
  std::lock_guard<std::mutex> g(r->mu);
  for (auto& kv : r->map) kv.second.replicas &= mask;
}

// Pick the pull source for each oid (one call per dep set — the `locate`
// batch API).  out[i]: -2 unknown entry, -1 use the primary location,
// otherwise the chosen replica slot.  prefer_slot is the consumer's own
// node (its copy wins: zero-copy attach); with no preference match the
// choice round-robins over {origin} + replicas in ascending-slot order.
void rtpu_refs_locate(void* h, const uint8_t* oids, int64_t n,
                      int32_t prefer_slot, int32_t* out) {
  auto* r = static_cast<RefIndex*>(h);
  std::lock_guard<std::mutex> g(r->mu);
  for (int64_t i = 0; i < n; ++i) {
    auto it = r->map.find(ref_key(oids, i));
    if (it == r->map.end()) {
      out[i] = -2;
      continue;
    }
    RefEntry& e = it->second;
    if (e.replicas == 0) {
      out[i] = -1;
      continue;
    }
    if (prefer_slot >= 0) {
      if (prefer_slot == e.origin_slot) {
        out[i] = -1;
        continue;
      }
      if (prefer_slot < kMaxSlots && (e.replicas & (1ULL << prefer_slot))) {
        out[i] = prefer_slot;
        continue;
      }
    }
    int n_rep = __builtin_popcountll(e.replicas);
    int idx = e.rr % (1 + n_rep);
    ++e.rr;
    if (idx == 0) {
      out[i] = -1;  // the origin's turn
      continue;
    }
    uint64_t m = e.replicas;
    for (int k = 1; k < idx; ++k) m &= m - 1;  // drop idx-1 lowest bits
    out[i] = __builtin_ctzll(m);
  }
}

void rtpu_refs_clear(void* h) {
  auto* r = static_cast<RefIndex*>(h);
  std::lock_guard<std::mutex> g(r->mu);
  r->map.clear();
}

}  // extern "C"
