"""Multi-node cluster harness for tests.

Analog of the reference's single most load-bearing test asset
(``python/ray/cluster_utils.py:99`` ``Cluster``, ``add_node`` at ``:165``):

- default mode: multiple raylet node-states with distinct ids/resources
  inside one head process, so scheduling spread, placement-group
  strategies, node affinity and node-death behavior are testable on one
  machine (SURVEY §4.2);
- ``real_processes=True``: each added node is a real
  :mod:`ray_tpu._private.node_agent` subprocess joining over TCP with its
  own worker pool and a private shm namespace — objects move between
  nodes only through the object-transfer plane (the reference's
  multi-raylet-per-host test topology).
"""

from __future__ import annotations

import itertools
import os
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

import ray_tpu
from ray_tpu._private.worker import global_worker


class Cluster:
    def __init__(
        self,
        initialize_head: bool = True,
        head_node_args: Optional[dict] = None,
        real_processes: bool = False,
    ):
        self._node_counter = itertools.count(1)
        self.node_ids: List[str] = []
        self.real_processes = real_processes
        self.agents: Dict[str, subprocess.Popen] = {}
        self._agent_dirs: List[str] = []
        if initialize_head:
            args = dict(head_node_args or {})
            ray_tpu.init(**args)
            self.node_ids.append(global_worker.node._head_node_id)

    def add_node(
        self,
        num_cpus: int = 1,
        num_tpus: int = 0,
        resources: Optional[Dict[str, float]] = None,
        env: Optional[Dict[str, str]] = None,
        wait: bool = True,
        slice_id: Optional[str] = None,
    ) -> str:
        node = global_worker.node
        node_id = f"node-{next(self._node_counter)}"
        if not self.real_processes:
            total = dict(resources or {})
            total["CPU"] = float(num_cpus)
            total["TPU"] = float(num_tpus)
            node.add_node_state(node_id, total, tpu_ids=list(range(num_tpus)),
                                env=env, slice_id=slice_id)
            self.node_ids.append(node_id)
            return node_id

        # real node: spawn an agent process that registers over TCP with a
        # private shm directory (honest cross-node object transfer even on
        # one test host)
        shm_sub = tempfile.mkdtemp(prefix=f"rtpu-{node_id}-", dir="/dev/shm")
        self._agent_dirs.append(shm_sub)
        host, port = node.tcp_address
        agent_env = dict(os.environ)
        agent_env.update(env or {})
        agent_env["RAY_TPU_AUTHKEY"] = node.authkey.hex()
        cmd = [
            sys.executable, "-m", "ray_tpu._private.node_agent",
            "--address", f"{host}:{port}",
            "--node-id", node_id,
            "--num-cpus", str(num_cpus),
            "--num-tpus", str(num_tpus),
            "--shm-dir", shm_sub,
        ]
        if slice_id:
            cmd += ["--slice-id", slice_id]
        if resources:
            import json

            cmd += ["--resources", json.dumps(resources)]
        proc = subprocess.Popen(cmd, env=agent_env)
        self.agents[node_id] = proc
        if wait:
            deadline = time.time() + 30
            while time.time() < deadline:
                with node.lock:
                    if node_id in node.nodes and node.nodes[node_id].alive:
                        break
                time.sleep(0.05)
            else:
                raise TimeoutError(f"node agent {node_id} did not register")
        self.node_ids.append(node_id)
        return node_id

    def remove_node(self, node_id: str) -> None:
        proc = self.agents.pop(node_id, None)
        if proc is not None:
            proc.kill()  # head notices the dropped agent connection
            deadline = time.time() + 15
            node = global_worker.node
            while time.time() < deadline:
                with node.lock:
                    ns = node.nodes.get(node_id)
                    if ns is None or not ns.alive:
                        return
                time.sleep(0.05)
            return
        global_worker.node.remove_node_state(node_id)

    def shutdown(self) -> None:
        ray_tpu.shutdown()
        for proc in self.agents.values():
            try:
                proc.kill()
            except Exception:
                pass
        self.agents.clear()
        import shutil

        for d in self._agent_dirs:
            shutil.rmtree(d, ignore_errors=True)
