"""Fake multi-node cluster for tests.

Analog of the reference's single most load-bearing test asset
(``python/ray/cluster_utils.py:99`` ``Cluster``, ``add_node`` at ``:165``):
multiple raylet node-states with distinct ids/resources inside one head
process, so scheduling spread, placement-group strategies, node affinity and
node-death behavior are testable on one machine (SURVEY §4.2).
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional

import ray_tpu
from ray_tpu._private.worker import global_worker


class Cluster:
    def __init__(self, initialize_head: bool = True, head_node_args: Optional[dict] = None):
        self._node_counter = itertools.count(1)
        self.node_ids: List[str] = []
        if initialize_head:
            args = dict(head_node_args or {})
            ray_tpu.init(**args)
            self.node_ids.append(global_worker.node._head_node_id)

    def add_node(
        self,
        num_cpus: int = 1,
        num_tpus: int = 0,
        resources: Optional[Dict[str, float]] = None,
        env: Optional[Dict[str, str]] = None,
    ) -> str:
        node = global_worker.node
        node_id = f"node-{next(self._node_counter)}"
        total = dict(resources or {})
        total["CPU"] = float(num_cpus)
        total["TPU"] = float(num_tpus)
        node.add_node_state(node_id, total, tpu_ids=list(range(num_tpus)), env=env)
        self.node_ids.append(node_id)
        return node_id

    def remove_node(self, node_id: str) -> None:
        global_worker.node.remove_node_state(node_id)

    def shutdown(self) -> None:
        ray_tpu.shutdown()
