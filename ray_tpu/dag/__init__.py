"""Lazy task/actor DAGs — the ``ray.dag`` analog.

Reference: ``python/ray/dag/`` (``dag_node.py``, ``function_node.py``,
``class_node.py``, ``input_node.py``) — the substrate of Serve deployment
graphs.  ``fn.bind(...)`` builds nodes instead of submitting; ``execute``
walks the graph, submits every task once, and returns the root's ref.
"""

from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    ClassNode,
    DAGNode,
    FunctionNode,
    InputNode,
)

__all__ = [
    "DAGNode", "FunctionNode", "ClassNode", "ClassMethodNode", "InputNode",
    "CompiledDAG", "CompiledDAGRef", "CompiledGraphError",
]


def __getattr__(name):
    # compiled-graph types load lazily: the channel/compile machinery is
    # only paid for by processes that actually compile a graph
    if name in ("CompiledDAG", "CompiledDAGRef", "CompiledGraphError"):
        from ray_tpu.dag import compiled

        return getattr(compiled, name)
    raise AttributeError(name)
