"""Pre-allocated channels for compiled execution graphs.

The data plane of ``dag/compiled.py`` (reference: Ray Compiled Graphs'
``experimental/channel/`` — ``shared_memory_channel.py``'s single-reader
ring over plasma mutable objects).  Two transports behind one interface:

- :class:`ShmChannel` — a fixed-slot SPSC ring living in ONE shm segment
  (the PR-1 pinned-arena mmap substrate, ``_private/shm.py``).  Writer and
  reader are different processes on the same node; publication is a
  per-slot sequence store after the payload bytes, consumption advances a
  shared read cursor, so steady-state transfer is two memcpys and zero
  syscalls — no scheduler, no head round trip, no object sealing.
- :class:`StreamWriterChannel` / :class:`StreamReaderChannel` — cross-node
  edges as an authenticated socket stream (the ``object_transfer.py``
  transfer-plane idiom) with credit-based backpressure: at most
  ``capacity`` unacknowledged messages in flight, acks ride the same
  duplex connection.

Capacity IS the backpressure: a full ring (or exhausted credits) blocks
``put`` until the consumer catches up, which is what bounds a compiled
graph's in-flight executions.  ``poison()`` works from either end and
wakes any blocked peer with :class:`ChannelClosedError` — teardown and
actor-death propagation both ride it.

Values larger than a slot overflow into a one-shot side segment whose
name rides in the slot (flag ``FLAG_OVERFLOW``); the reader unlinks it
after consumption, and orphans die with the session sweep because the
names keep the session prefix.
"""

from __future__ import annotations

import os
import struct
import threading
import time
from typing import Callable, Optional, Tuple

from ray_tpu._private.shm import ShmSegment

# message flags (bitfield in the slot/frame header)
FLAG_ERROR = 1      # payload is a serialized exception (propagates downstream)
FLAG_OVERFLOW = 2   # payload is the name of a one-shot overflow segment

_MAGIC = b"CDG1"
_HDR = 64               # channel header bytes
_SLOT_HDR = 24          # per-slot header: seq u64, length u64, flags u64
_OFF_NSLOTS = 8
_OFF_SLOT_BYTES = 16
_OFF_WRITE_SEQ = 24
_OFF_READ_SEQ = 32
_OFF_STATE = 40         # u8: 0 open, 1 closed/poisoned

_U64 = struct.Struct("<Q")
_SLOT = struct.Struct("<QQQ")


class ChannelError(Exception):
    """Base class for compiled-graph channel errors."""


class ChannelClosedError(ChannelError):
    """The channel was poisoned/torn down while waiting on it."""


class ChannelTimeoutError(ChannelError, TimeoutError):
    """A put/get exceeded its timeout with the peer making no progress."""


def _wait(cond: Callable[[], bool], deadline: Optional[float],
          closed: Callable[[], bool], what: str) -> None:
    """Adaptive wait: spin briefly (the common sub-100us handoff), then
    yield, then sleep — cross-process progress comes from the peer's mmap
    stores, so there is nothing to block on but time."""
    n = 0
    while True:
        if cond():
            return
        if closed():
            raise ChannelClosedError(f"channel closed while waiting to {what}")
        if deadline is not None and time.monotonic() >= deadline:
            raise ChannelTimeoutError(f"channel {what} timed out")
        n += 1
        if n < 1000:
            continue  # ~50-100us pure spin covers the in-flight handoff
        time.sleep(0 if n < 2000 else 0.0003)


class ShmChannel:
    """Fixed-slot SPSC ring in a shared-memory segment.

    Exactly one writer process and one reader process; each end keeps its
    own message counter, the shared header carries the published/consumed
    cursors.  ``create`` is the writer side, ``attach`` the reader side
    (either end may also attach purely to :meth:`poison`).
    """

    def __init__(self, seg: ShmSegment, owner: bool):
        self._seg = seg
        self._buf = seg.buf
        self._owner = owner  # creator unlinks the segment on close(unlink=True)
        self.n_slots = _U64.unpack_from(self._buf, _OFF_NSLOTS)[0]
        self.slot_bytes = _U64.unpack_from(self._buf, _OFF_SLOT_BYTES)[0]
        self._seq = 0  # this end's next message index
        self._closed_locally = False

    # -- construction --------------------------------------------------
    @classmethod
    def create(cls, name: str, n_slots: int, slot_bytes: int) -> "ShmChannel":
        size = _HDR + n_slots * (_SLOT_HDR + slot_bytes)
        seg = ShmSegment.create(name, size)
        buf = seg.buf
        buf[0:4] = _MAGIC
        _U64.pack_into(buf, _OFF_NSLOTS, n_slots)
        _U64.pack_into(buf, _OFF_SLOT_BYTES, slot_bytes)
        return cls(seg, owner=True)

    @classmethod
    def attach(cls, name: str) -> "ShmChannel":
        seg = ShmSegment.attach(name)
        if bytes(seg.buf[0:4]) != _MAGIC:
            raise ChannelError(f"segment {name} is not a compiled-graph channel")
        return cls(seg, owner=False)

    @property
    def name(self) -> str:
        return self._seg.name

    # -- state ---------------------------------------------------------
    def _state_closed(self) -> bool:
        return self._closed_locally or self._buf[_OFF_STATE] != 0

    def poison(self) -> None:
        """Mark the channel closed; both ends' blocked waits wake with
        :class:`ChannelClosedError`.  Idempotent, callable from either
        end (or from a third process that attached by name)."""
        try:
            self._buf[_OFF_STATE] = 1
        except (ValueError, IndexError):
            pass  # mapping already closed

    def close(self, unlink: bool = False) -> None:
        self._closed_locally = True
        name = self._seg.name
        self._buf = None
        self._seg.close()
        if unlink:
            ShmSegment.unlink(name)

    # -- data plane ----------------------------------------------------
    def _slot_off(self, k: int) -> int:
        return _HDR + (k % self.n_slots) * (_SLOT_HDR + self.slot_bytes)

    def can_put(self) -> bool:
        """True when a put would not block (slot free).  Single-writer, so
        a True answer cannot be invalidated by anyone but this caller."""
        buf = self._buf
        if buf is None or self._state_closed():
            return False
        return _U64.unpack_from(buf, _OFF_READ_SEQ)[0] + self.n_slots > self._seq

    def put(self, payload: bytes, flags: int = 0,
            timeout: Optional[float] = None) -> None:
        """Write one message; blocks while the ring is full (backpressure)."""
        buf = self._buf
        if buf is None or self._state_closed():
            raise ChannelClosedError("put on closed channel")
        k = self._seq
        deadline = None if timeout is None else time.monotonic() + timeout
        # wait for the slot BEFORE any side effect: a timed-out put must be
        # retryable with the same payload (the overflow spill below creates
        # an O_EXCL-named segment keyed by k)
        _wait(lambda: _U64.unpack_from(buf, _OFF_READ_SEQ)[0] + self.n_slots > k,
              deadline, self._state_closed, "put")
        if len(payload) > self.slot_bytes:
            payload, flags = self._spill_overflow(payload, k, flags)
        off = self._slot_off(k)
        data_off = off + _SLOT_HDR
        buf[data_off:data_off + len(payload)] = payload
        # publish: length+flags first, then the slot seq store the reader
        # spins on, then the aggregate write cursor (introspection only)
        struct.pack_into("<QQ", buf, off + 8, len(payload), flags)
        _U64.pack_into(buf, off, k + 1)
        _U64.pack_into(buf, _OFF_WRITE_SEQ, k + 1)
        self._seq = k + 1

    def _spill_overflow(self, payload: bytes, k: int, flags: int):
        name = f"{self._seg.name}-ovf{k}"
        try:
            seg = ShmSegment.create(name, len(payload))
        except FileExistsError:
            # a prior attempt of this same (channel, k) spilled but never
            # published (it can only have failed before the slot write) —
            # the orphan is ours to replace
            ShmSegment.unlink(name)
            seg = ShmSegment.create(name, len(payload))
        try:
            seg.buf[:] = payload
        finally:
            seg.close()
        return name.encode(), flags | FLAG_OVERFLOW

    def get(self, timeout: Optional[float] = None) -> Tuple[bytes, int]:
        """Read the next message; blocks until the writer publishes it."""
        buf = self._buf
        if buf is None:
            raise ChannelClosedError("get on closed channel")
        k = self._seq
        off = self._slot_off(k)
        deadline = None if timeout is None else time.monotonic() + timeout
        _wait(lambda: _U64.unpack_from(buf, off)[0] == k + 1,
              deadline, self._state_closed, "get")
        _, length, flags = _SLOT.unpack_from(buf, off)
        data_off = off + _SLOT_HDR
        payload = bytes(buf[data_off:data_off + length])
        _U64.pack_into(buf, _OFF_READ_SEQ, k + 1)  # frees the slot
        self._seq = k + 1
        if flags & FLAG_OVERFLOW:
            name = payload.decode()
            seg = ShmSegment.attach(name)
            try:
                payload = bytes(seg.buf)
            finally:
                seg.close()
                ShmSegment.unlink(name)
            flags &= ~FLAG_OVERFLOW
        return payload, flags


# ---------------------------------------------------------------------------
# Cross-node stream channels
# ---------------------------------------------------------------------------


def advertise_host() -> str:
    """Routable address for this node's stream listeners.  Follows the
    transfer plane's convention (``node.py`` object server): the operator-
    configured ``RAY_TPU_HOST`` wins; hostname resolution is only a
    fallback (on Debian-style hosts it maps to 127.0.1.1, and on
    multi-homed hosts it may pick a non-routable interface)."""
    import socket

    host = os.environ.get("RAY_TPU_HOST")
    if host:
        return host
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


class StreamWriterChannel:
    """Writer end of a cross-node edge: owns a Listener, accepts the one
    reader in the background, sends ``(seq, flags, payload)`` frames with
    at most ``capacity`` unacknowledged (credit backpressure)."""

    def __init__(self, capacity: int, authkey: bytes):
        from multiprocessing.connection import Listener

        self.capacity = capacity
        self._listener = Listener(("0.0.0.0", 0), family="AF_INET",
                                  authkey=authkey)
        self.addr = (advertise_host(), self._listener.address[1])
        self._conn = None
        self._conn_ready = threading.Event()
        self._closed = False
        self._seq = 0
        self._acked = 0
        threading.Thread(target=self._accept, daemon=True,
                         name="cdag-stream-accept").start()

    def _accept(self) -> None:
        try:
            self._conn = self._listener.accept()
        except Exception:
            self._closed = True
        self._conn_ready.set()

    def _drain_acks(self, block_timeout: float) -> None:
        conn = self._conn
        if conn is None:
            return
        try:
            while conn.poll(block_timeout):
                msg = conn.recv()
                if isinstance(msg, tuple) and msg and msg[0] == "ack":
                    self._acked = max(self._acked, int(msg[1]))
                elif isinstance(msg, tuple) and msg and msg[0] == "poison":
                    self._closed = True
                    return
                block_timeout = 0.0
        except (EOFError, OSError):
            self._closed = True

    def can_put(self) -> bool:
        """True when a put would not block: reader connected and a credit
        is available (acks drained opportunistically)."""
        if self._closed or not self._conn_ready.is_set():
            return False
        if self._seq - self._acked >= self.capacity:
            self._drain_acks(0.0)
        return (not self._closed
                and self._seq - self._acked < self.capacity)

    def put(self, payload: bytes, flags: int = 0,
            timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        _wait(self._conn_ready.is_set, deadline, lambda: self._closed,
              "put (await reader)")
        while self._seq - self._acked >= self.capacity:
            if self._closed:
                raise ChannelClosedError("put on closed stream channel")
            if deadline is not None and time.monotonic() >= deadline:
                raise ChannelTimeoutError("stream put timed out awaiting acks")
            self._drain_acks(0.02)
        if self._closed:
            raise ChannelClosedError("put on closed stream channel")
        try:
            self._conn.send((self._seq, flags, payload))
        except (OSError, ValueError, BrokenPipeError):
            self._closed = True
            raise ChannelClosedError("stream reader went away") from None
        self._seq += 1
        self._drain_acks(0.0)

    def poison(self) -> None:
        self._closed = True
        conn = self._conn
        if conn is not None:
            try:
                conn.send(("poison",))
            except Exception:
                pass
        self.close()

    def close(self, unlink: bool = False) -> None:
        self._closed = True
        for c in (self._conn, self._listener):
            try:
                if c is not None:
                    c.close()
            except Exception:
                pass


class StreamReaderChannel:
    """Reader end: dials the writer's listener, receives frames in order,
    acks after consumption so the writer's credit window advances."""

    def __init__(self, addr, authkey: bytes):
        from multiprocessing import AuthenticationError
        from multiprocessing.connection import Client as MPClient

        # same challenge-race retry as CoreClient/object_transfer
        for attempt in range(5):
            try:
                self._conn = MPClient(tuple(addr), family="AF_INET",
                                      authkey=authkey)
                break
            except (AuthenticationError, OSError, EOFError):
                if attempt == 4:
                    raise
                time.sleep(0.05 * (attempt + 1))
        self._closed = False
        self._seq = 0

    def get(self, timeout: Optional[float] = None) -> Tuple[bytes, int]:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._closed:
                raise ChannelClosedError("get on closed stream channel")
            if deadline is None:
                poll_t = 0.02
            else:
                poll_t = max(0.0, min(0.02, deadline - time.monotonic()))
            # NOTE the timeout raise lives OUTSIDE the try: TimeoutError is
            # an OSError subclass, so raising it inside would trip the
            # peer-went-away handler and wrongly close the channel
            try:
                ready = self._conn.poll(poll_t)
            except (EOFError, OSError):
                self._closed = True
                raise ChannelClosedError("stream writer went away") from None
            if not ready:
                if deadline is not None and time.monotonic() >= deadline:
                    raise ChannelTimeoutError("stream get timed out")
                continue
            try:
                msg = self._conn.recv()
            except (EOFError, OSError):
                self._closed = True
                raise ChannelClosedError("stream writer went away") from None
            if isinstance(msg, tuple) and msg and msg[0] == "poison":
                self._closed = True
                raise ChannelClosedError("stream channel poisoned")
            seq, flags, payload = msg
            self._seq = seq + 1
            try:
                self._conn.send(("ack", self._seq))
            except (OSError, ValueError, BrokenPipeError):
                self._closed = True  # writer gone; deliver the frame anyway
            return payload, flags

    def poison(self) -> None:
        self._closed = True
        try:
            self._conn.send(("poison",))
        except Exception:
            pass
        self.close()

    def close(self, unlink: bool = False) -> None:
        self._closed = True
        try:
            self._conn.close()
        except Exception:
            pass
