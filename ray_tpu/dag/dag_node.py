"""DAG node types.

A node records a bound computation (``dag_node.py:DAGNode`` in the
reference); nothing runs until ``execute``.  During execution each node
submits exactly once per call (diamond dependencies share the result —
the upstream task's ObjectRef is passed straight into downstream task
args, so the object plane does all data movement).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    """Base: bound args may contain other DAGNodes (the graph edges)."""

    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    # -- traversal -----------------------------------------------------
    def _children(self) -> List["DAGNode"]:
        out = []

        def scan(v):
            if isinstance(v, DAGNode):
                out.append(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    scan(x)
            elif isinstance(v, dict):
                for x in v.values():
                    scan(x)

        for a in self._bound_args:
            scan(a)
        for a in self._bound_kwargs.values():
            scan(a)
        return out

    def topological(self) -> List["DAGNode"]:
        """Dependencies-first ordering of the reachable graph.

        Iterative post-order DFS: a recursive visit overflows Python's
        recursion limit around 1k-node chains, and compiled pipeline
        graphs legitimately get that deep."""
        seen: Dict[int, DAGNode] = {}  # keeps nodes alive so ids stay unique
        order: List[DAGNode] = []
        stack: List[Tuple[DAGNode, bool]] = [(self, False)]
        while stack:
            n, emit = stack.pop()
            if emit:
                order.append(n)
                continue
            if id(n) in seen:
                continue
            seen[id(n)] = n
            stack.append((n, True))
            # reversed: the stack pops right-to-left, so this preserves the
            # recursive left-to-right sibling order — workflow checkpoint
            # step ids are keyed on the topological index and must not
            # shift across this rewrite
            for c in reversed(n._children()):
                if id(c) not in seen:
                    stack.append((c, False))
        return order

    # -- execution -----------------------------------------------------
    def _resolve(self, v, results: Dict[int, Any]):
        if isinstance(v, DAGNode):
            return results[id(v)]
        if isinstance(v, list):
            return [self._resolve(x, results) for x in v]
        if isinstance(v, tuple):
            return tuple(self._resolve(x, results) for x in v)
        if isinstance(v, dict):
            return {k: self._resolve(x, results) for k, x in v.items()}
        return v

    def _execute_impl(self, args: tuple, kwargs: dict):
        raise NotImplementedError

    def experimental_compile(self, **kwargs) -> "CompiledDAG":
        """Compile this static DAG once: actors get persistent execution
        loops, edges become pre-allocated channels, and repeated
        ``execute()`` calls bypass the scheduler entirely.  See
        :mod:`ray_tpu.dag.compiled` for semantics and limitations."""
        from ray_tpu.dag.compiled import CompiledDAG

        return CompiledDAG(self, **kwargs)

    def execute(self, *input_args, **input_kwargs):
        """Run the DAG; returns whatever the root node produces (an
        ObjectRef for function/method nodes, an actor handle for a
        ClassNode root)."""
        results: Dict[int, Any] = {}
        for node in self.topological():
            if isinstance(node, InputNode):
                if len(input_args) == 1 and not input_kwargs:
                    results[id(node)] = input_args[0]
                else:
                    results[id(node)] = _DAGInput(input_args, input_kwargs)
                continue
            args = tuple(node._resolve(a, results) for a in node._bound_args)
            kwargs = {k: node._resolve(v, results) for k, v in node._bound_kwargs.items()}
            results[id(node)] = node._execute_impl(args, kwargs)
        return results[id(self)]


class _DAGInput:
    """Multi-arg DAG input (InputNode with several values)."""

    def __init__(self, args: tuple, kwargs: dict):
        self.args = args
        self.kwargs = kwargs


class InputNode(DAGNode):
    """Placeholder for the value passed to ``dag.execute(...)``
    (``input_node.py`` analog).  Usable as a context manager::

        with InputNode() as inp:
            dag = f.bind(inp)
        dag.execute(5)
    """

    def __init__(self):
        super().__init__((), {})

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        pass

    def _execute_impl(self, args, kwargs):  # replaced by execute()
        raise RuntimeError("InputNode executed without an input")


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args: tuple, kwargs: dict,
                 options: Optional[dict] = None):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn
        self._options = options or {}

    def _execute_impl(self, args, kwargs):
        fn = self._remote_fn.options(**self._options) if self._options else self._remote_fn
        return fn.remote(*args, **kwargs)

    def options(self, **opts) -> "FunctionNode":
        merged = dict(self._options)
        merged.update(opts)
        return FunctionNode(self._remote_fn, self._bound_args,
                            self._bound_kwargs, merged)


class ClassNode(DAGNode):
    """A bound actor constructor; ``.method.bind(...)`` chains calls."""

    def __init__(self, actor_cls, args: tuple, kwargs: dict,
                 options: Optional[dict] = None):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls
        self._options = options or {}
        self._lock = threading.Lock()
        self._handle = None  # one actor per DAG instance

    def _execute_impl(self, args, kwargs):
        with self._lock:
            if self._handle is None:
                cls = (self._actor_cls.options(**self._options)
                       if self._options else self._actor_cls)
                self._handle = cls.remote(*args, **kwargs)
            return self._handle

    def options(self, **opts) -> "ClassNode":
        """Override actor options on the bound constructor (parity with
        ``FunctionNode.options``): returns a NEW ClassNode, so methods
        bound from this one keep targeting the original node/actor."""
        merged = dict(self._options)
        merged.update(opts)
        return ClassNode(self._actor_cls, self._bound_args,
                         self._bound_kwargs, merged)

    def __getattr__(self, name: str) -> "_ClassMethodBinder":
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClassMethodBinder(self, name)


class _ClassMethodBinder:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method_name, args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method_name: str,
                 args: tuple, kwargs: dict):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method_name = method_name

    def _children(self) -> List[DAGNode]:
        return [self._class_node] + super()._children()

    def _execute_impl(self, args, kwargs):
        # the class node ran first (topological order) -> handle exists
        handle = self._class_node._handle
        return getattr(handle, self._method_name).remote(*args, **kwargs)
