"""Compiled execution graphs: static actor DAGs over pre-allocated channels.

The reference's Compiled Graphs (aDAG, ``python/ray/dag/compiled_dag_node.py``)
applied to this runtime: ``dag.experimental_compile()`` schedules a
ClassMethodNode graph ONCE — after compile, a repeated ``execute()`` pays
zero scheduler involvement.  Every actor in the graph runs a persistent
execution loop on a dedicated thread (installed through the
``compiled_graph`` task lane in ``actor.py``/``_private/worker.py``, so the
loop never occupies the normal method lane), and every edge is a
pre-allocated channel (``dag/channel.py``): a fixed-slot SPSC shm ring for
same-node edges, an ``object_transfer``-style authenticated stream for
cross-node edges.  The dynamic path re-submits every node per call — each
hop paying dispatch + object-plane sealing; here a call is just channel
hops, which is what pipeline-parallel schedules and prefill→decode serving
need to keep up with pjit-compiled step times.

Compile protocol (driver-side, three actor round trips, all at compile
time only):

1. ``locality`` — each actor reports ``(hostname, shm_dir)``; comparing
   endpoint localities picks each edge's transport.
2. ``prepare`` — each actor creates its OUT-edge resources (shm rings in
   its node's namespace / stream listeners) and returns stream addresses.
3. ``start`` — each actor attaches its IN-edge readers and starts the loop.

Execution semantics:

- ``compiled.execute(x)`` writes the input into the entry channels and
  returns a :class:`CompiledDAGRef`; results are read from the output
  channel strictly in submission order (the static schedule makes per-seq
  ordering deterministic), buffered for out-of-order ``get``.
- In-flight executions are bounded by the channel slot count
  (``max_inflight``): a full ring backpressures ``execute``.
- A node exception becomes an error payload (``FLAG_ERROR``) that flows
  THROUGH downstream nodes (they skip execution and forward it) and
  re-raises on ``get`` — the graph itself survives and keeps serving.
- Actor death cannot hang the caller: ``get`` interleaves channel waits
  with actor-liveness checks against the head and raises
  :class:`ray_tpu.exceptions.ActorDiedError`.
- ``teardown()`` poisons every channel (waking any blocked loop), asks
  each live actor to join its loop and unlink its segments, and is
  idempotent.

Observability: per-node execution spans and channel-wait spans are emitted
on the ``compiled_dag`` flight-recorder source (``_private/events.py``),
so ``ray_tpu timeline`` renders the pipeline bubble structure next to the
task slices (``util/timeline.py``).  When ``execute()`` runs inside a
``tracing.trace()`` block, the caller's context rides the channel
payloads (:class:`_Traced`): every node's exec/channel-wait span joins
the request's trace, stages chain parent→child, recv waits are clamped
to the request's entry time (loop idle never bills to a trace), and
``ray_tpu trace <id>`` attributes the request's wall time across
node execution vs channel wait vs result wait.  Untraced executions
serialize bare values — nothing changes off-trace.

Limitations vs the reference aDAG: DAG nodes must be actor method calls
(no bare task nodes), node arguments may reference other nodes only at
top level (no nesting inside containers), one output node, asyncio actors
not special-cased, ObjectRefs cannot ride channel payloads (nothing would
pin them; loudly rejected), and thin-client drivers are unsupported (the
driver must share a control plane + either shm or TCP reachability with
the cluster).  Concurrency caveat: compiled methods run on the graph's
dedicated loop thread — they are serialized against each other but NOT
against normal ``.remote()`` method calls on the same actor (same
tradeoff as the reference's aDAG executor thread), so an actor serving
both lanes concurrently must guard shared state itself.
"""

from __future__ import annotations

import os
import socket
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import events as _events
from ray_tpu._private import serialization
from ray_tpu._private.locks import make_lock
from ray_tpu.util import tracing as _tracing
from ray_tpu.dag.channel import (
    FLAG_ERROR,
    ChannelClosedError,
    ChannelTimeoutError,
    ShmChannel,
    StreamReaderChannel,
    StreamWriterChannel,
)
from ray_tpu.dag.dag_node import ClassMethodNode, ClassNode, InputNode, _DAGInput
from ray_tpu.exceptions import ActorDiedError, RayTaskError

DRIVER = -1  # endpoint index for the driver process
_SOURCE = "compiled_dag"  # flight-recorder source for node/channel spans
# channel waits shorter than this don't emit a span (ring-buffer noise)
_WAIT_SPAN_MIN_S = 0.001


class CompiledGraphError(Exception):
    """Compiled-graph lifecycle error (bad graph shape, use after
    teardown, capacity exceeded)."""


def _ser(value: Any) -> bytes:
    meta, buffers, refs = serialization.serialize(value)
    if refs:
        # Channel payloads bypass the object plane entirely, so nothing
        # would pin the referenced objects for the consumer (the submit
        # path pins via client.add_refs; here the producer has no idea
        # when the consumer's borrow registers).  A silent use-after-free
        # is worse than a loud rejection.
        raise ValueError(
            "ObjectRefs cannot pass through compiled-graph channels "
            f"({len(refs)} found); pass the value itself, or ray_tpu.get "
            "it first")
    return serialization.to_bytes(meta, buffers)


def _deser(payload: bytes) -> Any:
    return serialization.deserialize(memoryview(payload))


def _ser_error(err: BaseException) -> bytes:
    """Serialize an error payload, falling back to a string-only
    RayTaskError when the user's exception itself won't pickle (custom
    __init__ signatures, captured locks/sockets, embedded ObjectRefs) —
    an unserializable error must degrade, not kill the loop."""
    try:
        return _ser(err)
    except Exception:
        return _ser(RayTaskError(
            f"{type(err).__name__}: {err} "
            f"(original exception not serializable)"))


def _deser_error(payload: bytes) -> BaseException:
    """Deserialize an error payload; a class importable on the producer
    but not here still yields a usable error object."""
    try:
        err = _deser(payload)
    except Exception as e:  # noqa: BLE001
        return RayTaskError(
            f"upstream compiled-graph error could not be deserialized: {e}")
    if isinstance(err, BaseException):
        return err
    return RayTaskError(f"upstream compiled-graph error: {err!r}")


def _locality() -> Tuple[str, str]:
    from ray_tpu._private.shm import shm_dir

    return (socket.gethostname(), shm_dir())


# ---------------------------------------------------------------------------
# Plan structures (driver builds them; actors receive them cloudpickled)
# ---------------------------------------------------------------------------


class _TaskPlan:
    """One ClassMethodNode's slice of the compiled schedule."""

    __slots__ = ("idx", "method", "args", "kwargs", "in_edges", "out_edges",
                 "label")

    def __init__(self, idx: int, method: str, args: list, kwargs: dict,
                 in_edges: List[int], out_edges: List[int], label: str):
        self.idx = idx
        self.method = method
        self.args = args          # list of ("const", v) | ("edge", eid)
        self.kwargs = kwargs      # name -> same spec
        self.in_edges = in_edges  # ALL in-edge ids (incl. trigger edges)
        self.out_edges = out_edges
        self.label = label


class _ErrVal:
    """An error flowing through the graph as a value."""

    __slots__ = ("err",)

    def __init__(self, err: BaseException):
        self.err = err


class _Traced:
    """A channel payload carrying its trace context alongside the value.

    When ``execute()`` runs inside a ``tracing.trace()`` block, the input
    payload is wrapped so the context rides the channel with the data —
    each node loop unwraps it, emits its exec/channel-wait spans as
    children of the caller's trace, and re-wraps its output with its own
    span as the parent (so a pipeline's spans chain stage to stage).
    Untraced executions serialize the bare value: zero overhead and
    byte-identical payloads when tracing is unused."""

    __slots__ = ("ctx", "value")

    def __init__(self, ctx: Dict[str, str], value: Any):
        self.ctx = ctx
        self.value = value

    def __reduce__(self):
        return (_Traced, (self.ctx, self.value))


# ---------------------------------------------------------------------------
# Actor-side execution (runs inside the actor's worker process)
# ---------------------------------------------------------------------------

_LOCAL_GRAPHS: Dict[str, "_ActorGraph"] = {}
_LOCAL_LOCK = make_lock("compiled.local_channels")


class _ActorGraph:
    """Per-actor compiled-graph state living in the actor's worker."""

    def __init__(self, gid: str, tasks: List[_TaskPlan], authkey: bytes):
        self.gid = gid
        self.tasks = tasks
        self.authkey = authkey
        self.writers: Dict[int, Any] = {}   # eid -> writer channel
        self.readers: Dict[int, Any] = {}   # eid -> reader channel
        self.owned_segments: List[str] = []
        self.thread: Optional[threading.Thread] = None
        self.stop = threading.Event()

    # -- loop ----------------------------------------------------------
    def run_loop(self) -> None:
        seq = 0
        try:
            while not self.stop.is_set():
                self._run_one(seq)
                seq += 1
        except ChannelClosedError:
            pass  # teardown or upstream poison: exit (and cascade below)
        except BaseException as e:  # pragma: no cover - defensive
            _events.emit(_SOURCE, "actor loop died", severity="ERROR",
                         entity_id=self.gid, error=repr(e))
        finally:
            # ANY exit poisons this actor's out-edges: a mid-chain loop
            # death (internal error OR an upstream poison arriving outside
            # teardown) must cascade, or downstream loops and the driver's
            # get() would block on a silently-dead producer forever
            for w in self.writers.values():
                try:
                    w.poison()
                except Exception:
                    pass

    def _read_inputs(self, task: _TaskPlan, seq: int):
        """Read every in-edge; returns (vals, waits) where waits carries
        each edge's blocked time AND its wall-clock completion — emitted
        as channel-wait spans by the caller AFTER trace-context
        extraction (the lineage rides the payloads), each stamped at its
        own end time so sequential waits on a multi-input node render as
        sequential, not stacked at emission time."""
        vals: Dict[int, Any] = {}
        waits: List[Tuple[int, float, float]] = []  # (eid, waited, t_end)
        for eid in task.in_edges:
            t0 = time.perf_counter()
            while True:
                if self.stop.is_set():
                    raise ChannelClosedError("graph torn down")
                try:
                    payload, flags = self.readers[eid].get(timeout=1.0)
                    break
                except ChannelTimeoutError:
                    continue
            waits.append((eid, time.perf_counter() - t0, time.time()))
            if flags & FLAG_ERROR:
                vals[eid] = _ErrVal(_deser_error(payload))
            else:
                vals[eid] = _deser(payload)
        return vals, waits

    def _run_one(self, seq: int) -> None:
        from ray_tpu.util.tracing import new_span_id, span_fields

        instance = self.instance
        for task in self.tasks:
            vals, waits = self._read_inputs(task, seq)
            # a traced execution's context rides the payload: unwrap, and
            # chain this node's spans under it
            ctx = None
            for eid, v in vals.items():
                if isinstance(v, _Traced):
                    ctx = ctx or v.ctx
                    vals[eid] = v.value
            node_ctx = None
            if ctx is not None:
                node_ctx = {"trace_id": ctx["trace_id"],
                            "span_id": new_span_id(),
                            "parent_span_id": ctx["span_id"],
                            "name": task.label}
                if "t0" in ctx:
                    node_ctx["t0"] = ctx["t0"]  # downstream clamps too
            # traced recv waits are clamped to the request's entry time: a
            # loop that sat idle for minutes BEFORE this request was
            # submitted must not charge that idle to the request's trace.
            # t0 is the DRIVER's wall clock; a skewed consumer clock could
            # push (t_end - t0) negative and wrongly suppress genuine
            # waits, so the clamp only applies while it has positive
            # headroom — beyond NTP-level skew the full wait is kept
            # (idle billing is a smaller lie than erasing the wait).
            req_t0 = None
            if node_ctx is not None and "t0" in node_ctx:
                req_t0 = float(node_ctx["t0"])
            for eid, waited, t_end in waits:
                if req_t0 is not None:
                    headroom = t_end - req_t0 + 0.25
                    if headroom > 0:
                        waited = min(waited, headroom)
                if waited >= _WAIT_SPAN_MIN_S:
                    # ts=t_end: each edge's span sits at ITS completion,
                    # so sequential waits render sequentially
                    _events.emit(_SOURCE, "channel wait", severity="DEBUG",
                                 entity_id=f"{self.gid}:{task.label}",
                                 span_dur=waited, ts=t_end, edge=eid,
                                 seq=seq, op="recv",
                                 **span_fields(node_ctx, "channel_wait"))
            err = next((v for v in vals.values() if isinstance(v, _ErrVal)),
                       None)
            if err is not None:
                out_payload, out_flags = _ser_error(err.err), FLAG_ERROR
            else:
                t0 = time.perf_counter()
                try:
                    args = [vals[s[1]] if s[0] == "edge" else s[1]
                            for s in task.args]
                    kwargs = {k: (vals[s[1]] if s[0] == "edge" else s[1])
                              for k, s in task.kwargs.items()}
                    result = getattr(instance, task.method)(*args, **kwargs)
                    if node_ctx is not None:
                        # downstream nodes (and the driver's output) chain
                        # under THIS node's span
                        result = _Traced(node_ctx, result)
                    out_payload, out_flags = _ser(result), 0
                except BaseException as e:  # noqa: BLE001 — user node error
                    tb = traceback.format_exc()
                    wrapped = e if isinstance(e, RayTaskError) else RayTaskError(
                        f"Compiled DAG node {task.label} failed:\n{tb}", cause=e)
                    out_payload, out_flags = _ser_error(wrapped), FLAG_ERROR
                # the exec span IS the node's own span (node_ctx), parented
                # to the incoming context
                _events.emit(_SOURCE, task.label, severity="DEBUG",
                             entity_id=f"{self.gid}:{task.label}",
                             span_dur=time.perf_counter() - t0, seq=seq,
                             **span_fields(
                                 ctx, "node_exec",
                                 span_id=(node_ctx or {}).get("span_id")))
            for eid in task.out_edges:
                t0 = time.perf_counter()
                while True:
                    if self.stop.is_set():
                        raise ChannelClosedError("graph torn down")
                    try:
                        self.writers[eid].put(out_payload, out_flags,
                                              timeout=1.0)
                        break
                    except ChannelTimeoutError:
                        continue
                waited = time.perf_counter() - t0
                if waited >= _WAIT_SPAN_MIN_S:
                    _events.emit(_SOURCE, "channel wait", severity="DEBUG",
                                 entity_id=f"{self.gid}:{task.label}",
                                 span_dur=waited, edge=eid, seq=seq,
                                 op="send",
                                 **span_fields(node_ctx, "channel_wait"))

    # -- teardown ------------------------------------------------------
    def teardown(self) -> None:
        self.stop.set()
        for ch in list(self.writers.values()) + list(self.readers.values()):
            try:
                ch.poison()
            except Exception:
                pass
        if self.thread is not None:
            self.thread.join(timeout=5.0)
        for ch in list(self.writers.values()) + list(self.readers.values()):
            try:
                ch.close()
            except Exception:
                pass
        from ray_tpu._private.shm import ShmSegment

        for name in self.owned_segments:
            ShmSegment.unlink(name)


def _cdag_rpc(instance, op: str, blob: bytes = b"") -> Any:
    """Single actor-side entry point for all compiled-graph control ops.

    Submitted through the ``compiled_graph`` task lane
    (``ActorHandle._submit_compiled_task``): the worker executes it with
    the actor INSTANCE as first argument, outside the normal
    ``getattr(instance, method)`` path.  The ops themselves return
    quickly — the execution loop runs on its own daemon thread, so it
    never occupies the task lane.
    """
    import cloudpickle

    if op == "locality":
        return _locality()

    if op == "prepare":
        plan = cloudpickle.loads(blob)
        g = _ActorGraph(plan["gid"], plan["tasks"], plan["authkey"])
        addrs: Dict[int, tuple] = {}
        for eid, spec in plan["out_channels"].items():
            if spec["kind"] == "shm":
                ch = ShmChannel.create(spec["name"], spec["slots"],
                                       spec["slot_bytes"])
                g.owned_segments.append(spec["name"])
            else:
                ch = StreamWriterChannel(spec["slots"], plan["authkey"])
                addrs[eid] = ch.addr
            g.writers[eid] = ch
        with _LOCAL_LOCK:
            _LOCAL_GRAPHS[plan["gid"]] = g
        return addrs

    if op == "start":
        info = cloudpickle.loads(blob)
        with _LOCAL_LOCK:
            g = _LOCAL_GRAPHS[info["gid"]]
        for eid, spec in info["in_channels"].items():
            if spec["kind"] == "shm":
                g.readers[eid] = ShmChannel.attach(spec["name"])
            else:
                g.readers[eid] = StreamReaderChannel(spec["addr"], g.authkey)
        g.instance = instance
        g.thread = threading.Thread(
            target=g.run_loop, daemon=True,
            name=f"cdag-loop-{info['gid'][:8]}")
        g.thread.start()
        return "ok"

    if op == "teardown":
        with _LOCAL_LOCK:
            g = _LOCAL_GRAPHS.pop(blob.decode() if blob else "", None)
        if g is not None:
            g.teardown()
        return "ok"

    raise ValueError(f"unknown compiled-graph op {op!r}")


# ---------------------------------------------------------------------------
# Driver side
# ---------------------------------------------------------------------------


class CompiledDAGRef:
    """Handle to one compiled-graph execution's output.

    ``ray_tpu.get`` accepts it alongside ObjectRefs; :meth:`get` reads the
    pre-allocated output channel directly (no object plane).  Dropping the
    ref without ``get`` releases its buffered result (a serving loop that
    abandons timed-out requests must not leak driver memory)."""

    __slots__ = ("_dag", "seq", "__weakref__")

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self.seq = seq

    def get(self, timeout: Optional[float] = None) -> Any:
        return self._dag._get_result(self.seq, timeout)

    def __del__(self):
        # lock-free (a GC pass may fire mid-locked-section on this very
        # thread): enqueue only; drained under the dag lock
        try:
            self._dag._abandoned_q.append(self.seq)
        except Exception:
            pass

    def __repr__(self) -> str:
        return f"CompiledDAGRef(seq={self.seq})"


class CompiledDAG:
    """A compiled static actor DAG.  Build via
    ``dag.experimental_compile(...)``; see the module docstring."""

    def __init__(self, root, *, max_inflight: int = 8,
                 slot_bytes: int = 1 << 20,
                 submit_timeout: float = 30.0,
                 get_timeout: Optional[float] = None):
        from ray_tpu._private.worker import global_worker

        if global_worker.thin_client:
            raise CompiledGraphError(
                "compiled graphs require a co-located driver (thin "
                "client:// drivers share no data plane with the cluster)")
        if max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        self._w = global_worker
        self._max_inflight = max_inflight
        self._slot_bytes = slot_bytes
        self._submit_timeout = submit_timeout
        self._get_timeout = get_timeout
        self._gid = os.urandom(6).hex()  # raylint: disable=R3 (per compile)
        self._torn_down = False
        self._lock = make_lock("compiled.graph")
        self._seq = 0            # next execution index to submit
        self._next_out = 0       # next seq expected from the output channel
        self._results: Dict[int, Tuple[bytes, int]] = {}
        # consumed-seq tracking in O(max_inflight) memory: everything below
        # the low-water mark is consumed; the set holds out-of-order gets
        self._fetched_below = 0
        self._fetched: set = set()
        # refs dropped without get(): finalizers append here (deque append
        # is atomic + lock-free — the worker.py _dead_handles pattern);
        # drained under the lock so their buffered results are released
        from collections import deque

        self._abandoned_q: "deque" = deque()
        # traced executions: seq -> the execute-span context (result-wait
        # spans chain under it); popped when the seq is consumed, so the
        # dict mirrors _results' lifecycle and stays bounded
        self._trace_ctxs: Dict[int, dict] = {}
        self._broken: Optional[str] = None  # set on a partial input write
        try:
            self._compile(root)
        except BaseException:
            # release whatever the partial compile built (actors,
            # prepared loops, listeners, segments) — the caller never
            # gets a handle to teardown
            try:
                self.teardown()
            except Exception:
                pass
            raise

    # -- compilation ---------------------------------------------------
    def _compile(self, root) -> None:
        import ray_tpu

        topo = root.topological()
        if not isinstance(root, ClassMethodNode):
            raise CompiledGraphError(
                "compiled DAGs must be rooted at an actor method node "
                f"(got {type(root).__name__}); bare task nodes are not "
                "supported")
        method_nodes: List[ClassMethodNode] = []
        input_nodes: List[InputNode] = []
        for n in topo:
            if isinstance(n, ClassMethodNode):
                method_nodes.append(n)
            elif isinstance(n, InputNode):
                input_nodes.append(n)
            elif not isinstance(n, ClassNode):
                raise CompiledGraphError(
                    f"unsupported node type in compiled DAG: "
                    f"{type(n).__name__}")
        if len(input_nodes) > 1:
            raise CompiledGraphError("compiled DAGs take a single InputNode")

        # create the actors (ClassNodes ran through the dynamic path keep
        # one actor per node instance — same semantics here)
        idx_of = {id(n): i for i, n in enumerate(method_nodes)}
        self.actors: List[Any] = []
        actor_of_node: List[int] = []  # method idx -> actor slot
        actor_slots: Dict[int, int] = {}  # id(class_node) -> actor slot
        for n in method_nodes:
            cn = n._class_node
            slot = actor_slots.get(id(cn))
            if slot is None:
                if any(_contains_node(a) for a in cn._bound_args) or any(
                        _contains_node(v) for v in cn._bound_kwargs.values()):
                    raise CompiledGraphError(
                        "node references in actor constructor arguments "
                        "are not supported in compiled DAGs (create the "
                        "value eagerly or pass it through the method "
                        "call instead)")
                args = tuple(cn._resolve(a, {}) for a in cn._bound_args)
                kwargs = {k: cn._resolve(v, {})
                          for k, v in cn._bound_kwargs.items()}
                handle = cn._execute_impl(args, kwargs)
                slot = len(self.actors)
                actor_slots[id(cn)] = slot
                self.actors.append(handle)
            actor_of_node.append(slot)

        # edges: one SPSC channel per (producer, consumer-node) pair
        edges: List[dict] = []   # {writer: idx|DRIVER, reader: idx|DRIVER}
        edge_ids: Dict[Tuple[int, int], int] = {}

        def edge(writer: int, reader: int) -> int:
            key = (writer, reader)
            eid = edge_ids.get(key)
            if eid is None:
                eid = len(edges)
                edge_ids[key] = eid
                edges.append({"writer": writer, "reader": reader})
            return eid

        def argspec(v, consumer: int):
            if isinstance(v, InputNode):
                return ("edge", edge(DRIVER, consumer))
            if isinstance(v, ClassMethodNode):
                return ("edge", edge(idx_of[id(v)], consumer))
            if isinstance(v, ClassNode):
                raise CompiledGraphError(
                    "actor handles cannot be passed as compiled DAG "
                    "arguments")
            if isinstance(v, (list, tuple, dict)) and _contains_node(v):
                raise CompiledGraphError(
                    "compiled DAGs support node references only at "
                    "top-level argument positions (no nesting inside "
                    "containers)")
            return ("const", v)

        plans: List[_TaskPlan] = []
        for j, n in enumerate(method_nodes):
            args = [argspec(a, j) for a in n._bound_args]
            kwargs = {k: argspec(v, j) for k, v in n._bound_kwargs.items()}
            label = f"{n._method_name}:{j}"
            plans.append(_TaskPlan(j, n._method_name, args, kwargs, [], [],
                                   label))
        # every task with no in-edges still needs a driver trigger edge to
        # pace its loop (a source node would otherwise free-run)
        for j, p in enumerate(plans):
            ins = sorted({s[1] for s in p.args if s[0] == "edge"}
                         | {s[1] for s in p.kwargs.values() if s[0] == "edge"})
            if not ins:
                ins = [edge(DRIVER, j)]
            p.in_edges = ins
        out_eid = edge(idx_of[id(root)], DRIVER)
        for eid, e in enumerate(edges):
            if e["writer"] != DRIVER:
                plans[e["writer"]].out_edges.append(eid)
        self._edges = edges
        self._out_eid = out_eid

        # -- locality gather (round trip 1) ----------------------------
        loc_refs = [h._submit_compiled_task(_cdag_rpc, ("locality",),
                                            name="cdag.locality")
                    for h in self.actors]
        localities = ray_tpu.get(loc_refs, timeout=120)
        driver_loc = _locality()
        from ray_tpu._private.shm import session_shm_name

        authkey = self._authkey()
        for eid, e in enumerate(edges):
            wloc = driver_loc if e["writer"] == DRIVER else \
                localities[actor_of_node[e["writer"]]]
            rloc = driver_loc if e["reader"] == DRIVER else \
                localities[actor_of_node[e["reader"]]]
            e["kind"] = "shm" if wloc == rloc else "stream"
            if e["kind"] == "shm":
                e["name"] = session_shm_name(f"cdag{self._gid}e{eid}")

        # -- prepare (round trip 2): writers create their channels ------
        import cloudpickle

        prep_refs = []
        for slot, h in enumerate(self.actors):
            my_tasks = [p for j, p in enumerate(plans)
                        if actor_of_node[j] == slot]
            out_channels = {}
            for p in my_tasks:
                for eid in p.out_edges:
                    e = edges[eid]
                    spec = {"kind": e["kind"], "slots": self._max_inflight,
                            "slot_bytes": self._slot_bytes}
                    if e["kind"] == "shm":
                        spec["name"] = e["name"]
                    out_channels[eid] = spec
            plan = {"gid": self._gid, "tasks": my_tasks, "authkey": authkey,
                    "out_channels": out_channels}
            prep_refs.append(h._submit_compiled_task(
                _cdag_rpc, ("prepare", cloudpickle.dumps(plan)),
                name="cdag.prepare"))
        stream_addrs: Dict[int, tuple] = {}
        for reply in ray_tpu.get(prep_refs, timeout=120):
            stream_addrs.update(reply)
        # driver-side writers (input/trigger edges)
        self._writers: Dict[int, Any] = {}
        self._input_eids: List[int] = []
        for eid, e in enumerate(edges):
            if e["writer"] != DRIVER:
                continue
            self._input_eids.append(eid)
            if e["kind"] == "shm":
                self._writers[eid] = ShmChannel.create(
                    e["name"], self._max_inflight, self._slot_bytes)
            else:
                ch = StreamWriterChannel(self._max_inflight, authkey)
                stream_addrs[eid] = ch.addr
                self._writers[eid] = ch

        # -- start (round trip 3): readers attach, loops start ----------
        start_refs = []
        for slot, h in enumerate(self.actors):
            in_channels = {}
            for j, p in enumerate(plans):
                if actor_of_node[j] != slot:
                    continue
                for eid in p.in_edges:
                    e = edges[eid]
                    if e["kind"] == "shm":
                        in_channels[eid] = {"kind": "shm", "name": e["name"]}
                    else:
                        in_channels[eid] = {"kind": "stream",
                                            "addr": stream_addrs[eid]}
            info = {"gid": self._gid, "in_channels": in_channels}
            start_refs.append(h._submit_compiled_task(
                _cdag_rpc, ("start", cloudpickle.dumps(info)),
                name="cdag.start"))
        ray_tpu.get(start_refs, timeout=120)
        out_e = edges[out_eid]
        if out_e["kind"] == "shm":
            self._reader = ShmChannel.attach(out_e["name"])
        else:
            self._reader = StreamReaderChannel(stream_addrs[out_eid], authkey)
        self._actor_ids = {h._actor_id.hex() for h in self.actors}
        # restart-detection baseline, snapshotted NOW: a ClassNode caches
        # its actor handle, so compile may adopt an actor that already
        # restarted before this graph existed — only a restart AFTER the
        # loops were installed means the graph's state died with an
        # incarnation
        self._baseline_restarts: Dict[str, int] = {}
        try:
            rows = self._w.client.request(
                {"type": "list_state", "what": "actors", "limit": 100_000},
                timeout=30)["value"]
            self._baseline_restarts = {
                r["actor_id"]: r.get("num_restarts") or 0
                for r in rows if r.get("actor_id") in self._actor_ids}
        except Exception:
            pass  # conservative default 0 per actor
        _events.emit(_SOURCE, "graph compiled", entity_id=self._gid,
                     nodes=len(plans), actors=len(self.actors),
                     edges=len(edges),
                     stream_edges=sum(e["kind"] == "stream" for e in edges))

    def _authkey(self) -> bytes:
        node = self._w.node
        if node is not None:
            return node.authkey
        return bytes.fromhex(os.environ["RAY_TPU_AUTHKEY"])

    # -- execution -----------------------------------------------------
    def execute(self, *args, **kwargs) -> CompiledDAGRef:
        """Run the graph once; returns a ref for the root node's output.
        Blocks only when ``max_inflight`` executions are already queued
        (channel backpressure)."""
        with self._lock:
            if self._torn_down:
                raise CompiledGraphError("compiled DAG is torn down")
            if self._broken:
                raise CompiledGraphError(
                    f"compiled DAG is broken ({self._broken}); teardown() "
                    f"and recompile")
            if len(args) == 1 and not kwargs:
                value = args[0]
            else:
                value = _DAGInput(args, kwargs)
            # a traced caller's context rides the channel payload: every
            # node loop's exec/channel-wait spans join this trace
            exec_ctx = None
            if _events.ENABLED:
                exec_ctx = _tracing.child_context(f"cdag.execute {self._gid[:6]}")
                if exec_ctx is not None:
                    # t0 = when the request entered the graph: node loops
                    # clamp their recv-wait spans to it, so idle-before-
                    # request time is never attributed to this trace
                    exec_ctx["t0"] = time.time()
                    value = _Traced(exec_ctx, value)
            payload = _ser(value)
            seq = self._seq
            deadline = time.monotonic() + self._submit_timeout
            t0 = time.perf_counter()
            # reserve-then-write: wait until EVERY input edge can accept
            # (draining completed outputs meanwhile), then write all of
            # them.  The driver is each edge's only writer, so a True
            # can_put() cannot be invalidated — the writes can't block,
            # and a timeout here leaves NO partial submission behind
            # (partial writes would desync the edges' seq pairing forever)
            while not all(self._writers[eid].can_put()
                          for eid in self._input_eids):
                self._drain_output(block=True)
                if self._broken:
                    raise CompiledGraphError(
                        f"compiled DAG is broken ({self._broken}); "
                        f"teardown() and recompile")
                if time.monotonic() >= deadline:
                    self._check_alive()
                    raise ChannelTimeoutError(
                        f"execute() backpressured for "
                        f"{self._submit_timeout}s ({self._max_inflight} "
                        f"executions in flight)")
            wrote = 0
            try:
                for eid in self._input_eids:
                    self._writers[eid].put(payload, 0, timeout=5.0)
                    wrote += 1
            except (ChannelClosedError, ChannelTimeoutError) as e:
                if wrote:
                    # some edges carry seq N that the others never got:
                    # the pairing is unrecoverable — poison everything so
                    # no consumer computes with mixed inputs
                    self._broken = f"partial input write ({e})"
                    for w in self._writers.values():
                        try:
                            w.poison()
                        except Exception:
                            pass
                self._check_alive()
                raise
            self._seq = seq + 1
            if exec_ctx is not None:
                self._trace_ctxs[seq] = exec_ctx
                _tracing.emit_span(f"cdag.execute {self._gid[:6]}",
                                  time.perf_counter() - t0, exec_ctx,
                                  phase="submit", seq=seq)
            waited = time.perf_counter() - t0
            if waited >= _WAIT_SPAN_MIN_S:
                _events.emit(_SOURCE, "execute backpressure", severity="DEBUG",
                             entity_id=self._gid, span_dur=waited, seq=seq)
            return CompiledDAGRef(self, seq)

    def _mark_consumed(self, seq: int) -> None:
        """Record ``seq`` as consumed (gotten or abandoned), advancing the
        low-water mark so tracking stays O(max_inflight).  Lock held."""
        self._fetched.add(seq)
        self._trace_ctxs.pop(seq, None)
        while self._fetched_below in self._fetched:
            self._fetched.discard(self._fetched_below)
            self._fetched_below += 1

    def _drain_abandoned(self) -> None:
        """Release results whose refs were GC'd without get().  Lock held."""
        while True:
            try:
                seq = self._abandoned_q.popleft()
            except IndexError:
                return
            if seq < self._fetched_below or seq in self._fetched:
                continue  # already consumed by get()
            self._results.pop(seq, None)
            self._mark_consumed(seq)

    def _drain_output(self, block: bool) -> bool:
        """Move any completed results from the output channel into the
        buffer (skipping abandoned seqs).  With ``block=False`` only takes
        what's already there."""
        self._drain_abandoned()
        got = False
        while True:
            try:
                payload, flags = self._reader.get(timeout=0.05 if block else 0)
            except ChannelTimeoutError:
                return got
            except ChannelClosedError:
                if self._torn_down:
                    raise
                # poisoned OUTSIDE teardown: an actor loop died and the
                # poison cascaded here — the graph cannot produce again
                self._broken = self._broken or "output channel closed"
                return got
            seq = self._next_out
            self._next_out += 1
            if seq < self._fetched_below or seq in self._fetched:
                continue  # abandoned before its result landed: discard
            self._results[seq] = (payload, flags)
            got = True
            block = False

    def _get_result(self, seq: int, timeout: Optional[float]) -> Any:
        if timeout is None:
            timeout = self._get_timeout
        deadline = None if timeout is None else time.monotonic() + timeout
        t0 = time.perf_counter()
        last_liveness = 0.0
        while True:
            with self._lock:
                self._drain_abandoned()
                if seq in self._results:
                    payload, flags = self._results.pop(seq)
                    exec_ctx = self._trace_ctxs.get(seq)
                    self._mark_consumed(seq)
                    break
                if seq < self._fetched_below or seq in self._fetched:
                    raise CompiledGraphError(
                        f"execution {seq} was already consumed by get()")
                if self._torn_down:
                    raise CompiledGraphError("compiled DAG is torn down")
                if seq >= self._seq:
                    raise CompiledGraphError(
                        f"execution {seq} was never submitted")
                self._drain_output(block=True)
                broken = (self._broken if seq not in self._results else None)
            if broken:
                # actor death is the usual cause of a poisoned output
                # (stream EOF) — surface it as the typed ActorDiedError
                self._check_alive()
                raise CompiledGraphError(
                    f"compiled DAG is broken ({broken}); teardown() and "
                    f"recompile")
            now = time.monotonic()
            # liveness every 2s, not per poll: each check is a full actor-
            # table fetch from the head, and the compiled path exists to
            # keep steady-state serving OFF the control plane
            if now - last_liveness >= 2.0:
                last_liveness = now
                self._check_alive()
            if deadline is not None and now >= deadline:
                from ray_tpu.exceptions import GetTimeoutError

                raise GetTimeoutError(
                    f"compiled DAG result {seq} not ready after {timeout}s")
        waited = time.perf_counter() - t0
        if waited >= _WAIT_SPAN_MIN_S:
            from ray_tpu.util.tracing import span_fields

            _events.emit(_SOURCE, "result wait", severity="DEBUG",
                         entity_id=self._gid, span_dur=waited, seq=seq,
                         **span_fields(exec_ctx, "result_wait"))
        if flags & FLAG_ERROR:
            raise _deser_error(payload)
        value = _deser(payload)
        if isinstance(value, _Traced):  # traced execution: unwrap the output
            value = value.value
        return value

    def _check_alive(self) -> None:
        """Raise a typed error if any participating actor died — the
        guarantee that a mid-graph SIGKILL can never hang the caller."""
        try:
            rows = self._w.client.request(
                {"type": "list_state", "what": "actors", "limit": 100_000},
                timeout=30)["value"]
        except Exception:
            return  # control plane unreachable; channel timeouts still bound us
        # DEAD is death; so is RESTARTING or a bumped restart count — a
        # restarted incarnation has neither the loop thread nor the
        # channel attachments, so the compiled graph cannot recover (the
        # get would otherwise poll a healthy-looking ALIVE actor forever)
        dead = [r for r in rows
                if r.get("actor_id") in self._actor_ids
                and (r.get("state") in ("DEAD", "RESTARTING")
                     or (r.get("num_restarts") or 0)
                     > self._baseline_restarts.get(r.get("actor_id"), 0))]
        if dead:
            names = ", ".join(f"{r.get('class_name')}"
                              f"({r.get('actor_id', '')[:8]})" for r in dead)
            raise ActorDiedError(
                f"compiled DAG actor(s) died or restarted mid-execution "
                f"(compiled graphs do not survive actor restarts): {names} "
                f"({dead[0].get('death_cause') or dead[0].get('state')})")

    # -- teardown ------------------------------------------------------
    def teardown(self) -> None:
        """Release loops, channels, and segments.  Idempotent; never
        raises on a dead actor (its loop died with it)."""
        with self._lock:
            if self._torn_down:
                return
            self._torn_down = True
        import ray_tpu

        # getattr-guarded throughout: a compile that failed partway (a
        # locality/prepare round trip erroring) leaves later attributes
        # unset, and teardown must still release whatever DID get built
        # (actors, prepared loops, listeners, segments)
        my_channels = list(getattr(self, "_writers", {}).values())
        reader = getattr(self, "_reader", None)
        if reader is not None:
            my_channels.append(reader)
        # poison the driver's ends first: wakes every loop blocked on an
        # edge that touches the driver
        for ch in my_channels:
            try:
                ch.poison()
            except Exception:
                pass
        # poison every same-namespace shm edge by name — covers edges
        # between two actors whose writer died and can't poison for itself
        for e in getattr(self, "_edges", []):
            if e.get("kind") == "shm":
                try:
                    ch = ShmChannel.attach(e["name"])
                    ch.poison()
                    ch.close()
                except Exception:
                    pass
        refs = []
        for h in getattr(self, "actors", []):
            try:
                refs.append(h._submit_compiled_task(
                    _cdag_rpc, ("teardown", self._gid.encode()),
                    name="cdag.teardown"))
            except Exception:
                pass
        for r in refs:
            try:
                ray_tpu.get(r, timeout=10)
            except Exception:
                pass  # dead actor / torn control plane: loop died with it
        for ch in my_channels:
            try:
                ch.close()
            except Exception:
                pass
        from ray_tpu._private.shm import ShmSegment

        for e in getattr(self, "_edges", []):
            if e.get("kind") == "shm" and e["writer"] == DRIVER:
                ShmSegment.unlink(e["name"])
        _events.emit(_SOURCE, "graph torn down", entity_id=self._gid)

    def __del__(self):
        try:
            if not getattr(self, "_torn_down", True):
                self.teardown()
        except Exception:
            pass


def _contains_node(v) -> bool:
    from ray_tpu.dag.dag_node import DAGNode

    if isinstance(v, DAGNode):
        return True
    if isinstance(v, (list, tuple)):
        return any(_contains_node(x) for x in v)
    if isinstance(v, dict):
        return any(_contains_node(x) for x in v.values())
    return False
