"""ObjectRef — the future/handle for an object in the store.

Analog of the reference's binary ``ObjectID`` (``src/ray/common/id.h``) plus
the Python ``ObjectRef`` exposed by the Cython binding
(``python/ray/_raylet.pyx``).  IDs are 16 random bytes; task IDs embed a
per-task counter the way the reference embeds lineage in object IDs.
"""

from __future__ import annotations

import itertools
import os
import struct


class ObjectRef:
    # __weakref__ lets the runtime attach a finalizer per handle so garbage-
    # collected refs decrement the owner-side count (ReferenceCounter's
    # local-handle tracking seam).
    __slots__ = ("_id", "__weakref__")

    def __init__(self, id_bytes: bytes):
        assert isinstance(id_bytes, bytes) and len(id_bytes) == 16
        self._id = id_bytes

    @classmethod
    def random(cls) -> "ObjectRef":
        return cls(new_id())

    @classmethod
    def from_hex(cls, h: str) -> "ObjectRef":
        return cls(bytes.fromhex(h))

    def hex(self) -> str:
        return self._id.hex()

    def binary(self) -> bytes:
        return self._id

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def future(self):
        """concurrent.futures-style future resolving to the object's value."""
        import concurrent.futures

        import ray_tpu

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _wait():
            try:
                fut.set_result(ray_tpu.get(self))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        import threading

        threading.Thread(target=_wait, daemon=True).start()
        return fut

    def __reduce__(self):
        # Plain pickling path (e.g. inside nested containers serialized by
        # third-party code). The runtime's serializer also special-cases us
        # to track borrowed refs.
        return (ObjectRef, (self._id,))


class ObjectRefGenerator:
    """Stream of ObjectRefs from a ``num_returns="dynamic"`` task
    (reference ``python/ray/_private/worker.py:2924`` ObjectRefGenerator).

    Returned directly by ``.remote()`` on a dynamic task: iterating yields
    each value's ObjectRef AS THE TASK PRODUCES IT (streamed through the
    head's yield directory), so a consumer can start on the first block
    while later ones are still being generated.  ``ray_tpu.get`` of the
    task's terminal return gives the materialized (list-backed) form.
    """

    def __init__(self, refs=None, task_id: bytes = None, primary=None):
        self._refs = list(refs) if refs is not None else None
        self._task_id = task_id
        self._primary = primary  # terminal return: errors surface via get

    def __iter__(self):
        if self._refs is not None:
            return iter(self._refs)
        return self._stream()

    def __len__(self):
        if self._refs is None:
            raise TypeError("length unknown until the task finishes; "
                            "iterate, or get() the materialized generator")
        return len(self._refs)

    def _stream(self):
        import ray_tpu
        from ray_tpu._private.worker import global_worker
        from ray_tpu.exceptions import WorkerCrashedError

        seen = 0
        attempt = 0
        while True:
            # long-poll: the head parks this request until a new yield
            # lands, the task ends, or ~20s pass (no busy polling)
            reply = global_worker.client.request({
                "type": "dynamic_yields", "task_id": self._task_id,
                "after": seen, "attempt": attempt,
            }, timeout=300)["value"]
            if reply.get("attempt", 0) != attempt:
                if seen:
                    # a retry re-yields from the start; duplicates must not
                    # flow into a half-consumed stream
                    raise WorkerCrashedError(
                        "dynamic-return task was retried mid-stream; "
                        "restart the iteration")
                attempt = reply.get("attempt", 0)
            for oid in reply["oids"]:
                seen += 1
                yield global_worker.track_ref(ObjectRef(oid), owned=False)
            if reply["done"] and not reply["oids"]:
                if self._primary is not None:
                    # raises the task's error, if it failed mid-stream;
                    # also recovers yields the head may have pruned
                    gen = ray_tpu.get(self._primary)
                    self._refs = gen._refs
                    for r in (gen._refs or [])[seen:]:
                        yield r
                return

    def completed(self):
        """ObjectRef of the terminal return (sealed when the task ends)."""
        return self._primary

    def __reduce__(self):
        return (ObjectRefGenerator, (self._refs, self._task_id, self._primary))


# IDs are a per-process random prefix + a monotonically increasing counter
# (the reference also derives object IDs from the task counter, id.h).  One
# urandom syscall per PROCESS instead of per id — new_id was the single
# hottest driver-side frame in a submission wave.  ``next()`` on an
# itertools.count is a single C call, so it is atomic under the GIL.
_prefix: bytes = os.urandom(8)
_counter = itertools.count(1)


def _reseed_after_fork() -> None:
    global _prefix, _counter
    _prefix = os.urandom(8)  # raylint: disable=R3 (one-shot, off the per-task path)
    _counter = itertools.count(1)


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reseed_after_fork)


def new_id(n: int = 16) -> bytes:
    if n != 16:
        return os.urandom(n)  # raylint: disable=R3 (rare non-16-byte ids)
    return _prefix + struct.pack(">Q", next(_counter))
