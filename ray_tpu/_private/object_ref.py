"""ObjectRef — the future/handle for an object in the store.

Analog of the reference's binary ``ObjectID`` (``src/ray/common/id.h``) plus
the Python ``ObjectRef`` exposed by the Cython binding
(``python/ray/_raylet.pyx``).  IDs are 16 random bytes; task IDs embed a
per-task counter the way the reference embeds lineage in object IDs.
"""

from __future__ import annotations

import itertools
import os
import struct


class ObjectRef:
    # __weakref__ lets the runtime attach a finalizer per handle so garbage-
    # collected refs decrement the owner-side count (ReferenceCounter's
    # local-handle tracking seam).
    __slots__ = ("_id", "__weakref__")

    def __init__(self, id_bytes: bytes):
        assert isinstance(id_bytes, bytes) and len(id_bytes) == 16
        self._id = id_bytes

    @classmethod
    def random(cls) -> "ObjectRef":
        return cls(new_id())

    @classmethod
    def from_hex(cls, h: str) -> "ObjectRef":
        return cls(bytes.fromhex(h))

    def hex(self) -> str:
        return self._id.hex()

    def binary(self) -> bytes:
        return self._id

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def future(self):
        """concurrent.futures-style future resolving to the object's value."""
        import concurrent.futures

        import ray_tpu

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _wait():
            try:
                fut.set_result(ray_tpu.get(self))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        import threading

        threading.Thread(target=_wait, daemon=True).start()
        return fut

    def __reduce__(self):
        # Plain pickling path (e.g. inside nested containers serialized by
        # third-party code). The runtime's serializer also special-cases us
        # to track borrowed refs.
        return (ObjectRef, (self._id,))


# IDs are a per-process random prefix + a monotonically increasing counter
# (the reference also derives object IDs from the task counter, id.h).  One
# urandom syscall per PROCESS instead of per id — new_id was the single
# hottest driver-side frame in a submission wave.  ``next()`` on an
# itertools.count is a single C call, so it is atomic under the GIL.
_prefix: bytes = os.urandom(8)
_counter = itertools.count(1)


def _reseed_after_fork() -> None:
    global _prefix, _counter
    _prefix = os.urandom(8)
    _counter = itertools.count(1)


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reseed_after_fork)


def new_id(n: int = 16) -> bytes:
    if n != 16:
        return os.urandom(n)
    return _prefix + struct.pack(">Q", next(_counter))
