"""runtime_env URI packaging: ship local code to every node.

Reference counterpart: ``python/ray/_private/runtime_env/packaging.py``
(zip local dirs into content-addressed packages, upload to the GCS KV,
download+extract into a node-local cache) and ``uri_cache.py`` (the
size-capped cache GC).

Flow:

- driver: ``prepare_runtime_env`` rewrites ``working_dir``/``py_modules``
  local paths into ``gcs://pkg-<sha1>.zip`` URIs, uploading each zip to
  the head KV (namespace ``pkg``) once — content addressing dedups
  re-submits of the same tree.
- worker: ``ensure_package_local`` downloads + extracts a URI into
  ``$RAY_TPU_RUNTIME_ENV_DIR/pkg-<sha1>/`` exactly once per node
  (fcntl-serialized, ``.ready``-marked, same pattern as the pip venv
  cache), then the worker chdirs into it (working_dir) or prepends it to
  ``sys.path`` (py_modules).

Zips are deterministic (sorted entries, zeroed timestamps) so the same
tree always produces the same URI.
"""

from __future__ import annotations

import fcntl
import fnmatch
import hashlib
import io
import os
import shutil
import time
import zipfile
from typing import Callable, Dict, List, Optional, Tuple

DEFAULT_BASE_DIR = "/tmp/ray_tpu/runtime_envs"
PKG_URI_PREFIX = "gcs://"
PKG_KV_NAMESPACE = "pkg"

# Always excluded from packages, on top of runtime_env["excludes"].
_DEFAULT_EXCLUDES = ("__pycache__", "*.pyc", ".git", ".hg", ".DS_Store")

_SIZE_LIMIT = int(os.environ.get("RAY_TPU_PKG_SIZE_LIMIT",
                                 256 * 1024 * 1024))
_CACHE_LIMIT = int(os.environ.get("RAY_TPU_PKG_CACHE_LIMIT",
                                  10 * 1024 * 1024 * 1024))


def is_package_uri(s: object) -> bool:
    return isinstance(s, str) and s.startswith(PKG_URI_PREFIX)


def _excluded(rel: str, patterns: Tuple[str, ...]) -> bool:
    parts = rel.split(os.sep)
    for pat in patterns:
        if any(fnmatch.fnmatch(p, pat) for p in parts):
            return True
        if fnmatch.fnmatch(rel, pat):
            return True
    return False


def zip_directory(path: str, *, top_level: bool,
                  excludes: Tuple[str, ...] = ()) -> bytes:
    """Deterministically zip ``path``.  ``top_level=False`` puts the
    directory's CONTENTS at the zip root (working_dir semantics: extract
    and chdir in); ``top_level=True`` keeps ``basename(path)/`` as the
    root (py_modules semantics: the extract dir goes on sys.path and
    ``import basename`` works)."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(
            f"runtime_env package path {path!r} does not exist "
            f"(deleted between validation and submission?)")
    patterns = _DEFAULT_EXCLUDES + tuple(excludes)
    prefix = os.path.basename(path.rstrip(os.sep)) if top_level else ""
    entries: List[Tuple[str, str]] = []  # (arcname, fs path)
    total = 0
    for root, dirs, files in os.walk(path):
        rel_root = os.path.relpath(root, path)
        rel_root = "" if rel_root == "." else rel_root
        dirs[:] = sorted(d for d in dirs
                         if not _excluded(os.path.join(rel_root, d), patterns))
        for f in sorted(files):
            rel = os.path.join(rel_root, f) if rel_root else f
            if _excluded(rel, patterns):
                continue
            fs = os.path.join(root, f)
            if not os.path.isfile(fs):
                continue  # sockets/fifos/broken symlinks don't package
            total += os.path.getsize(fs)
            if total > _SIZE_LIMIT:
                raise ValueError(
                    f"runtime_env package {path!r} exceeds the "
                    f"{_SIZE_LIMIT >> 20} MiB limit "
                    f"(RAY_TPU_PKG_SIZE_LIMIT to raise); add 'excludes' "
                    f"patterns for data/checkpoint directories")
            entries.append((os.path.join(prefix, rel) if prefix else rel, fs))
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for arc, fs in entries:
            info = zipfile.ZipInfo(arc, date_time=(1980, 1, 1, 0, 0, 0))
            info.external_attr = (os.stat(fs).st_mode & 0o777) << 16
            info.compress_type = zipfile.ZIP_DEFLATED
            with open(fs, "rb") as f:
                zf.writestr(info, f.read())
    return buf.getvalue()


def package_uri(blob: bytes) -> str:
    return f"{PKG_URI_PREFIX}pkg-{hashlib.sha1(blob).hexdigest()[:20]}.zip"


def upload_package_if_needed(client, path_or_zip: str, *, top_level: bool,
                             excludes: Tuple[str, ...] = ()) -> str:
    """Zip (or read) a local path, upload to the head KV once, return the
    content-addressed URI."""
    if os.path.isfile(path_or_zip) and path_or_zip.endswith(".zip"):
        with open(path_or_zip, "rb") as f:
            blob = f.read()
        if len(blob) > _SIZE_LIMIT:
            raise ValueError(
                f"{path_or_zip!r} exceeds the {_SIZE_LIMIT >> 20} MiB "
                f"package limit")
    else:
        blob = zip_directory(path_or_zip, top_level=top_level,
                             excludes=excludes)
    uri = package_uri(blob)
    key = uri.encode()
    # probe a tiny side marker, not the blob itself — the dedup check for
    # an already-uploaded 100+ MiB package must not pull it back over the
    # control socket just to discard it
    meta_key = key + b".meta"
    if client.kv_get(PKG_KV_NAMESPACE, meta_key, timeout=60) is None:
        client.kv_put(PKG_KV_NAMESPACE, key, blob)
        client.kv_put(PKG_KV_NAMESPACE, meta_key,
                      str(len(blob)).encode())  # blob first: meta implies blob
    return uri


def _pin_name(pid: Optional[int], suffix: Optional[str]) -> str:
    # name shape: .pin-<pid>[-<suffix>] — the pid governs liveness; the
    # suffix distinguishes concurrent consumers INSIDE one process (two
    # job submits in the head sharing a package must not share one pin
    # file, or the first unpin strips the other's protection)
    name = f".pin-{pid or os.getpid()}"
    return f"{name}-{suffix}" if suffix else name


def _pin(dest: str, pid: Optional[int] = None,
         suffix: Optional[str] = None) -> None:
    """Mark ``dest`` in use by ``pid`` (default: this process).  GC
    skips packages with any live pin, so a long-lived worker's
    cwd/sys.path entry can't be evicted out from under it.  Pins are
    pid-named: a dead process's pin is ignored (checked against
    /proc)."""
    try:
        open(os.path.join(dest, _pin_name(pid, suffix)), "w").close()
    except OSError:
        pass


def repin(dest: str, pid: int, suffix: Optional[str] = None) -> None:
    """Transfer this process's pin (``suffix``-scoped) to ``pid`` — used
    by the head after launching a job driver whose cwd/PYTHONPATH is the
    package: the package then lives exactly as long as the job
    process."""
    _pin(dest, pid)
    unpin(dest, suffix=suffix)


def unpin(dest: str, pid: Optional[int] = None,
          suffix: Optional[str] = None) -> None:
    try:
        os.unlink(os.path.join(dest, _pin_name(pid, suffix)))
    except OSError:
        pass


def ensure_package_local(fetch: Callable[[str], Optional[bytes]], uri: str,
                         base_dir: str = DEFAULT_BASE_DIR, *,
                         pin_suffix: Optional[str] = None) -> str:
    """Download + extract ``uri`` into the node-local cache; returns the
    extracted directory, pinned for this process (``pin_suffix`` scopes
    the pin when one process holds several concurrent consumers).  Safe
    under concurrent workers (flock + .ready, the pip-venv cache
    pattern)."""
    name = uri[len(PKG_URI_PREFIX):].removesuffix(".zip")
    dest = os.path.join(base_dir, name)
    ready = os.path.join(dest, ".ready")
    os.makedirs(base_dir, exist_ok=True)
    # pin + check happen UNDER the per-package flock — GC deletes under
    # the same lock after re-verifying pins, so a package can never
    # vanish between this check and a consumer using it.  ensure runs
    # once per worker boot; the serialization is noise next to spawn.
    with open(os.path.join(base_dir, f"{name}.lock"), "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if os.path.exists(ready):
                _pin(dest, suffix=pin_suffix)
                os.utime(ready)  # LRU touch
                return dest
            blob = fetch(uri)
            if blob is None:
                raise RuntimeError(
                    f"runtime_env package {uri} not found in the cluster KV "
                    f"(head restarted since the driver uploaded it?)")
            shutil.rmtree(dest, ignore_errors=True)  # partial extract
            extracted_size = 0
            with zipfile.ZipFile(io.BytesIO(blob)) as zf:
                zf.extractall(dest)
                # zipfile.extractall drops external_attr: restore modes
                # so executables keep their exec bit on the worker
                for info in zf.infolist():
                    extracted_size += info.file_size
                    mode = (info.external_attr >> 16) & 0o777
                    if mode:
                        try:
                            os.chmod(os.path.join(dest, info.filename), mode)
                        except OSError:
                            pass
            os.makedirs(dest, exist_ok=True)  # empty package: no entries
            _pin(dest, suffix=pin_suffix)
            with open(ready, "w") as f:
                # EXTRACTED size (what the cache cap governs), not the
                # compressed blob size — cheap GC accounting
                f.write(str(extracted_size))
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)
    _gc_cache(base_dir)
    return dest


def _is_pinned(full: str) -> bool:
    """A package is pinned while any pinning process is still alive."""
    try:
        for f in os.listdir(full):
            if f.startswith(".pin-"):
                pid = f[len(".pin-"):].split("-", 1)[0]
                if pid.isdigit() and os.path.exists(f"/proc/{pid}"):
                    return True
                try:  # stale pin from a dead process: clean it up
                    os.unlink(os.path.join(full, f))
                except OSError:
                    pass
    except OSError:
        pass
    return False


def _gc_cache(base_dir: str, limit: int = 0) -> None:
    """Evict least-recently-used extracted packages beyond the cache cap
    (reference uri_cache.py).  Only unpinned ``pkg-*`` dirs with a
    ``.ready`` marker are candidates — in-flight extractions hold the
    lock, live consumers hold pid pins."""
    limit = limit or _CACHE_LIMIT
    try:
        cands = []
        total = 0
        for d in os.listdir(base_dir):
            if not d.startswith("pkg-"):
                continue
            full = os.path.join(base_dir, d)
            ready = os.path.join(full, ".ready")
            if not os.path.exists(ready):
                continue
            try:  # extract-time size lives in .ready — no tree walk
                size = int(open(ready).read() or 0)
            except (OSError, ValueError):
                size = sum(
                    os.path.getsize(os.path.join(r, f))
                    for r, _, fs in os.walk(full) for f in fs
                    if os.path.isfile(os.path.join(r, f)))
            total += size
            if _is_pinned(full):
                continue
            cands.append((os.path.getmtime(ready), full, size))
        cands.sort()
        while total > limit and cands:
            _, victim, size = cands.pop(0)
            # take the same per-package flock ensure_package_local holds
            # and RE-verify pins under it: a worker on the fast path pins
            # then re-checks .ready, so deleting only unpinned packages
            # while holding the lock closes the pin/scan race
            lock_path = os.path.join(base_dir,
                                     os.path.basename(victim) + ".lock")
            try:
                with open(lock_path, "w") as lock:
                    fcntl.flock(lock, fcntl.LOCK_EX)
                    try:
                        if _is_pinned(victim):
                            continue
                        shutil.rmtree(victim, ignore_errors=True)
                    finally:
                        fcntl.flock(lock, fcntl.LOCK_UN)
            except OSError:
                continue
            total -= size
    except OSError:
        pass  # cache GC is best-effort


# ---------------------------------------------------------------------------
# driver-side rewrite

def prepare_runtime_env(runtime_env: Optional[dict],
                        client) -> Optional[dict]:
    """Rewrite local ``working_dir``/``py_modules`` paths to uploaded
    package URIs (reference ``upload_package_if_needed`` call sites in
    ``runtime_env/working_dir.py`` / ``py_modules.py``).  Already-URI
    values pass through, so specs survive resubmission (retries, Tune
    trials) without re-uploading."""
    if not runtime_env:
        return runtime_env
    wd = runtime_env.get("working_dir")
    mods = runtime_env.get("py_modules")
    if not (isinstance(wd, str) and not is_package_uri(wd)) and not any(
            isinstance(m, str) and not is_package_uri(m)
            for m in (mods or ())):
        return runtime_env
    excludes = tuple(runtime_env.get("excludes") or ())
    out: Dict[str, object] = dict(runtime_env)
    if isinstance(wd, str) and not is_package_uri(wd):
        out["working_dir"] = upload_package_if_needed(
            client, wd, top_level=False, excludes=excludes)
    if mods:
        out["py_modules"] = [
            m if is_package_uri(m) else upload_package_if_needed(
                client, m, top_level=True, excludes=excludes)
            for m in mods
        ]
    return out


# ---------------------------------------------------------------------------
# worker-side resolution

def apply_packages_in_worker(client) -> None:
    """Materialize this worker's package URIs (``RAY_TPU_RUNTIME_ENV``,
    set at spawn): extract + chdir for working_dir, extract + sys.path
    prepend for py_modules.  Runs in worker main right after
    registration, before any task executes."""
    import json
    import sys

    blob = os.environ.get("RAY_TPU_RUNTIME_ENV")
    if not blob:
        return
    try:
        env = json.loads(blob)
    except ValueError:
        return
    base = os.environ.get("RAY_TPU_RUNTIME_ENV_DIR", DEFAULT_BASE_DIR)

    def fetch(uri: str) -> Optional[bytes]:
        return client.kv_get(PKG_KV_NAMESPACE, uri.encode(), timeout=120)

    for m in reversed(env.get("py_modules") or []):
        if is_package_uri(m):
            p = ensure_package_local(fetch, m, base)
            if p not in sys.path:
                sys.path.insert(0, p)
    wd = env.get("working_dir")
    if is_package_uri(wd):
        p = ensure_package_local(fetch, wd, base)
        os.chdir(p)
        if p not in sys.path:
            sys.path.insert(0, p)  # reference working_dir is importable
