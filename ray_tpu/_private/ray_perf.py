"""Core microbenchmark harness — the ``ray_perf.py`` analog.

Mirrors the reference's microbenchmark surface
(``/root/reference/python/ray/_private/ray_perf.py:93`` ``main`` — timed
put/get, task and actor call throughput, run by
``release/microbenchmark/run_microbenchmark.py``): these numbers are the
core runtime's regression surface (BASELINE.md).  Run as a module to print
one JSON object per metric and write ``BENCH_core.json`` at the repo root:

    python -m ray_tpu._private.ray_perf [--quick]
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List

import numpy as np

import ray_tpu


def timeit(name: str, fn: Callable[[], Any], multiplier: int = 1,
           min_time_s: float = 1.0, results: List[Dict] | None = None) -> Dict:
    """Run ``fn`` repeatedly for ~min_time_s; report ops/s (x multiplier)."""
    fn()  # warmup
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < min_time_s:
        fn()
        count += 1
    dt = time.perf_counter() - start
    rate = count * multiplier / dt
    rec = {"metric": name, "value": round(rate, 2), "unit": "ops/s"}
    print(json.dumps(rec), flush=True)
    if results is not None:
        results.append(rec)
    return rec


def main(quick: bool = False) -> List[Dict]:
    """All core microbenchmarks on a local node.  ``quick`` shrinks the
    large-object sizes and iteration floors for CI."""
    results: List[Dict] = []
    min_t = 0.3 if quick else 1.0
    ray_tpu.init(num_cpus=4, num_tpus=0)
    try:
        # settle: the prestarted worker pool boots concurrently with init;
        # benching against half-booted interpreters starves them of CPU
        # and skews every number
        from ray_tpu._private.worker import global_worker as _gw0

        deadline = time.time() + 20
        while time.time() < deadline:
            rows = _gw0.client.request(
                {"type": "list_state", "what": "workers"}, timeout=10
            )["value"]
            if sum(1 for r in rows if r.get("state") in ("idle", "busy")) >= 4:
                break
            time.sleep(0.3)
        # -------------------------------------------------- put/get small
        small = b"x" * 1024

        def put_small():
            ray_tpu.put(small)

        timeit("put_small_1kb", put_small, min_time_s=min_t, results=results)

        ref_small = ray_tpu.put(small)

        def get_small():
            ray_tpu.get(ref_small)

        timeit("get_small_1kb", get_small, min_time_s=min_t, results=results)

        # ------------------------------------------------- put/get large
        mb = 64 if quick else 256
        arr = np.random.default_rng(0).integers(0, 255, mb << 20, dtype=np.uint8)
        # warmup put/free: the steady-state number is what matters — the
        # arena recycles freed pages, so only the first-ever put pays the
        # kernel's fault-and-zero cost
        import gc

        warm = ray_tpu.put(arr)
        del warm
        gc.collect()
        from ray_tpu._private.worker import global_worker as _gw

        _gw.flush_removals()
        time.sleep(0.2)
        t0 = time.perf_counter()
        ref_big = ray_tpu.put(arr)
        put_dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = ray_tpu.get(ref_big)
        get_dt = time.perf_counter() - t0
        assert out.nbytes == arr.nbytes
        del out
        for name, dt in (("put", put_dt), ("get", get_dt)):
            rec = {"metric": f"{name}_numpy_{mb}mb_gbps",
                   "value": round(mb / 1024 / dt, 3), "unit": "GiB/s"}
            print(json.dumps(rec), flush=True)
            results.append(rec)
        del ref_big

        # -------------------------------------------------- tasks
        @ray_tpu.remote
        def noop():
            return None

        # single in-flight round trip (scheduler+dispatch+seal latency)
        def task_rt():
            ray_tpu.get(noop.remote(), timeout=60)

        timeit("task_round_trip", task_rt, min_time_s=min_t, results=results)

        # pipelined wave (throughput with the pool warm).  Worker boot is
        # ~2s on a small host while one wave is ~100ms, so ramp the pool
        # with un-timed waves first — otherwise the window measures 1-3
        # workers with 2 still booting and underreports ~3x.
        wave = 20 if quick else 100

        def task_wave():
            ray_tpu.get([noop.remote() for _ in range(wave)], timeout=120)

        ramp_until = time.perf_counter() + (1.0 if quick else 3.0)
        while time.perf_counter() < ramp_until:
            task_wave()
        timeit("task_throughput", task_wave, multiplier=wave,
               min_time_s=min_t, results=results)

        # -------------------------------------------------- actors
        @ray_tpu.remote
        class Echo:
            def ping(self):
                return None

        a = Echo.remote()
        ray_tpu.get(a.ping.remote(), timeout=60)

        def actor_rt():
            ray_tpu.get(a.ping.remote(), timeout=60)

        timeit("actor_call_round_trip", actor_rt, min_time_s=min_t, results=results)

        def actor_wave():
            ray_tpu.get([a.ping.remote() for _ in range(wave)], timeout=120)

        timeit("actor_call_throughput", actor_wave, multiplier=wave,
               min_time_s=min_t, results=results)

        # threaded actor: pipelined calls overlap worker-side
        @ray_tpu.remote(max_concurrency=8)
        class EchoMC:
            def ping(self):
                return None

        mc = EchoMC.remote()
        ray_tpu.get(mc.ping.remote(), timeout=60)

        def actor_mc_wave():
            ray_tpu.get([mc.ping.remote() for _ in range(wave)], timeout=120)

        timeit("threaded_actor_call_throughput", actor_mc_wave, multiplier=wave,
               min_time_s=min_t, results=results)

        # -------------------------------------------------- data ingest
        from ray_tpu import data as rd

        mb_data = 32 if quick else 128
        arr2 = np.random.default_rng(1).standard_normal((mb_data << 20) // 8)
        ds = rd.from_numpy(arr2, parallelism=8)
        ds.materialize()
        t0 = time.perf_counter()
        seen = 0
        for batch in ds.iter_batches(batch_size=1 << 16, prefetch_blocks=3):
            seen += np.asarray(batch).nbytes
        dt = time.perf_counter() - t0
        rec = {"metric": f"data_iter_batches_{mb_data}mb_gbps",
               "value": round(seen / (1 << 30) / dt, 3), "unit": "GiB/s"}
        print(json.dumps(rec), flush=True)
        results.append(rec)

        # -------------------------------------------------- wait
        refs = [noop.remote() for _ in range(8)]
        ray_tpu.get(refs, timeout=60)

        def do_wait():
            ray_tpu.wait(refs, num_returns=len(refs), timeout=60)

        timeit("wait_8_ready", do_wait, min_time_s=min_t, results=results)
    finally:
        ray_tpu.shutdown()

    # ---------------------------------------------------- broadcast (1->N)
    # real-process 2-agent cluster: disjoint shm namespaces force the
    # copies through the object plane (PushManager fan-out analog)
    from ray_tpu import experimental
    from ray_tpu.cluster_utils import Cluster

    mb = 16 if quick else 64
    cluster = Cluster(
        initialize_head=True,
        head_node_args={"num_cpus": 2, "num_tpus": 0},
        real_processes=True,
    )
    try:
        for _ in range(2):
            cluster.add_node(num_cpus=1)
        arr = np.random.default_rng(2).integers(0, 255, mb << 20, dtype=np.uint8)
        ref = ray_tpu.put(arr)
        t0 = time.perf_counter()
        out = experimental.broadcast_object(ref, timeout=300)
        dt = time.perf_counter() - t0
        assert out["replicas"] == 2, out
        rec = {"metric": f"broadcast_{mb}mb_to_2_nodes_gbps",
               "value": round(mb * 2 / 1024 / dt, 3), "unit": "GiB/s"}
        print(json.dumps(rec), flush=True)
        results.append(rec)
    finally:
        cluster.shutdown()
    return results


if __name__ == "__main__":
    import argparse
    import os

    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true")
    p.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "BENCH_core.json"))
    args = p.parse_args()
    res = main(quick=args.quick)
    with open(args.out, "w") as f:
        json.dump({"benchmarks": res, "host": "single-node"}, f, indent=2)
    print(f"wrote {args.out}")
