"""Core microbenchmark harness — the ``ray_perf.py`` analog.

Mirrors the reference's microbenchmark surface
(``/root/reference/python/ray/_private/ray_perf.py:93`` ``main`` — timed
put/get, task and actor call throughput, run by
``release/microbenchmark/run_microbenchmark.py``): these numbers are the
core runtime's regression surface (BASELINE.md).  Run as a module to print
one JSON object per metric and write ``BENCH_core.json`` at the repo root:

    python -m ray_tpu._private.ray_perf [--quick]
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List

import numpy as np

import ray_tpu


def timeit(name: str, fn: Callable[[], Any], multiplier: int = 1,
           min_time_s: float = 1.0, results: List[Dict] | None = None) -> Dict:
    """Run ``fn`` repeatedly for ~min_time_s; report ops/s (x multiplier)."""
    fn()  # warmup
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < min_time_s:
        fn()
        count += 1
    dt = time.perf_counter() - start
    rate = count * multiplier / dt
    rec = {"metric": name, "value": round(rate, 2), "unit": "ops/s"}
    print(json.dumps(rec), flush=True)
    if results is not None:
        results.append(rec)
    return rec


def main(quick: bool = False) -> List[Dict]:
    """All core microbenchmarks on a local node.  ``quick`` shrinks the
    large-object sizes and iteration floors for CI."""
    results: List[Dict] = []
    min_t = 0.3 if quick else 1.0
    ray_tpu.init(num_cpus=4, num_tpus=0)
    try:
        # settle: the prestarted worker pool boots concurrently with init;
        # benching against half-booted interpreters starves them of CPU
        # and skews every number
        from ray_tpu._private.worker import global_worker as _gw0

        deadline = time.time() + 20
        while time.time() < deadline:
            rows = _gw0.client.request(
                {"type": "list_state", "what": "workers"}, timeout=10
            )["value"]
            if sum(1 for r in rows if r.get("state") in ("idle", "busy")) >= 4:
                break
            time.sleep(0.3)
        # -------------------------------------------------- put/get small
        small = b"x" * 1024

        def put_small():
            ray_tpu.put(small)

        timeit("put_small_1kb", put_small, min_time_s=min_t, results=results)

        ref_small = ray_tpu.put(small)

        def get_small():
            ray_tpu.get(ref_small)

        timeit("get_small_1kb", get_small, min_time_s=min_t, results=results)

        # ------------------------------------------------- put/get large
        mb = 64 if quick else 256
        arr = np.random.default_rng(0).integers(0, 255, mb << 20, dtype=np.uint8)
        # warmup put/free: the steady-state number is what matters — the
        # arena recycles freed pages, so only the first-ever put pays the
        # kernel's fault-and-zero cost
        import gc

        warm = ray_tpu.put(arr)
        del warm
        gc.collect()
        from ray_tpu._private.worker import global_worker as _gw

        _gw.flush_removals()
        time.sleep(0.2)
        t0 = time.perf_counter()
        ref_big = ray_tpu.put(arr)
        put_dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = ray_tpu.get(ref_big)
        get_dt = time.perf_counter() - t0
        assert out.nbytes == arr.nbytes
        del out
        for name, dt in (("put", put_dt), ("get", get_dt)):
            rec = {"metric": f"{name}_numpy_{mb}mb_gbps",
                   "value": round(mb / 1024 / dt, 3), "unit": "GiB/s"}
            print(json.dumps(rec), flush=True)
            results.append(rec)
        del ref_big

        # -------------------------------------------------- tasks
        @ray_tpu.remote
        def noop():
            return None

        # single in-flight round trip (scheduler+dispatch+seal latency)
        def task_rt():
            ray_tpu.get(noop.remote(), timeout=60)

        timeit("task_round_trip", task_rt, min_time_s=min_t, results=results)

        # pipelined wave (throughput with the pool warm).  Worker boot is
        # ~2s on a small host while one wave is ~100ms, so ramp the pool
        # with un-timed waves first — otherwise the window measures 1-3
        # workers with 2 still booting and underreports ~3x.
        wave = 20 if quick else 100

        def task_wave():
            ray_tpu.get([noop.remote() for _ in range(wave)], timeout=120)

        ramp_until = time.perf_counter() + (1.0 if quick else 3.0)
        while time.perf_counter() < ramp_until:
            task_wave()
        timeit("task_throughput", task_wave, multiplier=wave,
               min_time_s=min_t, results=results)

        # -------------------------------------------------- actors
        @ray_tpu.remote
        class Echo:
            def ping(self):
                return None

        a = Echo.remote()
        ray_tpu.get(a.ping.remote(), timeout=60)

        def actor_rt():
            ray_tpu.get(a.ping.remote(), timeout=60)

        timeit("actor_call_round_trip", actor_rt, min_time_s=min_t, results=results)

        def actor_wave():
            ray_tpu.get([a.ping.remote() for _ in range(wave)], timeout=120)

        timeit("actor_call_throughput", actor_wave, multiplier=wave,
               min_time_s=min_t, results=results)

        # threaded actor: pipelined calls overlap worker-side
        @ray_tpu.remote(max_concurrency=8)
        class EchoMC:
            def ping(self):
                return None

        mc = EchoMC.remote()
        ray_tpu.get(mc.ping.remote(), timeout=60)

        def actor_mc_wave():
            ray_tpu.get([mc.ping.remote() for _ in range(wave)], timeout=120)

        timeit("threaded_actor_call_throughput", actor_mc_wave, multiplier=wave,
               min_time_s=min_t, results=results)

        # -------------------------------------------------- data ingest
        from ray_tpu import data as rd

        mb_data = 32 if quick else 128
        arr2 = np.random.default_rng(1).standard_normal((mb_data << 20) // 8)
        ds = rd.from_numpy(arr2, parallelism=8)
        ds.materialize()
        t0 = time.perf_counter()
        seen = 0
        for batch in ds.iter_batches(batch_size=1 << 16, prefetch_blocks=3):
            seen += np.asarray(batch).nbytes
        dt = time.perf_counter() - t0
        rec = {"metric": f"data_iter_batches_{mb_data}mb_gbps",
               "value": round(seen / (1 << 30) / dt, 3), "unit": "GiB/s"}
        print(json.dumps(rec), flush=True)
        results.append(rec)

        # -------------------------------------------------- wait
        refs = [noop.remote() for _ in range(8)]
        ray_tpu.get(refs, timeout=60)

        def do_wait():
            ray_tpu.wait(refs, num_returns=len(refs), timeout=60)

        timeit("wait_8_ready", do_wait, min_time_s=min_t, results=results)

        # ------------------------------------ watchdog tick (head-local)
        # one full evaluation pass — incremental doctor + trend queries +
        # SLO burn-rate — against the head this run just loaded with
        # tasks/actors/events.  ops/s so bench --check gates it: a
        # full-table pull sneaking back into the tick path shows up as a
        # step-function drop here.
        from ray_tpu._private.worker import global_worker as _gw

        wd = getattr(_gw.node, "watchdog", None)
        if wd is not None:
            wd.tick()  # warm the event cursors / doctor window
            timeit("watchdog_tick", wd.tick, min_time_s=min_t,
                   results=results)
    finally:
        ray_tpu.shutdown()

    # ---------------------------------------------------- broadcast (1->N)
    # real-process 2-agent cluster, measured both ways: the socket object
    # plane (RAY_TPU_FORCE_REMOTE_PULL=1 — what distinct hosts would see,
    # sendfile -> mmap) and the same-host copy_file_range fast path
    # (PushManager fan-out analog either way)
    import os as _os

    from ray_tpu import experimental
    from ray_tpu.cluster_utils import Cluster

    mb = 16 if quick else 64
    for forced, suffix in ((True, ""), (False, "_samehost")):
        if forced:
            _os.environ["RAY_TPU_FORCE_REMOTE_PULL"] = "1"
        else:
            _os.environ.pop("RAY_TPU_FORCE_REMOTE_PULL", None)
        cluster = Cluster(
            initialize_head=True,
            head_node_args={"num_cpus": 2, "num_tpus": 0},
            real_processes=True,
        )
        try:
            for _ in range(2):
                cluster.add_node(num_cpus=1)
            arr = np.random.default_rng(2).integers(
                0, 255, mb << 20, dtype=np.uint8)
            ref = ray_tpu.put(arr)
            t0 = time.perf_counter()
            out = experimental.broadcast_object(ref, timeout=300)
            dt = time.perf_counter() - t0
            assert out["replicas"] == 2, out
            rec = {"metric": f"broadcast_{mb}mb_to_2_nodes{suffix}_gbps",
                   "value": round(mb * 2 / 1024 / dt, 3), "unit": "GiB/s"}
            print(json.dumps(rec), flush=True)
            results.append(rec)
        finally:
            cluster.shutdown()
    return results


def scale_envelope(quick: bool = False) -> List[Dict]:
    """Scalability-envelope proofs — the reference publishes these for its
    release qualification (``release/benchmarks/README.md:8-31``: queued
    tasks per node, live actors, large ``ray.get``, object spilling).
    Sizes scale to a single small host; each scenario records what was
    actually achieved."""
    import gc
    import os as _os

    results: List[Dict] = []

    def record(rec):
        print(json.dumps(rec), flush=True)
        results.append(rec)

    # --------------------------- queued tasks (100k and 1M), 1 node
    # BOTH phases under ONE contention regime: dispatch/drain runs
    # concurrently with submission from first submit to last completion
    # (the old row measured submit against a concurrent drain but drain
    # after submit had finished — drain_ops_s was flattered ~15x by the
    # work already done during the submit wall).  sustained_ops_s is the
    # honest end-to-end number: n_tasks over first-submit -> last-
    # completion, plus bucket-estimated p50/p99 dispatch latency from
    # the head's scheduler histogram.
    def queued_tasks_row(n_tasks: int, label: str):
        ray_tpu.init(num_cpus=4, num_tpus=0)
        try:
            @ray_tpu.remote
            def noop():
                return None

            ray_tpu.get([noop.remote() for _ in range(50)], timeout=120)
            t0 = time.perf_counter()
            refs = [noop.remote() for _ in range(n_tasks)]
            submit_dt = time.perf_counter() - t0
            for i in range(0, n_tasks, 5000):
                ray_tpu.get(refs[i:i + 5000], timeout=3600)
            total_dt = time.perf_counter() - t0
            from ray_tpu._private.worker import global_worker

            node = global_worker.node
            lat = node._merged_histogram_summary(
                node._merged_metrics_snapshot(),
                "ray_tpu_sched_dispatch_latency_s") or {}
            record({"metric": label, "value": n_tasks, "unit": "tasks",
                    "submit_ops_s": round(n_tasks / submit_dt, 1),
                    "sustained_ops_s": round(n_tasks / total_dt, 1),
                    "drain_wall_s": round(total_dt - submit_dt, 1),
                    "dispatch_p50_est_s": lat.get("p50_est_s"),
                    "dispatch_p99_est_s": lat.get("p99_est_s")})
            del refs
        finally:
            ray_tpu.shutdown()

    queued_tasks_row(10_000 if quick else 100_000,
                     "queued_tasks_10k" if quick else "queued_tasks_100k")
    if not quick:
        # the reference-bar row: 1M queued tasks through one head
        # (release/benchmarks' many_tasks), target >=10k sustained ops/s
        queued_tasks_row(1_000_000, "queued_tasks_1m")

    # --------------------------- typed-wire overhead on task_throughput
    # the proto arm (packed hot-frame codec) vs the raw-pickle arm, same
    # wave benchmark: the acceptance bar is <=3% overhead, recorded here
    # per arm so the default flip stays justified by data.  ALTERNATING
    # repeats + medians: on a 1-core host a single back-to-back pair is
    # dominated by pool-warmup/GC ordering noise (one-shot runs swung
    # +-15% either direction); A/B/A/B with medians is stable.
    import statistics as _stats

    wave = 20 if quick else 100
    reps = 1 if quick else 3
    arms = {"pickle": [], "proto": []}

    def wire_arm(mode: str) -> float:
        saved_wire = _os.environ.get("RAY_TPU_WIRE")
        _os.environ["RAY_TPU_WIRE"] = mode
        ray_tpu.init(num_cpus=4, num_tpus=0)
        try:
            @ray_tpu.remote
            def noop2():
                return None

            def wavefn():
                ray_tpu.get([noop2.remote() for _ in range(wave)],
                            timeout=120)

            ramp_until = time.perf_counter() + (1.0 if quick else 3.0)
            while time.perf_counter() < ramp_until:
                wavefn()
            t0 = time.perf_counter()
            n = 0
            while time.perf_counter() - t0 < (0.5 if quick else 2.0):
                wavefn()
                n += wave
            return n / (time.perf_counter() - t0)
        finally:
            ray_tpu.shutdown()
            # restore the CALLER's pin (pop would flip the rest of the
            # envelope run to the built-in default mid-bench)
            if saved_wire is None:
                _os.environ.pop("RAY_TPU_WIRE", None)
            else:
                _os.environ["RAY_TPU_WIRE"] = saved_wire

    for i in range(reps):
        order = ("proto", "pickle") if i % 2 else ("pickle", "proto")
        for mode in order:
            arms[mode].append(wire_arm(mode))
    p = _stats.median(arms["pickle"])
    q = _stats.median(arms["proto"])
    record({"metric": "task_throughput_wire_pickle",
            "value": round(p, 2), "unit": "ops/s"})
    record({"metric": "task_throughput_wire_proto",
            "value": round(q, 2), "unit": "ops/s"})
    record({"metric": "wire_overhead", "value": round((p - q) / p * 100, 2),
            "unit": "%", "proto_ops_s": round(q, 2),
            "pickle_ops_s": round(p, 2), "reps": reps})

    # ------------------------------------------------- 1k live actors
    # every actor is its own worker process; on a 1-core host the boot
    # storm is the cost, so creation is deadline-bounded and the record
    # says how many came alive
    n_actors = 100 if quick else 1000
    budget_s = 60 if quick else 900
    _os.environ["RAY_TPU_MAXIMUM_STARTUP_CONCURRENCY"] = "16"
    ray_tpu.init(num_cpus=n_actors + 4, num_tpus=0)
    try:
        @ray_tpu.remote
        class Lite:
            def ping(self):
                return 1

        t0 = time.perf_counter()
        actors = [Lite.remote() for _ in range(n_actors)]
        alive = 0
        pings = [a.ping.remote() for a in actors]
        deadline = time.time() + budget_s
        for i in range(0, len(pings), 100):
            try:
                ray_tpu.get(pings[i:i + 100],
                            timeout=max(5.0, deadline - time.time()))
                alive += min(100, len(pings) - i)
            except Exception:
                break
        dt = time.perf_counter() - t0
        record({"metric": "live_actors", "value": alive, "unit": "actors",
                "target": n_actors, "wall_s": round(dt, 1),
                "actors_per_s": round(alive / dt, 2)})
        del actors
    finally:
        ray_tpu.shutdown()
        _os.environ.pop("RAY_TPU_MAXIMUM_STARTUP_CONCURRENCY", None)

    # ------------------- sustained 16-emulated-node envelope, doctor-watched
    # the multi-node head envelope: every node takes dispatches for a
    # sustained window (tasks spread + one actor per node), then the
    # doctor reads the recorded state — the run only counts as healthy
    # with zero ERROR/CRITICAL findings (doctor_clean).
    n_nodes = 4 if quick else 16
    budget_s = 10 if quick else 45
    from ray_tpu.cluster_utils import Cluster as _Cluster

    cluster = _Cluster(initialize_head=True,
                       head_node_args={"num_cpus": 2, "num_tpus": 0})
    try:
        for _ in range(n_nodes - 1):
            cluster.add_node(num_cpus=2)

        @ray_tpu.remote
        def spread():
            return None

        @ray_tpu.remote
        class PerNode:
            def ping(self):
                return 1

        actors = [PerNode.remote() for _ in range(n_nodes)]
        ray_tpu.get([a.ping.remote() for a in actors], timeout=600)
        done = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < budget_s:
            refs = [spread.remote() for _ in range(400)]
            refs += [a.ping.remote() for a in actors]
            ray_tpu.get(refs, timeout=600)
            done += len(refs)
        dt = time.perf_counter() - t0
        from ray_tpu.util.doctor import run_doctor

        findings = run_doctor()
        errors = [f for f in findings
                  if f.get("severity") in ("ERROR", "CRITICAL")]
        # watchdog tick against this loaded multi-node head rides along
        # as a field (the --check-gated watchdog_tick row lives in the
        # core run; this is the same tick at envelope scale)
        from ray_tpu._private.worker import global_worker as _gw

        wd = getattr(_gw.node, "watchdog", None)
        wd_tick_ms = None
        if wd is not None:
            wd.tick()  # warm the cursors / doctor window
            n_ticks = 20 if quick else 100
            t1 = time.perf_counter()
            for _ in range(n_ticks):
                wd.tick()
            wd_tick_ms = round(
                (time.perf_counter() - t1) / n_ticks * 1e3, 3)
        record({"metric": "multi_node_envelope", "value": n_nodes,
                "unit": "nodes", "sustained_s": round(dt, 1),
                "ops_s": round(done / dt, 1),
                "doctor_findings": len(findings),
                "doctor_errors": len(errors),
                "doctor_clean": not errors,
                "watchdog_tick_ms": wd_tick_ms})
    finally:
        cluster.shutdown()

    # ------------------------------------------------- 8 GiB single get
    gib = 1 if quick else 8
    _os.environ["RAY_TPU_OBJECT_STORE_MEMORY"] = str((gib + 2) << 30)
    ray_tpu.init(num_cpus=2, num_tpus=0)
    try:
        arr = np.frombuffer(
            bytearray(_os.urandom(1 << 20)) * (gib << 10), dtype=np.uint8)
        t0 = time.perf_counter()
        ref = ray_tpu.put(arr)
        put_dt = time.perf_counter() - t0
        head, tail = int(arr[5]), int(arr[-5])
        del arr
        gc.collect()
        t0 = time.perf_counter()
        out = ray_tpu.get(ref)
        get_dt = time.perf_counter() - t0
        assert out.nbytes == gib << 30
        assert int(out[5]) == head and int(out[-5]) == tail
        record({"metric": f"single_get_{gib}gib", "value": gib, "unit": "GiB",
                "put_gbps": round(gib / put_dt, 2),
                "get_gbps": round(gib / get_dt, 2)})
        del out, ref
    finally:
        ray_tpu.shutdown()
        _os.environ.pop("RAY_TPU_OBJECT_STORE_MEMORY", None)

    # -------------------------------------- spill under pressure + recovery
    # store capped far below the working set: puts must spill, gets must
    # restore every payload intact
    n_obj, mb_obj = (6, 64) if quick else (12, 64)  # working set > cap
    _os.environ["RAY_TPU_OBJECT_STORE_MEMORY"] = str(256 << 20)
    ray_tpu.init(num_cpus=2, num_tpus=0)
    try:
        rng = np.random.default_rng(7)
        sums, refs2 = [], []
        t0 = time.perf_counter()
        for i in range(n_obj):
            a = rng.integers(0, 255, mb_obj << 20, dtype=np.uint8)
            sums.append(int(a[::4096].sum()))
            refs2.append(ray_tpu.put(a))
            del a
        put_dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        ok = 0
        for ref, want in zip(refs2, sums):
            got = ray_tpu.get(ref, timeout=300)
            assert int(got[::4096].sum()) == want
            ok += 1
            del got
        get_dt = time.perf_counter() - t0
        total_mb = n_obj * mb_obj
        record({"metric": "spill_under_pressure", "value": ok,
                "unit": "objects", "working_set_mb": total_mb,
                "store_cap_mb": 256,
                "put_gbps": round(total_mb / 1024 / put_dt, 2),
                "restore_gbps": round(total_mb / 1024 / get_dt, 2)})
    finally:
        ray_tpu.shutdown()
        _os.environ.pop("RAY_TPU_OBJECT_STORE_MEMORY", None)
    return results


if __name__ == "__main__":
    import argparse
    import os

    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true")
    p.add_argument("--scale", action="store_true",
                   help="also run the scalability-envelope scenarios")
    p.add_argument("--scale-only", action="store_true")
    p.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "BENCH_core.json"))
    args = p.parse_args()
    res = [] if args.scale_only else main(quick=args.quick)
    if args.scale or args.scale_only:
        res += scale_envelope(quick=args.quick)
    payload = {"benchmarks": res, "host": "single-node"}
    if os.path.exists(args.out):
        # ALWAYS merge by metric name: a core-only run must not silently
        # drop the scale-envelope rows (or vice versa) — only the metrics
        # measured THIS run are refreshed
        try:
            with open(args.out) as f:
                old = json.load(f)
            merged = {r["metric"]: r for r in old.get("benchmarks", [])}
            merged.update({r["metric"]: r for r in res})
            payload = {"benchmarks": list(merged.values()), "host": "single-node"}
        except Exception:
            pass
    with open(args.out, "w") as f:
        json.dump(payload, f, indent=2)
    print(f"wrote {args.out}")
