"""Chaos-testing utilities.

Analog of the reference's fault-injection helpers — ``NodeKillerActor``
(``python/ray/_private/test_utils.py:1301``, ``_kill_raylet`` ``:1377``)
which SIGKILLs raylets on an interval to drive the chaos suite
(``python/ray/tests/test_chaos.py``).  Here the unit of failure on a
single host is the worker process: the killer SIGKILLs busy workers on an
interval and the runtime's retry/restart machinery must absorb it.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional


class WorkerKiller:
    """SIGKILLs a random busy worker every ``interval_s`` seconds.

    Usage::

        killer = WorkerKiller(interval_s=0.4)
        killer.start()
        ... run workload with retries enabled ...
        killer.stop()
        assert killer.kills > 0
    """

    def __init__(
        self,
        node=None,
        interval_s: float = 0.5,
        include_actor_workers: bool = True,
        seed: Optional[int] = None,
    ):
        if node is None:
            from ray_tpu._private.worker import global_worker

            node = global_worker.node
        self.node = node
        self.interval_s = interval_s
        self.include_actor_workers = include_actor_workers
        self.kills = 0
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _candidates(self):
        with self.node.lock:
            return [
                w
                for w in self.node.workers.values()
                if w.state == "busy"
                and w.proc is not None
                and (self.include_actor_workers or not w.is_actor_worker)
            ]

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            cands = self._candidates()
            if not cands:
                continue
            victim = self._rng.choice(cands)
            try:
                victim.proc.kill()
                self.kills += 1
            except Exception:
                pass

    def start(self) -> "WorkerKiller":
        self._thread = threading.Thread(target=self._loop, daemon=True, name="worker-killer")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
