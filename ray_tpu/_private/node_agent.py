"""Per-node agent: joins a running cluster over TCP and manages this host.

The raylet-join path of the reference (``ray start --address=<head>``:
``python/ray/_private/services.py:1273`` launches a raylet that registers
with the GCS and serves its node): the agent

- registers a real ``NodeState`` with the head (resources + TPU chips),
- spawns/kills worker processes on THIS host when the head asks (the
  workers connect straight back to the head's TCP control plane),
- serves object pulls from this node's private shm namespace through an
  :class:`~ray_tpu._private.object_transfer.ObjectServer`,
- reports pre-registration worker deaths (the head cannot poll a remote
  process),
- unlinks local segments when the head evicts them, and
- gossips its resource view + liveness to peer agents through the
  :mod:`~ray_tpu._private.syncer` P2P mesh (on by default), shipping the
  converged view back to the head each tick so the head is no longer the
  sole fan-in for every heartbeat and peer-observed death reaches it
  faster than a missed-pong timeout.

Run via ``python -m ray_tpu._private.node_agent --address host:port
--authkey <hex>`` or through ``ray_tpu start`` / ``cluster_utils.Cluster``.
"""

from __future__ import annotations

import argparse
import logging
import os
import pickle
import random
import subprocess
import sys
import threading
import time
from multiprocessing.connection import Client as MPClient
from typing import Dict, Optional

logger = logging.getLogger(__name__)


def _worker_pythonpath(existing: str) -> str:
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    parts = [pkg_root]
    if existing:
        parts.append(existing)
    return os.pathsep.join(parts)


class NodeAgent:
    def __init__(
        self,
        address: str,
        authkey: bytes,
        num_cpus: Optional[int] = None,
        num_tpus: Optional[int] = None,
        resources: Optional[Dict[str, float]] = None,
        node_id: Optional[str] = None,
        shm_dir: Optional[str] = None,
        host: str = "127.0.0.1",
        slice_id: Optional[str] = None,
    ):
        from ray_tpu._private import shm as shm_mod
        from ray_tpu._private.object_transfer import ObjectServer, configure
        from ray_tpu._private.resource_spec import autodetect_resources

        self.node_id = node_id or f"node-{os.urandom(4).hex()}"
        self.slice_id = slice_id or os.environ.get("RAY_TPU_SLICE_ID") or None
        self.authkey = authkey
        host_s, port_s = address.rsplit(":", 1)
        self.head_addr = (host_s, int(port_s))

        # Private shm namespace for this node: own directory (when given)
        # and own session id, so same-host siblings can't short-circuit the
        # object-transfer plane by attaching each other's segments.
        if shm_dir:
            os.makedirs(shm_dir, exist_ok=True)
            os.environ[shm_mod._SHM_DIR_ENV] = shm_dir
        session = os.environ.get(shm_mod._SESSION_ENV, "nosession")
        self.session = f"{session}{self.node_id.replace('-', '')[-6:]}"
        os.environ[shm_mod._SESSION_ENV] = self.session
        shm_mod.sweep_orphaned_segments()
        shm_mod.write_session_marker(self.session, os.getpid())

        configure(authkey)
        self.object_server = ObjectServer(host, authkey)

        total, tpu_ids = autodetect_resources(num_cpus, num_tpus, resources)
        self.resources = total
        self.procs: Dict[str, subprocess.Popen] = {}  # worker_id hex -> proc
        self._lock = threading.Lock()
        self._shutdown = False
        # chaos message-drop window (devtools.chaos `drop` op): while
        # active, outbound control messages are dropped with probability
        # ``frac`` — the head's direct view of this agent goes dark while
        # the P2P mesh keeps carrying its state
        self._drop: Optional[dict] = None

        # P2P resource/health mesh: on by default whenever this process
        # exists at all (an agent IS the multi-node case)
        self.syncer = None
        from ray_tpu._private import syncer as syncer_mod

        if syncer_mod.ENABLED:
            self.syncer = syncer_mod.ResourceSyncer(
                self.node_id, authkey,
                state_fn=self._syncer_state,
                report_fn=self._syncer_report,
                host=host,
            )

        from ray_tpu._private import wire

        self.conn = wire.wrap(
            MPClient(self.head_addr, family="AF_INET", authkey=authkey))
        self._send_lock = threading.Lock()
        self._send({
            "type": "register_node",
            "node_id": self.node_id,
            "resources": total,
            "tpu_ids": tpu_ids,
            "fetch_addr": tuple(self.object_server.addr),
            "slice_id": self.slice_id,
            "syncer_addr": tuple(self.syncer.addr) if self.syncer else None,
        })
        if self.syncer is not None:
            self.syncer.start()

        # agent events (syncer suspicions, chaos windows) ship to the
        # head's event table like any worker's — without this pusher an
        # agent's flight-recorder ring would be invisible to `ray_tpu
        # events` / doctor
        from ray_tpu._private.events import EventsPusher

        self.events_pusher = EventsPusher(
            self._send, origin=self.node_id,
            closed_fn=lambda: self._shutdown).start()

        # continuous flamegraphs for the agent process itself, shipped
        # over the same head connection (workers on this host each run
        # their own)
        from ray_tpu._private import sampling_profiler as _sp

        self.cont_profiler = None
        if _sp.continuous_enabled():
            self.cont_profiler = _sp.ContinuousProfiler(
                f"agent:{self.node_id}", send_fn=self._send,
                closed_fn=lambda: self._shutdown).start()

        # log plane: tail this host's worker capture files (registered at
        # spawn) and batch-ship them to the head's log store over the
        # same control connection (log_report frames, the metrics_report
        # path).  Registration-based — the head tails only ITS local
        # workers, so shared-session-dir emulation never double-ships.
        from ray_tpu._private import log_plane as log_plane_mod

        self.log_monitor = None
        if log_plane_mod.enabled():
            self.log_monitor = log_plane_mod.LogMonitor(
                self.node_id, send_fn=self._send,
                closed_fn=lambda: self._shutdown).start()
            agent_log = os.environ.get("RAY_TPU_AGENT_LOG")
            if agent_log and log_plane_mod.redirect_process_output(agent_log):
                self.log_monitor.register(
                    f"agent-{self.node_id}", agent_log,
                    node=self.node_id, pid=os.getpid())

        self._monitor = threading.Thread(target=self._monitor_loop, daemon=True,
                                         name="agent-monitor")
        self._monitor.start()

        # Per-entity resource sampler (reporter_agent analog): RSS / CPU% /
        # open fds for every worker on THIS host plus the agent itself,
        # shipped as tagged gauges over the metrics_report path so they
        # land in the head's merged registry and its TSDB.  The head
        # cannot read a remote host's /proc — this loop is the only
        # source of per-worker stats for agent nodes.
        self._resource_interval = self._resource_sample_interval()
        if self._resource_interval > 0:
            t = threading.Thread(target=self._resource_loop, daemon=True,
                                 name="agent-resources")
            t.start()

    @staticmethod
    def _resource_sample_interval() -> float:
        """Sampling cadence; <= 0 disables (shared parse helper — the
        head honors the same knob for its local workers)."""
        from ray_tpu._private.events import _float_env
        from ray_tpu.util.metrics import push_interval_s

        return _float_env("RAY_TPU_RESOURCE_SAMPLE_S", push_interval_s())

    # -- plumbing ---------------------------------------------------------
    def _send(self, msg: dict) -> None:
        drop = self._drop
        if drop is not None:
            if time.time() >= drop["until"]:
                self._drop = None
            elif drop["rng"].random() < drop["frac"]:
                return  # chaos: this control message is lost on the floor
        with self._send_lock:
            self.conn.send(msg)

    # -- P2P mesh ---------------------------------------------------------
    def _syncer_state(self) -> dict:
        """This node's own versioned snapshot payload (gossiped each tick)."""
        from ray_tpu._private.resource_spec import host_stats

        return {
            "resources": dict(self.resources),
            "stats": host_stats(),
            "slice_id": self.slice_id,
            "workers": len(self.procs),
        }

    def _syncer_report(self, view: dict) -> None:
        """Ship the converged mesh view to the head (one frame per tick;
        rides the same control connection as metrics_report)."""
        try:
            self._send({"type": "syncer_report", "origin": self.node_id,
                        **view})
        except (OSError, ValueError):
            pass  # head gone or conn tearing down; gossip continues

    # -- head message loop ------------------------------------------------
    def serve_forever(self) -> None:
        logger.info("node agent %s joined %s (object server %s)",
                    self.node_id, self.head_addr, self.object_server.addr)
        try:
            while not self._shutdown:
                try:
                    msg = self.conn.recv()
                except (EOFError, OSError, pickle.UnpicklingError):
                    # incl. wire.WireDecodeError: treat a bad frame as a
                    # lost head connection, not an agent crash
                    logger.warning("head connection lost; shutting down node")
                    break
                try:
                    self._handle(msg)
                except Exception:
                    logger.exception("agent error handling %s", msg.get("type"))
        finally:
            self.shutdown()

    def _handle(self, msg: dict) -> None:
        mtype = msg["type"]
        if mtype == "spawn_worker":
            self._spawn_worker(msg)
        elif mtype == "kill_worker":
            self._kill_worker(msg["worker_id"])
        elif mtype == "unlink":
            from ray_tpu._private.shm import ShmSegment

            ShmSegment.unlink(msg["name"])
        elif mtype == "pull_object":
            # broadcast fan-out: fetch a copy into this node's namespace
            # (transfers take seconds — never on the agent's control loop)
            threading.Thread(
                target=self._pull_object, args=(msg,), daemon=True
            ).start()
        elif mtype == "shutdown":
            self._shutdown = True
        elif mtype == "syncer_peers":
            # head-maintained mesh directory (rebroadcast on membership
            # change); the syncer prunes its store to it
            if self.syncer is not None:
                self.syncer.set_peers({
                    nid: tuple(addr)
                    for nid, addr in (msg.get("peers") or {}).items()})
        elif mtype == "chaos_drop":
            # devtools.chaos fault injection: drop outbound control
            # messages for a window (seeded — reproducible schedules)
            frac = float(msg.get("frac", 1.0))
            dur = float(msg.get("duration_s", 5.0))
            self._drop = {"frac": frac, "until": time.time() + dur,
                          "rng": random.Random(msg.get("seed"))}
            logger.warning("chaos: dropping %d%% of outbound messages for "
                           "%.1fs", int(frac * 100), dur)
        elif mtype == "ping":
            # heartbeat reply doubles as the per-node metrics report
            # (reporter_agent analog): live host utilization rides every
            # pong and lands on the head's NodeState for /api/nodes
            from ray_tpu._private.resource_spec import host_stats

            self._send({"type": "pong", "ts": msg.get("ts"),
                        "stats": host_stats()})
        else:
            logger.warning("agent: unknown message %s", mtype)

    def _pull_object(self, msg: dict) -> None:
        from ray_tpu._private.object_transfer import pull_object

        try:
            pull_object(
                msg["name"], tuple(msg["addr"]), msg.get("size", -1),
                arena=tuple(msg["arena"]) if msg.get("arena") else None,
            )
            ok, error = True, None
        except Exception as e:  # noqa: BLE001 — the head needs the nack
            ok, error = False, f"{type(e).__name__}: {e}"
        try:
            self._send({"type": "object_pulled", "token": msg.get("token"),
                        "ok": ok, "error": error})
        except (OSError, ValueError):
            pass

    # -- worker management ------------------------------------------------
    def _spawn_worker(self, msg: dict) -> None:
        env = dict(os.environ)
        env.update(msg.get("env_overrides") or {})
        # this node's namespace must win over anything inherited/overridden
        from ray_tpu._private import shm as shm_mod

        env[shm_mod._SESSION_ENV] = self.session
        if os.environ.get(shm_mod._SHM_DIR_ENV):
            env[shm_mod._SHM_DIR_ENV] = os.environ[shm_mod._SHM_DIR_ENV]
        env["RAY_TPU_NODE_ID"] = self.node_id
        env["PYTHONPATH"] = _worker_pythonpath(env.get("PYTHONPATH", ""))
        cwd = msg.get("cwd")
        wid = msg["worker_id"]
        from ray_tpu._private.runtime_env_setup import worker_argv

        try:
            proc = subprocess.Popen(
                worker_argv(msg.get("pip"), msg.get("conda")), env=env, cwd=cwd)
        except OSError as e:
            self._send({"type": "worker_exited", "worker_id": wid,
                        "returncode": -1, "error": str(e)})
            return
        with self._lock:
            self.procs[wid] = proc
        if self.log_monitor is not None and env.get("RAY_TPU_WORKER_LOG"):
            self.log_monitor.register(
                f"worker-{wid}", env["RAY_TPU_WORKER_LOG"],
                node=self.node_id, pid=proc.pid)

    def _kill_worker(self, worker_id: str) -> None:
        with self._lock:
            proc = self.procs.pop(worker_id, None)
        if proc is not None:
            try:
                proc.kill()
            except Exception:
                pass
        if self.log_monitor is not None:
            # ship whatever the file gained before the head retires the
            # stream (its kill_worker -> death path runs after this)
            self.log_monitor.unregister(f"worker-{worker_id}")

    def _resource_loop(self) -> None:
        """/proc sampling of agent + workers on the shared deadline grid
        (``metrics.grid_ticks``) — spacing must stay uniform for the
        head's TSDB."""
        from ray_tpu._private.resource_spec import (
            ProcSampler,
            resource_metrics_snapshot,
        )
        from ray_tpu.util.metrics import grid_ticks

        sampler = ProcSampler()

        def wait(timeout: float) -> bool:
            time.sleep(timeout)
            return self._shutdown

        for _ in grid_ticks(self._resource_interval, wait):
            entities = [({"entity": "agent", "node": self.node_id},
                         os.getpid())]
            with self._lock:
                for wid, proc in self.procs.items():
                    entities.append((
                        {"entity": "worker", "worker_id": wid,
                         "node": self.node_id}, proc.pid))
            snap, _ = resource_metrics_snapshot(sampler, entities)
            if not snap:
                continue
            try:
                self._send({"type": "metrics_report", "origin": self.node_id,
                            "metrics": snap})
            except (OSError, ValueError):
                return  # head gone; serve_forever is tearing down

    def _monitor_loop(self) -> None:
        """Report worker processes that die (the head polls local procs
        itself; remote ones are invisible to it)."""
        while not self._shutdown:
            time.sleep(0.2)
            dead = []
            with self._lock:
                for wid, proc in list(self.procs.items()):
                    rc = proc.poll()
                    if rc is not None:
                        dead.append((wid, rc))
                        del self.procs[wid]
            for wid, rc in dead:
                if self.log_monitor is not None:
                    # final drain FIRST: the log_report rides the same
                    # connection, so the head holds the death tail before
                    # it processes worker_exited (the SIGKILL'd-stderr
                    # guarantee for remote workers)
                    self.log_monitor.unregister(f"worker-{wid}")
                try:
                    self._send({"type": "worker_exited", "worker_id": wid,
                                "returncode": rc})
                except (OSError, ValueError):
                    return

    def shutdown(self) -> None:
        from ray_tpu._private import shm as shm_mod

        self._shutdown = True
        if self.log_monitor is not None:
            try:
                self.log_monitor.stop()  # final ship while the conn lives
            except Exception:
                pass
        if self.syncer is not None:
            self.syncer.stop()
        if self.cont_profiler is not None:
            try:
                self.cont_profiler.stop()
            except Exception:
                pass
        try:
            self.events_pusher.stop()
        except Exception:
            pass
        with self._lock:
            procs = list(self.procs.values())
            self.procs.clear()
        for p in procs:
            try:
                p.kill()
            except Exception:
                pass
        self.object_server.close()
        try:
            self.conn.close()
        except Exception:
            pass
        # reclaim this node's namespace
        shm_mod.remove_session_marker(self.session)
        shm_mod.sweep_orphaned_segments()


def main() -> None:
    p = argparse.ArgumentParser(description="ray_tpu node agent")
    p.add_argument("--address", required=True, help="head host:port")
    p.add_argument("--authkey", default=None, help="cluster authkey (hex); "
                   "defaults to $RAY_TPU_AUTHKEY")
    p.add_argument("--num-cpus", type=int, default=None)
    p.add_argument("--num-tpus", type=int, default=None)
    p.add_argument("--resources", default=None,
                   help='extra custom resources as JSON, e.g. \'{"special": 1}\'')
    p.add_argument("--node-id", default=None)
    p.add_argument("--shm-dir", default=None)
    p.add_argument("--slice-id", default=None,
                   help="failure-domain id: hosts of one TPU slice share it "
                        "and are provisioned/replaced as one unit")
    args = p.parse_args()
    authkey = bytes.fromhex(args.authkey or os.environ["RAY_TPU_AUTHKEY"])
    logging.basicConfig(level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"))
    import json

    agent = NodeAgent(
        args.address, authkey,
        num_cpus=args.num_cpus, num_tpus=args.num_tpus,
        resources=json.loads(args.resources) if args.resources else None,
        node_id=args.node_id, shm_dir=args.shm_dir, slice_id=args.slice_id,
    )
    agent.serve_forever()


if __name__ == "__main__":
    main()
