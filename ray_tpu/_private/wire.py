"""Typed wire codec for the control plane.

Every control-plane frame is one ``protocol.Envelope`` (see
``ray_tpu/protocol/ray_tpu.proto``) carried over the existing
length-prefixed connection framing.  Hot-path messages — task
submission batches, execute dispatches, task completions, seals,
refcount updates, KV ops, get/wait and their replies — encode as typed
protobuf; the long tail rides the ``pickled`` fallback arm unchanged.
This is the reference's protobuf-over-gRPC L0 re-shaped for a
socket-multiplexed control plane (``src/ray/protobuf/common.proto``
TaskSpec: typed spec, language-serialized arg blobs as bytes).

Handlers keep their dict interface: ``encode``/``decode`` translate
dict <-> Envelope, and ``WireConnection`` swaps the codec in under any
``multiprocessing.connection.Connection`` via send_bytes/recv_bytes.

Interop: a pickle frame starts with opcode 0x80; an Envelope always
starts with the version varint tag 0x08; a PACKED frame (packed_wire.py
— the hot ~7 frame types lowered to struct-packed headers, no protobuf
reflection) starts with the magic 0xB1 — receivers sniff the first
byte, so all three encodings are always accepted.  Untyped long-tail
messages are sent as RAW pickle frames (no envelope wrap): that avoids
double-copying the payload and protobuf's 2 GiB message cap
(thin-client blobs ship multi-GiB frames here).

Encoding selection (``RAY_TPU_WIRE``): every connection RECEIVES
through the sniffing decoder — mixed clusters interoperate — and the
flag selects only what a process SENDS.  The DEFAULT is ``proto``: hot
frames take the packed codec (low-single-digit % overhead vs raw
pickle — the packed headers cost ~2-6us/frame where the pure-Python
protobuf Envelope cost ~50-90us/task, ~19% of no-op throughput on a
1-core head), other typed frames take the Envelope arm, and the long
tail rides raw pickle.  ``envelope`` forces the protobuf arm for every
typed frame (the packed codec off — the IDL-conformance arm a
cross-language peer would speak); ``pickle`` restores the raw-pickle
fast path everywhere (the pre-flip default, still fully supported).
The suite pins RAY_TPU_WIRE=proto in tests/conftest.py (redundant with
the default, but explicit), and test_wire.py cluster-tests the pickle
and mixed-mode arms via subprocess drivers.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional

from ray_tpu._private import packed_wire
from ray_tpu._private.object_store import ObjectLocation
from ray_tpu.protocol import ray_tpu_pb2 as pb

WIRE_VERSION = 1

_PICKLE_PROTO = pickle.HIGHEST_PROTOCOL

# Largest Envelope a peer is guaranteed to parse: protobuf's 2 GiB message
# cap, with headroom for field tags/framing around the payload.  Frames at
# or past this take the raw-pickle arm (no cap there).
_PB_MAX_FRAME = (1 << 31) - (1 << 20)

try:
    from google.protobuf.message import EncodeError as _EncodeError
except Exception:  # pragma: no cover — protobuf always present in-image
    class _EncodeError(Exception):
        pass


class WireDecodeError(pickle.UnpicklingError):
    """Bad frame.  Subclasses UnpicklingError so every existing
    reader-loop ``except`` clause treats it as a broken connection."""


# ---------------------------------------------------------------------------
# field helpers

def _loc_to_pb(loc: ObjectLocation) -> pb.ObjectLocation:
    # None is NOT accepted: encoders catch the TypeError and fall back
    # to the pickle arm, which preserves None exactly (a dep can unseal
    # between scheduling and dispatch).  Single constructor call — these
    # ride in every seal/location-reply message.
    if loc is None:
        raise TypeError("ObjectLocation is None")
    kw: Dict[str, Any] = {}
    if loc.inline is not None:
        kw["inline"] = bytes(loc.inline)
    if loc.shm_name is not None:
        kw["shm_name"] = loc.shm_name
    if loc.spilled_path is not None:
        kw["spilled_path"] = loc.spilled_path
    if loc.size:
        kw["size"] = loc.size
    if loc.is_error:
        kw["is_error"] = True
    if loc.node_id:
        kw["node_id"] = loc.node_id
    if loc.fetch_addr is not None:
        kw["fetch_host"] = str(loc.fetch_addr[0])
        kw["fetch_port"] = int(loc.fetch_addr[1])
    if loc.arena_path is not None:
        kw["arena_path"] = loc.arena_path
    if loc.arena_off:
        kw["arena_off"] = loc.arena_off
    if loc.arena_key is not None:
        kw["arena_key"] = loc.arena_key
    return pb.ObjectLocation(**kw)


def _loc_from_pb(m: pb.ObjectLocation) -> ObjectLocation:
    return ObjectLocation(
        inline=m.inline if m.HasField("inline") else None,
        shm_name=m.shm_name if m.HasField("shm_name") else None,
        spilled_path=m.spilled_path if m.HasField("spilled_path") else None,
        size=m.size,
        is_error=m.is_error,
        node_id=m.node_id,
        fetch_addr=((m.fetch_host, m.fetch_port)
                    if m.HasField("fetch_host") else None),
        arena_path=m.arena_path if m.HasField("arena_path") else None,
        arena_off=m.arena_off,
        arena_key=m.arena_key if m.HasField("arena_key") else None,
    )


# TaskSpec scalar/bytes/string fields copied 1:1 between dict key and
# proto field; repeated and special fields handled explicitly.
_SPEC_SCALARS = (
    "task_id", "name", "fn_id", "args_blob", "args_oid", "num_returns",
    "retries_left", "actor_id", "method_name", "is_actor_creation",
    "max_restarts", "max_task_retries", "actor_name", "max_concurrency",
    "release_cpu_after_start", "parent_task_id",
)
_SPEC_REPEATED = ("dep_ids", "pinned_refs", "owned_oids", "return_ids")
_SPEC_PICKLED = ("scheduling_strategy", "runtime_env")
_SPEC_KEYS = frozenset(_SPEC_SCALARS + _SPEC_REPEATED + _SPEC_PICKLED
                       + ("resources",))


def _spec_to_pb(spec: Dict[str, Any]) -> pb.TaskSpec:
    # one constructor call (a single C roundtrip under upb — per-field
    # setattr was ~10x slower on the submit hot path); repeated fields
    # take lists and the resources map takes a dict directly
    known: Dict[str, Any] = {}
    extra = None
    for k, v in spec.items():
        if k in _SPEC_KEYS:
            if k in _SPEC_PICKLED:
                known[k] = pickle.dumps(v, _PICKLE_PROTO)
            elif k == "resources":
                # validate_options doesn't type-check custom resource
                # amounts; coerce so e.g. {"accel": "1"} stays schedulable
                known[k] = {rk: float(rv) for rk, rv in v.items()}
            elif v is not None:
                known[k] = v
        else:
            # forward-compat long tail (trace_ctx, dynamic_returns, ...)
            if extra is None:
                extra = {}
            extra[k] = v
    if extra:
        known["extra"] = pickle.dumps(extra, _PICKLE_PROTO)
    return pb.TaskSpec(**known)


_SPEC_REPEATED_SET = frozenset(_SPEC_REPEATED)
_SPEC_PICKLED_SET = frozenset(_SPEC_PICKLED)


def _spec_from_pb(m: pb.TaskSpec) -> Dict[str, Any]:
    # Reconstruct the stripped-dict form: proto default => key absent
    # (build_task_spec drops None/0/False/[] keys), except the four
    # always-present keys.  ListFields() walks only the SET fields — one
    # pass instead of probing all 24.
    spec: Dict[str, Any] = {}
    for fd, v in m.ListFields():
        k = fd.name
        if k in _SPEC_REPEATED_SET:
            spec[k] = list(v)
        elif k in _SPEC_PICKLED_SET or k == "extra":
            if k == "extra":
                spec.update(pickle.loads(v))
            else:
                spec[k] = pickle.loads(v)
        elif k == "resources":
            spec[k] = dict(v)
        else:
            spec[k] = v
    # the four always-present keys (proto3 omits zero-valued scalars)
    spec.setdefault("task_id", m.task_id)
    spec.setdefault("name", m.name)
    spec.setdefault("return_ids", [])
    spec.setdefault("num_returns", m.num_returns)
    return spec


def _seal_to_pb(oid: bytes, loc, contained) -> pb.SealEntry:
    return pb.SealEntry(oid=oid, loc=_loc_to_pb(loc),
                        contained=list(contained or ()))


# ---------------------------------------------------------------------------
# per-type encoders: dict -> Envelope (return None to fall back to pickle)

def _enc_submit_batch(msg, env) -> bool:
    env.submit_batch.items.extend(
        pb.Submit(kind=kind, spec=_spec_to_pb(spec))
        for kind, spec in msg["batch"])
    return True


def _enc_execute(msg, env) -> bool:
    env.execute.MergeFrom(pb.Execute(
        spec=_spec_to_pb(msg["spec"]),
        dep_locs=[pb.LocEntry(oid=oid, loc=_loc_to_pb(loc))
                  for oid, loc in msg.get("dep_locs", {}).items()],
        tpu_ids=msg.get("tpu_ids", ()),
    ))
    return True


_TASK_DONE_KEYS = frozenset((
    "type", "seals", "spec_ref", "failed", "error_str", "exec_start",
    "exec_end", "worker_pid",
))


def _enc_task_done(msg, env) -> bool:
    m = env.task_done
    for oid, loc, contained in msg.get("seals", ()):
        m.seals.append(_seal_to_pb(oid, loc, contained))
    ref = msg["spec_ref"]
    m.task_id = ref["task_id"]
    m.return_ids.extend(ref.get("return_ids", ()))
    if ref.get("is_actor_creation"):
        m.is_actor_creation = True
    if ref.get("actor_id") is not None:
        m.actor_id = ref["actor_id"]
    if ref.get("name") is not None:
        m.name = ref["name"]
    if msg.get("failed"):
        m.failed = True
    if msg.get("error_str") is not None:
        m.error_str = msg["error_str"]
    m.exec_start = msg.get("exec_start", 0.0)
    m.exec_end = msg.get("exec_end", 0.0)
    m.worker_pid = msg.get("worker_pid", 0)
    rest = {k: v for k, v in msg.items() if k not in _TASK_DONE_KEYS}
    if rest:
        m.extra = pickle.dumps(rest, _PICKLE_PROTO)
    return True


def _enc_seal(msg, env) -> bool:
    env.seal.CopyFrom(
        _seal_to_pb(msg["oid"], msg["loc"], msg.get("contained", ())))
    return True


def _enc_add_ref(msg, env) -> bool:
    if msg.get("reason", "handle") != "handle":
        # the RefUpdate schema predates pin reasons: encoding here would
        # silently drop the reason and skew the head's pin-reason audit.
        # The packed arm carries it; this Envelope fallback preserves it
        # via the pickle arm.
        return False
    env.add_ref.oids.extend(msg["oids"])
    return True


def _enc_remove_ref(msg, env) -> bool:
    if msg.get("reason", "handle") != "handle":
        return False  # see _enc_add_ref
    env.remove_ref.oids.extend(msg["oids"])
    return True


def _enc_kv_put(msg, env) -> bool:
    if len(msg["value"]) >= _PB_MAX_FRAME:
        # size-gate the one arm that carries unbounded bytes BEFORE
        # copying them into the Envelope: a near-/over-2 GiB value would
        # serialize (upb has no encode cap) into a frame no receiving
        # backend can parse — the raw-pickle frame has no such cap
        return False
    env.kv_put.ns = msg["ns"]
    env.kv_put.key = msg["key"]
    env.kv_put.value = msg["value"]
    return True


def _enc_kv_get(msg, env) -> bool:
    env.kv_get.ns = msg["ns"]
    env.kv_get.key = msg["key"]
    env.kv_get.req_id = msg["req_id"]
    return True


def _enc_get_locations(msg, env) -> bool:
    m = env.get_locations
    m.oids.extend(msg["oids"])
    if msg.get("timeout") is not None:
        m.timeout = msg["timeout"]
    m.req_id = msg["req_id"]
    return True


def _enc_wait(msg, env) -> bool:
    m = env.wait
    m.oids.extend(msg["oids"])
    m.num_returns = msg["num_returns"]
    if msg.get("timeout") is not None:
        m.timeout = msg["timeout"]
    m.req_id = msg["req_id"]
    return True


_REPLY_GET = frozenset(("type", "req_id", "locations"))
_REPLY_TIMEOUT = frozenset(("type", "req_id", "timeout"))
_REPLY_WAIT = frozenset(("type", "req_id", "ready", "locations"))


def _enc_reply(msg, env) -> bool:
    # Only the three get/wait reply shapes are typed; every other reply
    # carries arbitrary Python values and falls back to pickle.
    keys = frozenset(msg)
    m = env.locations_reply
    if keys == _REPLY_TIMEOUT and msg["timeout"] is True:
        m.req_id = msg["req_id"]
        m.timeout = True
        return True
    if keys == _REPLY_GET or keys == _REPLY_WAIT:
        locs = msg["locations"]
        if not all(isinstance(l, ObjectLocation) for l in locs.values()):
            return False  # a None slipped in: pickle preserves it exactly
        m.req_id = msg["req_id"]
        for oid, loc in locs.items():
            m.locations.append(pb.LocEntry(oid=oid, loc=_loc_to_pb(loc)))
        if keys == _REPLY_WAIT:
            m.is_wait = True
            m.ready.extend(msg["ready"])
        return True
    return False


_SIMPLE_TYPES = frozenset((
    "ping", "pong", "blocked", "unblocked", "exit", "register_client",
    "flush",
))

_ENCODERS = {
    "submit_batch": _enc_submit_batch,
    "execute": _enc_execute,
    "task_done": _enc_task_done,
    "seal": _enc_seal,
    "add_ref": _enc_add_ref,
    "remove_ref": _enc_remove_ref,
    "kv_put": _enc_kv_put,
    "kv_get": _enc_kv_get,
    "get_locations": _enc_get_locations,
    "wait": _enc_wait,
    "reply": _enc_reply,
}


def encode(msg: Dict[str, Any], packed: bool = True) -> bytes:
    if packed:
        # hot frames take the struct-packed codec; None means "not a
        # packed type / oversize / unexpected shape" and falls through to
        # the Envelope arm (whose own gates land on raw pickle)
        out = packed_wire.encode(msg)
        if out is not None:
            return out
    env = pb.Envelope(version=WIRE_VERSION)
    enc = _ENCODERS.get(msg.get("type"))
    done = False
    if enc is not None:
        try:
            done = enc(msg, env)
        except (KeyError, TypeError, ValueError):
            done = False  # unexpected shape: the pickle arm is always valid
    if not done:
        if msg.get("type") in _SIMPLE_TYPES and len(msg) == 1:
            env.simple.type = msg["type"]
        else:
            # Long-tail fallback: a RAW pickle frame, not pickle-inside-
            # Envelope.  decode() sniffs it by the 0x80 opcode, so this
            # costs nothing in interop and (a) skips a full extra copy of
            # the payload, (b) dodges protobuf's 2 GiB message cap — thin
            # client put_blob/get_blob legitimately ship multi-GiB frames
            # over this connection.
            return pickle.dumps(msg, _PICKLE_PROTO)
    try:
        out = env.SerializeToString()
    except (ValueError, _EncodeError):
        # A typed arm can build an Envelope that protobuf then refuses to
        # serialize — the C++ backend raises only at SerializeToString
        # time for a > 2 GiB message, never in the encoder itself.  The
        # raw pickle frame has no size cap and decode() sniffs it by
        # opcode, so falling back is always correct; leaking the raise
        # would poison every send() call site.
        return pickle.dumps(msg, _PICKLE_PROTO)
    if len(out) >= _PB_MAX_FRAME:
        # the upb backend SERIALIZES oversized messages happily, but no
        # receiving backend can PARSE a > 2 GiB frame (DecodeError at the
        # peer — a silent wire break).  Catches any typed arm that grew
        # past the cap (big inline task args, batched seals), not just
        # the kv_put arm gated above.
        return pickle.dumps(msg, _PICKLE_PROTO)
    return out


# ---------------------------------------------------------------------------
# per-type decoders: Envelope -> dict

def _dec_submit_batch(m) -> dict:
    return {"type": "submit_batch",
            "batch": [(s.kind, _spec_from_pb(s.spec)) for s in m.items]}


def _dec_execute(m) -> dict:
    out = {"type": "execute", "spec": _spec_from_pb(m.spec)}
    if m.dep_locs:
        out["dep_locs"] = {e.oid: _loc_from_pb(e.loc) for e in m.dep_locs}
    if m.tpu_ids:
        out["tpu_ids"] = list(m.tpu_ids)
    return out


def _dec_task_done(m) -> dict:
    out = {
        "type": "task_done",
        "seals": [(e.oid, _loc_from_pb(e.loc), list(e.contained))
                  for e in m.seals],
        "spec_ref": {
            "task_id": m.task_id,
            "return_ids": list(m.return_ids),
            "is_actor_creation": m.is_actor_creation or None,
            "actor_id": m.actor_id if m.HasField("actor_id") else None,
            "name": m.name if m.HasField("name") else None,
        },
        "failed": m.failed,
        "error_str": m.error_str if m.HasField("error_str") else None,
        "exec_start": m.exec_start,
        "exec_end": m.exec_end,
        "worker_pid": m.worker_pid,
    }
    if m.HasField("extra"):
        out.update(pickle.loads(m.extra))
    return out


def _dec_seal(m) -> dict:
    return {"type": "seal", "oid": m.oid, "loc": _loc_from_pb(m.loc),
            "contained": list(m.contained)}


def _dec_reply(m) -> dict:
    out: Dict[str, Any] = {"type": "reply", "req_id": m.req_id}
    if m.timeout:
        out["timeout"] = True
        return out
    out["locations"] = {e.oid: _loc_from_pb(e.loc) for e in m.locations}
    if m.is_wait:
        out["ready"] = list(m.ready)
    return out


_DECODERS = {
    "submit_batch": _dec_submit_batch,
    "execute": _dec_execute,
    "task_done": _dec_task_done,
    "seal": _dec_seal,
    # the Envelope RefUpdate arm only ever carries handle-reason updates
    # (non-handle reasons fall back to pickle — see _enc_add_ref);
    # materializing the default keeps decode(encode(x)) == x
    "add_ref": lambda m: {"type": "add_ref", "oids": list(m.oids),
                          "reason": "handle"},
    "remove_ref": lambda m: {"type": "remove_ref", "oids": list(m.oids),
                             "reason": "handle"},
    "kv_put": lambda m: {"type": "kv_put", "ns": m.ns, "key": m.key,
                         "value": m.value},
    "kv_get": lambda m: {"type": "kv_get", "ns": m.ns, "key": m.key,
                         "req_id": m.req_id},
    "get_locations": lambda m: {
        "type": "get_locations", "oids": list(m.oids),
        "timeout": m.timeout if m.HasField("timeout") else None,
        "req_id": m.req_id},
    "wait": lambda m: {
        "type": "wait", "oids": list(m.oids), "num_returns": m.num_returns,
        "timeout": m.timeout if m.HasField("timeout") else None,
        "req_id": m.req_id},
    "locations_reply": _dec_reply,
    "simple": lambda m: {"type": m.type},
}


def decode(data: bytes) -> Dict[str, Any]:
    head = data[:1]
    if head == b"\x80":
        # raw pickle frame — RAY_TPU_WIRE=pickle senders and the untyped
        # long-tail of proto-mode senders.  This arm is load-bearing,
        # not legacy: removing it breaks every pickle-mode cluster.
        return pickle.loads(data)
    if head == packed_wire.MAGIC_BYTE:
        # packed hot frame (the proto-mode default for ~7 frame types)
        try:
            return packed_wire.decode(data)
        except Exception as e:
            raise WireDecodeError(f"bad packed frame: {e}") from e
    try:
        env = pb.Envelope.FromString(data)
    except Exception as e:
        raise WireDecodeError(f"bad wire frame: {e}") from e
    if env.version != WIRE_VERSION:
        raise WireDecodeError(
            f"wire version {env.version} != {WIRE_VERSION}")
    body = env.WhichOneof("body")
    if body == "pickled":
        return pickle.loads(env.pickled)
    dec = _DECODERS.get(body)
    if dec is None:
        raise WireDecodeError(f"unknown envelope body {body!r}")
    return dec(getattr(env, body))


# ---------------------------------------------------------------------------
# connection wrapper

class WireConnection:
    """Drop-in ``Connection`` facade.  The RECEIVE path always accepts
    every encoding (decode() sniffs the first byte — raw pickle, packed,
    and Envelope frames share the same length-prefixed transport
    framing); ``typed``/``packed`` gate only what THIS side emits."""

    __slots__ = ("_conn", "_typed", "_packed")

    def __init__(self, conn, typed: bool, packed: bool = True):
        self._conn = conn
        self._typed = typed
        self._packed = packed

    def send(self, msg: Dict[str, Any]) -> None:
        if self._typed:
            self._conn.send_bytes(encode(msg, packed=self._packed))
        else:
            self._conn.send_bytes(pickle.dumps(msg, _PICKLE_PROTO))

    def recv(self) -> Dict[str, Any]:
        return decode(self._conn.recv_bytes())

    def poll(self, timeout: float = 0.0) -> bool:
        return self._conn.poll(timeout)

    def fileno(self) -> int:
        return self._conn.fileno()

    def close(self) -> None:
        self._conn.close()

    @property
    def closed(self) -> bool:
        return self._conn.closed

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def wrap(conn):
    """Wrap a freshly connected/accepted control connection.  EVERY
    connection receives through the sniffing decoder, so any peer can
    speak any encoding at any time (mixed clusters and rolling flag
    changes just work); ``RAY_TPU_WIRE=proto|envelope|pickle`` selects
    only what this process SENDS (see the module docstring).  The
    default is ``proto`` — the typed wire with the packed hot-frame
    codec.  Caveat: a peer from a release that predates the packed
    codec cannot sniff its 0xB1 magic — when rolling such a fleet, pin
    ``RAY_TPU_WIRE=envelope`` (or ``pickle``) on upgraded processes
    until every node is current, then drop the pin."""
    mode = os.environ.get("RAY_TPU_WIRE", "proto")
    return WireConnection(
        conn,
        typed=mode in ("proto", "envelope"),
        packed=mode == "proto")
