"""Head dispatch shards: N independent lock domains for the hot paths.

The reference head dispatches from a C++ ``ClusterTaskManager`` built for
1M+ queued tasks and 10k+ actors
(``src/ray/raylet/scheduling/cluster_task_manager.h:41``); our fused
Python head serialized every dispatch on one registry RLock.  This module
splits the *dispatch key space* into shards:

- **actor tasks** shard by actor id: an actor's method queue, in-flight
  window, and concurrency-group windows live entirely inside its shard,
  so submissions and completions for different actors (different tenant
  connections, different reader threads) proceed in parallel and never
  touch the head lock on the hot path.
- **plain leased tasks** shard by target node: a node's runnable (ready)
  queue belongs to its shard; resource accounting stays under the head
  lock, the queue structure itself under the shard lock.

Lock ordering is fixed and witness-verified: the head ``node.registry``
lock always precedes any shard lock, and no thread ever holds two shard
locks at once.  Cross-shard operations — gang scheduling, slice repair,
actor death sweeps, cancel scans — take the head lock first and then
each shard lock one at a time, so the lockwitness graph stays acyclic;
``RAY_TPU_LOCKWITNESS=1`` proves it live (the locks come from
``locks.make_lock`` like every other head lock).

Shard count: ``RAY_TPU_HEAD_SHARDS`` (default 4; 1 restores the fused
behavior — useful for bisecting shard-sensitive bugs).
"""

from __future__ import annotations

import os
from typing import List

from ray_tpu._private.locks import make_lock

DEFAULT_SHARDS = 4


def shard_count() -> int:
    try:
        n = int(os.environ.get("RAY_TPU_HEAD_SHARDS", DEFAULT_SHARDS))
    except ValueError:
        n = DEFAULT_SHARDS
    return max(1, min(n, 64))


class Shard:
    """One dispatch lock domain."""

    __slots__ = ("index", "lock")

    def __init__(self, index: int):
        self.index = index
        # named per shard so the lockwitness order graph distinguishes
        # them (an ABBA between two shards must be visible as a cycle)
        self.lock = make_lock(f"node.shard{index}")


class ShardSet:
    """The head's shard table with stable key -> shard assignment."""

    def __init__(self, n: int = 0):
        self.n = n or shard_count()
        self.shards: List[Shard] = [Shard(i) for i in range(self.n)]

    def for_actor(self, actor_id: bytes) -> Shard:
        """An actor's home shard — stable for the actor's lifetime, so
        its FIFO queue and concurrency windows never migrate.  Keyed on
        the TAIL of the id: ids are a per-process random prefix + a
        counter (object_ref.new_id), so the head bytes are identical for
        every actor one driver creates — sharding on them would pile a
        whole tenant onto one shard."""
        # big-endian: the id's final byte (the counter's low byte, the
        # fastest-changing bit of entropy) must land in the LSB so
        # consecutive actors round-robin shards instead of aliasing
        return self.shards[int.from_bytes(actor_id[-4:], "big") % self.n]

    def for_node(self, node_id: str) -> Shard:
        """A node's home shard for its runnable queue.  Stable string
        hash (not ``hash()``: PYTHONHASHSEED must not move queues between
        head restarts that share persisted state)."""
        h = 0
        for ch in node_id:
            h = (h * 131 + ord(ch)) & 0xFFFFFFFF
        return self.shards[h % self.n]
