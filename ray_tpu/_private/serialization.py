"""Zero-copy serialization for objects crossing process boundaries.

Analog of the reference's ``SerializationContext``
(``python/ray/_private/serialization.py:92``) and its zero-copy numpy path
(``python/ray/_private/arrow_serialization.py``): we use pickle protocol 5
with out-of-band buffers so that large contiguous payloads (numpy arrays,
jax host arrays, bytes) are written directly into a shared-memory segment
and mapped back as zero-copy views on the consumer side.

Wire layout of a serialized object (one blob, possibly inside one shm
segment):

    [u64 meta_len][meta pickle][buffer 0][pad to 64][buffer 1]...

where ``meta pickle`` is the pickle-5 stream with ``PickleBuffer``s replaced
by indices, plus a table of (offset, length) for each out-of-band buffer.

ObjectRefs found inside values are serialized by id and re-hydrated on the
other side (the reference does this through its serialization context's
object-ref reducer so that the owner address travels with the ref).
"""

from __future__ import annotations

import io
import pickle
import struct
from typing import Any, List, Tuple

_ALIGN = 64
_HEADER = struct.Struct("<Q")


def _pad(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


import cloudpickle as _cloudpickle


class _OOBPickler(_cloudpickle.Pickler):
    """cloudpickle-based pickler (lambdas/closures work) that additionally
    collects out-of-band buffers and contained ObjectRefs."""

    def __init__(self, file, collected_refs: list):
        super().__init__(file, protocol=5, buffer_callback=self._buffer_cb)
        self.buffers: List[pickle.PickleBuffer] = []
        self._collected_refs = collected_refs

    def _buffer_cb(self, buf: pickle.PickleBuffer) -> bool:
        self.buffers.append(buf)
        return False  # do not serialize in-band

    def reducer_override(self, obj):
        from ray_tpu._private.object_ref import ObjectRef

        if isinstance(obj, ObjectRef):
            self._collected_refs.append(obj)
            return (_deserialize_object_ref, (obj.hex(),))
        # cloudpickle's own reducer_override handles functions/classes
        return super().reducer_override(obj)


def _deserialize_object_ref(hex_id: str):
    from ray_tpu._private.object_ref import ObjectRef
    from ray_tpu._private.worker import global_worker

    ref = ObjectRef.from_hex(hex_id)
    if global_worker.connected:
        # borrow registration: this process now holds a handle; the
        # enclosing container (task spec or sealed object) is still pinned
        # while we deserialize, so the add_ref cannot race the deletion
        return global_worker.track_ref(ref, owned=False)
    return ref


def serialize(value: Any) -> Tuple[bytes, List[memoryview], list]:
    """Serialize ``value``.

    Returns (meta_blob, raw_buffers, contained_object_refs).  ``meta_blob``
    is self-contained; ``raw_buffers`` must be written after it per the wire
    layout above.
    """
    f = io.BytesIO()
    refs: list = []
    p = _OOBPickler(f, refs)
    p.dump(value)
    payload = f.getvalue()
    # Non-contiguous buffers are rare (strided views); make them contiguous
    # once, then the table is just nbytes of each final buffer.
    out_views = []
    for b in p.buffers:
        try:
            v = b.raw()  # flat contiguous view; raises if non-contiguous
        except BufferError:
            v = memoryview(memoryview(b).tobytes())
        out_views.append(v)
    table = [v.nbytes for v in out_views]
    meta = pickle.dumps((payload, table), protocol=5)
    return meta, out_views, refs


def total_size(meta: bytes, buffers: List[memoryview]) -> int:
    n = _HEADER.size + _pad(len(meta))
    for b in buffers:
        n += _pad(b.nbytes)
    return n


def write_into(dest: memoryview, meta: bytes, buffers: List[memoryview]) -> int:
    """Write the wire layout into ``dest`` (e.g. an shm buffer). Returns bytes written."""
    off = 0
    _HEADER.pack_into(dest, off, len(meta))
    off += _HEADER.size
    dest[off : off + len(meta)] = meta
    off = _HEADER.size + _pad(len(meta))
    for b in buffers:
        dest[off : off + b.nbytes] = b
        off += _pad(b.nbytes)
    return off


# A single writer thread hits the tmpfs page-allocation ceiling well below
# memory bandwidth; os.pwrite releases the GIL, so sharding one huge buffer
# across a few threads overlaps shmem page allocation + copy.  Past ~8
# writers the shmem lock serializes them (measured plateau), so cap there.
_PAR_WRITE_MIN = 512 << 20  # parallelize only multi-100MB buffers
_PAR_WRITE_THREADS = 8
_PAR_WRITE_CHUNK = 256 << 20  # per-syscall cap (far below pwrite's 2 GiB)


def _pwrite_span(fd: int, view: memoryview, pos: int, end: int,
                 base: int) -> None:
    """pwrite ``view[pos:end]`` at file offset ``base + pos``."""
    import os

    while pos < end:
        pos += os.pwrite(fd, view[pos:min(end, pos + _PAR_WRITE_CHUNK)],
                         base + pos)


def _pwrite_buffer(fd: int, view: memoryview, file_off: int) -> None:
    """Write one buffer at ``file_off``, sharded across threads when big
    enough for the parallelism to win."""
    import concurrent.futures

    n = view.nbytes
    if n < _PAR_WRITE_MIN:
        _pwrite_span(fd, view, 0, n, file_off)
        return
    nt = _PAR_WRITE_THREADS
    shard = (n + nt - 1) // nt
    with concurrent.futures.ThreadPoolExecutor(nt) as ex:
        list(ex.map(lambda i: _pwrite_span(
            fd, view, i * shard, min(n, (i + 1) * shard), file_off),
            range(nt)))


def write_to_fd(fd: int, meta: bytes, buffers: List[memoryview]) -> int:
    """Write the wire layout straight to ``fd`` with ``os.write``.

    On tmpfs this is ~2.4x faster than memcpy into a fresh mmap: the write
    syscall allocates pages directly instead of zero-filling each page and
    then faulting it in again for the copy.  Multi-100MB buffers shard
    across pwrite threads (see ``_pwrite_buffer``).  Returns bytes
    written."""
    import os

    off = 0

    def put(view) -> None:
        nonlocal off
        view = memoryview(view).cast("B")
        if view.nbytes >= _PAR_WRITE_MIN:
            _pwrite_buffer(fd, view, off)
            off += view.nbytes
            os.lseek(fd, off, os.SEEK_SET)  # keep the cursor in sync
            return
        while view.nbytes:
            n = os.write(fd, view)
            off += n
            view = view[n:]

    put(_HEADER.pack(len(meta)))
    put(meta)
    pad = _pad(len(meta)) - len(meta)  # matches write_into's layout
    if pad:
        put(b"\0" * pad)
    for b in buffers:
        put(b)
        rem = _pad(b.nbytes) - b.nbytes
        if rem:
            put(b"\0" * rem)
    return off


def write_to_fd_at(fd: int, offset: int, meta: bytes,
                   buffers: List[memoryview]) -> int:
    """Write the wire layout at ``offset`` of ``fd`` with ``os.pwrite``.

    The arena's big-object path: one pass over the payload through the
    file write path instead of memcpy into the arena mmap.  On a fresh
    (never-faulted) arena region the mmap path pays a userspace page
    fault + kernel zero-fill + copy per 4 KiB page — on multi-GiB values
    (checkpoint-sized blocks) that fault loop is the 45x put cliff.
    pwrite allocates and fills each tmpfs page in one kernel pass, stays
    page-cache-coherent with every reader's mmap of the arena, and chunks
    below the ~2 GiB single-syscall cap.  Multi-100MB buffers shard across
    pwrite threads (see ``_pwrite_buffer``).  Returns bytes written."""
    pos = offset

    def put(view) -> None:
        nonlocal pos
        view = memoryview(view).cast("B")
        _pwrite_buffer(fd, view, pos)
        pos += view.nbytes

    put(_HEADER.pack(len(meta)))
    put(meta)
    pad = _pad(len(meta)) - len(meta)
    if pad:
        put(b"\0" * pad)
    for b in buffers:
        put(b)
        rem = _pad(b.nbytes) - b.nbytes
        if rem:
            put(b"\0" * rem)
    return pos - offset


def to_bytes(meta: bytes, buffers: List[memoryview]) -> bytes:
    out = bytearray(total_size(meta, buffers))
    write_into(memoryview(out), meta, buffers)
    return bytes(out)


def deserialize(src: memoryview) -> Any:
    """Deserialize from the wire layout; buffers are zero-copy views of
    ``src``, so they live exactly as long as ``src``'s exporting object
    (the arena store passes a pinned mmap — see ``_pinned_arena_slice``)."""
    (meta_len,) = _HEADER.unpack_from(src, 0)
    meta = bytes(src[_HEADER.size : _HEADER.size + meta_len])
    payload, table = pickle.loads(meta)
    off = _HEADER.size + _pad(meta_len)
    bufs = []
    for n in table:
        bufs.append(pickle.PickleBuffer(src[off : off + n]))
        off += _pad(n)
    return pickle.loads(payload, buffers=bufs)
