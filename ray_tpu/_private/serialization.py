"""Zero-copy serialization for objects crossing process boundaries.

Analog of the reference's ``SerializationContext``
(``python/ray/_private/serialization.py:92``) and its zero-copy numpy path
(``python/ray/_private/arrow_serialization.py``): we use pickle protocol 5
with out-of-band buffers so that large contiguous payloads (numpy arrays,
jax host arrays, bytes) are written directly into a shared-memory segment
and mapped back as zero-copy views on the consumer side.

Wire layout of a serialized object (one blob, possibly inside one shm
segment):

    [u64 meta_len][meta pickle][buffer 0][pad to 64][buffer 1]...

where ``meta pickle`` is the pickle-5 stream with ``PickleBuffer``s replaced
by indices, plus a table of (offset, length) for each out-of-band buffer.

ObjectRefs found inside values are serialized by id and re-hydrated on the
other side (the reference does this through its serialization context's
object-ref reducer so that the owner address travels with the ref).
"""

from __future__ import annotations

import io
import pickle
import struct
from typing import Any, Callable, List, Tuple

_ALIGN = 64
_HEADER = struct.Struct("<Q")


def _pad(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


import cloudpickle as _cloudpickle


class _OOBPickler(_cloudpickle.Pickler):
    """cloudpickle-based pickler (lambdas/closures work) that additionally
    collects out-of-band buffers and contained ObjectRefs."""

    def __init__(self, file, collected_refs: list):
        super().__init__(file, protocol=5, buffer_callback=self._buffer_cb)
        self.buffers: List[pickle.PickleBuffer] = []
        self._collected_refs = collected_refs

    def _buffer_cb(self, buf: pickle.PickleBuffer) -> bool:
        self.buffers.append(buf)
        return False  # do not serialize in-band

    def reducer_override(self, obj):
        from ray_tpu._private.object_ref import ObjectRef

        if isinstance(obj, ObjectRef):
            self._collected_refs.append(obj)
            return (_deserialize_object_ref, (obj.hex(),))
        # cloudpickle's own reducer_override handles functions/classes
        return super().reducer_override(obj)


def _deserialize_object_ref(hex_id: str):
    from ray_tpu._private.object_ref import ObjectRef
    from ray_tpu._private.worker import global_worker

    ref = ObjectRef.from_hex(hex_id)
    if global_worker.connected:
        # borrow registration: this process now holds a handle; the
        # enclosing container (task spec or sealed object) is still pinned
        # while we deserialize, so the add_ref cannot race the deletion
        return global_worker.track_ref(ref, owned=False)
    return ref


def serialize(value: Any) -> Tuple[bytes, List[memoryview], list]:
    """Serialize ``value``.

    Returns (meta_blob, raw_buffers, contained_object_refs).  ``meta_blob``
    is self-contained; ``raw_buffers`` must be written after it per the wire
    layout above.
    """
    f = io.BytesIO()
    refs: list = []
    p = _OOBPickler(f, refs)
    p.dump(value)
    payload = f.getvalue()
    # Non-contiguous buffers are rare (strided views); make them contiguous
    # once, then the table is just nbytes of each final buffer.
    out_views = []
    for b in p.buffers:
        try:
            v = b.raw()  # flat contiguous view; raises if non-contiguous
        except BufferError:
            v = memoryview(memoryview(b).tobytes())
        out_views.append(v)
    table = [v.nbytes for v in out_views]
    meta = pickle.dumps((payload, table), protocol=5)
    return meta, out_views, refs


def total_size(meta: bytes, buffers: List[memoryview]) -> int:
    n = _HEADER.size + _pad(len(meta))
    for b in buffers:
        n += _pad(b.nbytes)
    return n


def write_into(dest: memoryview, meta: bytes, buffers: List[memoryview]) -> int:
    """Write the wire layout into ``dest`` (e.g. an shm buffer). Returns bytes written."""
    off = 0
    _HEADER.pack_into(dest, off, len(meta))
    off += _HEADER.size
    dest[off : off + len(meta)] = meta
    off = _HEADER.size + _pad(len(meta))
    for b in buffers:
        dest[off : off + b.nbytes] = b
        off += _pad(b.nbytes)
    return off


def write_to_fd(fd: int, meta: bytes, buffers: List[memoryview]) -> int:
    """Write the wire layout straight to ``fd`` with ``os.write``.

    On tmpfs this is ~2.4x faster than memcpy into a fresh mmap: the write
    syscall allocates pages directly instead of zero-filling each page and
    then faulting it in again for the copy.  Returns bytes written."""
    import os

    off = 0

    def put(view) -> None:
        nonlocal off
        view = memoryview(view).cast("B")
        while view.nbytes:
            n = os.write(fd, view)
            off += n
            view = view[n:]

    put(_HEADER.pack(len(meta)))
    put(meta)
    pad = _pad(len(meta)) - len(meta)  # matches write_into's layout
    if pad:
        put(b"\0" * pad)
    for b in buffers:
        put(b)
        rem = _pad(b.nbytes) - b.nbytes
        if rem:
            put(b"\0" * rem)
    return off


def to_bytes(meta: bytes, buffers: List[memoryview]) -> bytes:
    out = bytearray(total_size(meta, buffers))
    write_into(memoryview(out), meta, buffers)
    return bytes(out)


def deserialize(src: memoryview, wrap_buffer: Optional[Callable] = None) -> Any:
    """Deserialize from the wire layout; buffers are zero-copy views of
    ``src``.  ``wrap_buffer`` (view -> buffer-protocol object) interposes
    on every out-of-band buffer — the arena store uses it to pin the
    backing object alive for as long as any deserialized view exists."""
    (meta_len,) = _HEADER.unpack_from(src, 0)
    meta = bytes(src[_HEADER.size : _HEADER.size + meta_len])
    payload, table = pickle.loads(meta)
    off = _HEADER.size + _pad(meta_len)
    bufs = []
    for n in table:
        view = src[off : off + n]
        if wrap_buffer is not None:
            view = memoryview(wrap_buffer(view))
        bufs.append(pickle.PickleBuffer(view))
        off += _pad(n)
    return pickle.loads(payload, buffers=bufs)
