"""Object store: registry + producer/consumer helpers.

Splits the reference's design across the same seams:

- ``ObjectRegistry`` lives in the head process and plays the role of the
  plasma store's directory + ``ObjectLifecycleManager``
  (``src/ray/object_manager/plasma/store.h:55``,
  ``object_lifecycle_manager.h:101``) plus the owner-side
  ``ReferenceCounter`` (``src/ray/core_worker/reference_count.h:61``):
  object id -> location, sealing, sizes, reference counts (handle refs +
  contained-in-object refs + task-spec pins), eviction-by-spilling at the
  ``object_store_memory`` cap (``local_object_manager.h:41`` analog), and
  segment unlinking when the count hits zero.
- Producers (workers/driver) serialize into a fresh shm segment themselves
  and then *seal* it with the registry — the plasma create/seal protocol
  without copying payloads through a socket.
- Small objects are carried inline, the analog of the core worker's
  in-process memory store for direct returns
  (``src/ray/core_worker/store_provider/memory_store/memory_store.h``).

Each consumer process keeps attached segments alive in ``_ATTACHED`` for the
life of the process, like plasma clients holding their mmaps (zero-copy
views of values alias the mapping, so it cannot be unmapped eagerly).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple, Union

from ray_tpu._private import events as _events
from ray_tpu._private import serialization
from ray_tpu._private.config import get_config
from ray_tpu._private.locks import make_lock
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.shm import ShmSegment, session_shm_name

# Lazy put/get latency histograms (registered on first use; observation is
# skipped entirely when the observability layer is disabled).
_STORE_METRICS = None
# shm puts at least this big get a flight-recorder event (arena/ingest
# pressure visibility without an event per small put)
_PUT_EVENT_MIN_BYTES = 1 << 20
# Payloads below this observe their latency 1:_SMALL_SAMPLE (a histogram
# lock on EVERY inline return/get rides the task hot path; big payloads —
# the interesting tail — always record).  Unlocked counters: a lost race
# just shifts which call samples.
_SMALL_SAMPLE_MAX_BYTES = 64 << 10
_SMALL_SAMPLE = 8
_put_n = 0
_get_n = 0


def _store_metrics():
    global _STORE_METRICS
    if _STORE_METRICS is None:
        from ray_tpu.util.metrics import Histogram

        bounds = [0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5]
        note = " (payloads <64KiB sampled 1:8)"
        _STORE_METRICS = {
            "put": Histogram("ray_tpu_object_put_latency_s",
                             "serialize+store latency per object (s)" + note,
                             boundaries=bounds),
            "get": Histogram("ray_tpu_object_get_latency_s",
                             "attach+deserialize latency per object (s)" + note,
                             boundaries=bounds),
        }
    return _STORE_METRICS


@dataclass
class ObjectLocation:
    """Where an object's payload lives.  Exactly one of inline/shm_name/
    spilled_path is set."""

    inline: Optional[bytes] = None
    shm_name: Optional[str] = None
    spilled_path: Optional[str] = None
    size: int = 0
    # Serialized error objects raise on get (RayTaskError analog).
    is_error: bool = False
    # Which cluster node holds the shm segment ("" = head) and that node's
    # object-server address — consumers on other nodes pull through it
    # (the head fills fetch_addr when serving locations cross-node).
    node_id: str = ""
    fetch_addr: Optional[tuple] = None
    # Native arena backing (plasma analog): the payload is the
    # [arena_off, arena_off+size) slice of the arena file.  shm_name is
    # still set — it names the pulled copy on remote consumers.
    arena_path: Optional[str] = None
    arena_off: int = 0
    # the arena index key (== oid normally; a fresh key when a retried
    # task re-produced a return whose first attempt left an allocation)
    arena_key: Optional[bytes] = None

    def __post_init__(self):
        if self.inline is not None:
            self.size = len(self.inline)

    def __reduce__(self):
        # Locations ride in every seal/location-reply message; positional
        # reconstruction skips dataclass-by-__dict__ pickling (~3x faster,
        # and the common inline case pickles only two live fields).
        return (ObjectLocation, (
            self.inline, self.shm_name, self.spilled_path, self.size,
            self.is_error, self.node_id, self.fetch_addr, self.arena_path,
            self.arena_off, self.arena_key,
        ))


@dataclass
class _Entry:
    loc: Optional[ObjectLocation] = None
    sealed: threading.Event = field(default_factory=threading.Event)
    # handle refs (one per process holding live ObjectRefs) + contained-in-
    # object refs + task-spec pins; starts at 1 for the creator's handle
    ref_count: int = 1
    contained: List[bytes] = field(default_factory=list)
    last_access: float = field(default_factory=time.monotonic)
    # ownership audit (`ray memory` analog): who sealed the payload —
    # "driver", a worker id hex, or an actor id hex — plus wall-clock
    # creation time for age and a per-reason pin breakdown.  pins is
    # ADVISORY accounting layered over ref_count (the lifetime source of
    # truth): it answers "why is this still alive", not "is it alive".
    owner: Optional[str] = None
    owner_kind: str = "unknown"  # driver | worker | actor | head
    created: float = field(default_factory=time.time)
    pins: Dict[str, int] = field(default_factory=lambda: {"handle": 1})
    # location SET (ownership_based_object_directory.h:37 analog): nodes
    # holding a pulled copy of the payload, node_id -> object-server addr.
    # Sources for future pulls; survivors when the origin node dies.
    replicas: Dict[str, tuple] = field(default_factory=dict)
    # round-robin cursor over {origin} + replicas for pull load-spreading
    rr: int = 0


# Objects touched within this window are not spill candidates — closes the
# race where a get reply carrying an shm location is in flight while the
# head spills the segment out from under the consumer.
#
# Why eviction candidate selection is safe PYTHON-side (vs the reference's
# in-store eviction_policy.h): the native arena is single-writer — only
# the head process allocates/frees (store_core.cc's contract), and every
# registry mutation (create/seal/pin/spill) happens under this registry's
# lock in that same process.  A concurrent seal therefore cannot race a
# spill decision: both serialize on self._lock, and the C layer is only
# ever called while it is held.  Readers in other processes see sealed
# slices via control-plane locations and are protected by the idle window
# + pin counts, not by store-internal locking.
_SPILL_MIN_IDLE_S = 5.0


class ObjectRegistry:
    """Head-process directory of all objects in the session."""

    def __init__(self, capacity_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        self._lock = make_lock("object_store.registry")
        self._objects: Dict[bytes, _Entry] = {}
        self._bytes_used = 0  # head-local shm bytes (spilled/inline/remote don't count)
        self._capacity = capacity_bytes
        self._spill_dir = spill_dir
        self._num_spilled = 0
        # incrementally-maintained ownership aggregate: (owner, kind) ->
        # [bytes, objects] over SEALED entries, adjusted at seal /
        # node-loss unseal / delete.  owner_summary() reads it in
        # O(owners) — the every-5s gauge refresh and /metrics scrape must
        # never scan the full object table under this lock.
        self._owner_agg: Dict[Tuple[str, str], list] = {}
        # set by the head Node: shm_name -> ask every node agent to unlink.
        # Any node may hold the origin segment OR a pulled replica, so
        # deletion broadcasts (the head's own copy/replica is unlinked
        # locally either way).
        self.broadcast_unlink = None
        # set by the head Node when the native arena backs local objects:
        # oid -> free the arena allocation
        self.arena_delete = None
        # set by the head Node: called (without the registry lock) for each
        # fully-deleted object so lineage entries die with the object
        self.on_delete = None

    # -- creation / sealing --------------------------------------------
    def create_pending(self, oid: bytes) -> None:
        """Declare an object that a task will produce (return slot)."""
        with self._lock:
            self._objects.setdefault(oid, _Entry())

    def seal(self, oid: bytes, loc: ObjectLocation,
             contained: Optional[List[bytes]] = None,
             only_if_live: bool = False, owner: Optional[str] = None,
             owner_kind: Optional[str] = None) -> bool:
        """Seal ``oid`` with ``loc``.  With ``only_if_live``, a concurrent
        deletion wins atomically: the prepared payload is discarded instead
        of resurrecting the entry (returns False).  Plain seal returns True."""
        unlink = None
        dead: List[bytes] = []
        missed = False
        with self._lock:
            if only_if_live:
                e = self._objects.get(oid)
            else:
                e = self._objects.setdefault(oid, _Entry())
            if e is None:
                # entry died between the caller's decision and this seal:
                # reap the orphaned payload (outside the lock — reap
                # callbacks may take the node lock), don't resurrect
                missed = True
                if loc.arena_path:
                    dead.append(("arena", (loc.arena_key, loc.shm_name)))
                elif loc.shm_name:
                    dead.append(("shm", loc.shm_name))
                elif loc.spilled_path:
                    dead.append(("file", loc.spilled_path))
            elif e.loc is not None:
                # First seal wins (objects are immutable).  A re-seal happens
                # when a task retried after its worker sealed a return and
                # then crashed — drop the duplicate payload.  Checked and
                # set under the lock so two concurrent seals can't both win.
                if loc.arena_path:
                    dead.append(("arena", (loc.arena_key, None)))
                    unlink = None
                elif e.loc is not None and loc.shm_name == e.loc.shm_name:
                    unlink = None  # same segment as the winner: never unlink
                else:
                    unlink = loc.shm_name
            else:
                e.loc = loc
                e.contained = list(contained or [])
                # first seal records the producer as owner; a re-seal after
                # lineage reconstruction keeps the original attribution
                if owner is not None and e.owner is None:
                    e.owner = owner
                    e.owner_kind = owner_kind or "unknown"
                e.created = time.time()
                self._owner_agg_add(e, 1)
                for c in e.contained:
                    ce = self._objects.get(c)
                    if ce is not None:
                        ce.ref_count += 1
                        ce.pins["contained"] = ce.pins.get("contained", 0) + 1
                if loc.shm_name and not loc.node_id:
                    self._bytes_used += loc.size
            if not missed:
                e.sealed.set()
                if e.ref_count <= 0:
                    # every handle died before the producer finished (fire-
                    # and-forget): reclaim immediately
                    self._delete_locked(oid, e, dead)
        if unlink:
            self._reap([("shm", unlink)])
        self._reap(dead)
        self._maybe_spill()
        return not missed

    def mark_node_lost(self, node_id: str) -> List[bytes]:
        """Un-seal every object whose only copy lived on a dead node, so
        lineage reconstruction (or an ObjectLostError seal) can refill the
        slot; consumers block on the cleared event meanwhile.  Returns the
        lost oids (reference: ObjectRecoveryManager's lost-object scan,
        ``object_recovery_manager.h:41``)."""
        if not node_id:
            return []  # head-local objects die with the session, not here
        lost: List[bytes] = []
        dead: List[tuple] = []
        with self._lock:
            # snapshot: dropping containment refs below can delete entries
            for oid, e in list(self._objects.items()):
                if oid not in self._objects:
                    continue  # deleted by an earlier iteration's ref drop
                e.replicas.pop(node_id, None)
                if e.loc is not None and e.loc.node_id == node_id:
                    if e.replicas:
                        # a surviving copy exists: promote it to primary —
                        # no un-seal, no lineage reconstruction (the payoff
                        # of the location set)
                        nid, addr = next(iter(e.replicas.items()))
                        del e.replicas[nid]
                        e.loc = ObjectLocation(
                            shm_name=e.loc.shm_name, size=e.loc.size,
                            is_error=e.loc.is_error, node_id=nid,
                            fetch_addr=tuple(addr))
                        continue
                    # drop contained-ref increments this payload made; a
                    # successful re-seal will re-add them
                    for c in e.contained:
                        self._remove_ref_locked(c, 1, dead, "contained")
                    e.contained = []
                    self._owner_agg_add(e, -1)  # a re-seal re-adds
                    e.loc = None
                    e.sealed = threading.Event()  # fresh event: old waiters
                    # saw the sealed one; new waiters block until refill
                    lost.append(oid)
        self._reap(dead)
        return lost

    def contains(self, oid: bytes) -> bool:
        with self._lock:
            return oid in self._objects

    # -- lookup --------------------------------------------------------
    def is_sealed(self, oid: bytes) -> bool:
        with self._lock:
            e = self._objects.get(oid)
        return e is not None and e.sealed.is_set()

    def wait_sealed_existing(
        self, oid: bytes, timeout: Optional[float]
    ) -> Union[ObjectLocation, None, str]:
        """Like :meth:`wait_sealed` but never creates an entry: returns the
        sentinel ``"missing"`` for unknown/deleted oids instead of parking a
        phantom _Entry nobody owns (thin-client get path)."""
        with self._lock:
            e = self._objects.get(oid)
        if e is None:
            return "missing"
        if not e.sealed.wait(timeout):
            return None
        e.last_access = time.monotonic()
        return e.loc

    def wait_sealed(self, oid: bytes, timeout: Optional[float]) -> Optional[ObjectLocation]:
        with self._lock:
            e = self._objects.setdefault(oid, _Entry())
        if not e.sealed.wait(timeout):
            return None
        e.last_access = time.monotonic()
        return e.loc

    def get_location(self, oid: bytes,
                     prefer_node: Optional[str] = None) -> Optional[ObjectLocation]:
        """Location for a consumer.  ``prefer_node`` is the consumer's node
        ("" = head / emulated): a copy on the consumer's own node wins
        (zero-copy attach); otherwise the pull source round-robins across
        origin + replicas (the location-set payoff: reads spread over every
        node holding a copy)."""
        with self._lock:
            e = self._objects.get(oid)
            if e is None or not e.sealed.is_set():
                return None
            e.last_access = time.monotonic()
            loc = e.loc
            if not (e.replicas and loc is not None and loc.shm_name
                    and loc.fetch_addr):
                return loc
            origin_node = loc.node_id or ""
            if prefer_node is not None:
                if prefer_node == origin_node:
                    return loc  # own-node origin (incl. head arena payloads)
                if prefer_node in e.replicas:
                    return self._replica_loc(loc, prefer_node,
                                             e.replicas[prefer_node])
            sources = [(origin_node, loc.fetch_addr)] + list(e.replicas.items())
            nid, addr = sources[e.rr % len(sources)]
            e.rr += 1
            if nid == origin_node:
                return loc
            return self._replica_loc(loc, nid, addr)

    @staticmethod
    def _replica_loc(loc: ObjectLocation, node_id: str, addr) -> ObjectLocation:
        # replicas are plain files — no arena fields
        return ObjectLocation(
            shm_name=loc.shm_name, size=loc.size, is_error=loc.is_error,
            node_id=node_id, fetch_addr=tuple(addr))

    def add_replica(self, oid: bytes, node_id: str, fetch_addr) -> None:
        """Record that ``node_id`` now holds a pulled copy (location-set
        update; reported by consumers after a successful pull or by the
        broadcast fan-out)."""
        if not node_id or not fetch_addr:
            return
        with self._lock:
            e = self._objects.get(oid)
            if (
                e is not None and e.loc is not None and e.loc.shm_name
                and node_id != e.loc.node_id
            ):
                e.replicas[node_id] = tuple(fetch_addr)

    def replica_nodes(self, oid: bytes) -> List[str]:
        with self._lock:
            e = self._objects.get(oid)
            return list(e.replicas) if e is not None else []

    # -- reference counting --------------------------------------------
    def add_ref(self, oid: bytes, n: int = 1, reason: str = "handle") -> None:
        """``reason`` feeds the audit's pin breakdown ("handle" = a live
        ObjectRef somewhere, "task_arg" = pinned by a pending task's spec,
        "contained" = referenced inside another sealed object)."""
        with self._lock:
            e = self._objects.get(oid)
            if e is not None:
                e.ref_count += n
                e.pins[reason] = e.pins.get(reason, 0) + n

    def remove_ref(self, oid: bytes, n: int = 1,
                   reason: str = "handle") -> None:
        """Owner-side count decrement; deletes (and cascades to contained
        refs) at zero.  Unsealed entries linger at count<=0 until their
        producer seals, then reclaim immediately."""
        dead: List[bytes] = []
        with self._lock:
            self._remove_ref_locked(oid, n, dead, reason)
        self._reap(dead)

    def _remove_ref_locked(self, oid: bytes, n: int, dead: List[bytes],
                           reason: str = "handle") -> None:
        e = self._objects.get(oid)
        if e is None:
            return
        e.ref_count -= n
        left = e.pins.get(reason, 0) - n
        if left > 0:
            e.pins[reason] = left
        else:
            e.pins.pop(reason, None)
        if e.ref_count <= 0 and e.sealed.is_set():
            self._delete_locked(oid, e, dead)

    def _owner_agg_add(self, e: "_Entry", n: int) -> None:
        """Adjust the sealed-bytes-per-owner aggregate by ``n`` objects
        of the entry's current size (lock held; n is +1 on seal, -1 on
        unseal/delete — explicit, never inferred from a size sign that a
        zero-byte payload would break).  An object counts exactly while
        it is sealed with a location — the same filter a full
        owner_summary() scan would apply."""
        key = (e.owner or "unknown", e.owner_kind)
        agg = self._owner_agg.get(key)
        if agg is None:
            agg = self._owner_agg[key] = [0, 0]
        agg[0] += n * e.loc.size
        agg[1] += n
        if agg[1] <= 0:
            del self._owner_agg[key]

    def _delete_locked(self, oid: bytes, e: _Entry, dead: List[tuple]) -> None:
        if e.loc is not None and e.sealed.is_set():
            self._owner_agg_add(e, -1)
        if e.loc is not None:
            if e.loc.arena_path:
                dead.append(("arena", (e.loc.arena_key, e.loc.shm_name)))
                if not e.loc.node_id:
                    self._bytes_used -= e.loc.size
            elif e.loc.shm_name:
                dead.append(("shm", e.loc.shm_name))
                if not e.loc.node_id:
                    self._bytes_used -= e.loc.size
            elif e.loc.spilled_path:
                dead.append(("file", e.loc.spilled_path))
        del self._objects[oid]
        for c in e.contained:
            self._remove_ref_locked(c, 1, dead, "contained")
        if self.on_delete is not None:
            dead.append(("hook", oid))

    def _reap(self, dead: List[tuple]) -> None:
        for kind, name in dead:
            if kind == "hook":
                if self.on_delete is not None:
                    self.on_delete(name)
            elif kind == "file":
                try:
                    os.unlink(name)
                except OSError:
                    pass
            elif kind == "arena":
                arena_key, copy_name = name
                if self.arena_delete is not None and arena_key:
                    self.arena_delete(arena_key)
                if copy_name:  # remote pulled copies use the shm name
                    ShmSegment.unlink(copy_name)
                    if self.broadcast_unlink is not None:
                        self.broadcast_unlink(copy_name)
            else:
                # origin copy or pulled replica in this process's namespace
                ShmSegment.unlink(name)
                if self.broadcast_unlink is not None:
                    self.broadcast_unlink(name)

    # -- capacity / spilling -------------------------------------------
    def _maybe_spill(self) -> None:
        """Move least-recently-accessed shm objects to disk until under the
        capacity (plasma eviction + LocalObjectManager spill analog).
        Spilled objects stay gettable — consumers read the file."""
        if self._capacity is None or self._spill_dir is None:
            return
        while True:
            with self._lock:
                if self._bytes_used <= self._capacity:
                    return
                now = time.monotonic()
                candidates = [
                    (e.last_access, oid, e)
                    for oid, e in self._objects.items()
                    if e.sealed.is_set() and e.loc is not None and e.loc.shm_name
                    and not e.loc.node_id  # remote segments aren't local files
                    and not e.loc.arena_path  # arena slices spill via delete
                    and now - e.last_access >= _SPILL_MIN_IDLE_S
                ]
                if not candidates:
                    return  # everything hot; stay over cap rather than race
                candidates.sort()
                _, oid, e = candidates[0]
                shm_name, size = e.loc.shm_name, e.loc.size
            os.makedirs(self._spill_dir, exist_ok=True)
            path = os.path.join(self._spill_dir, oid.hex())
            try:
                seg = ShmSegment.attach(shm_name, size)
                try:
                    with open(path, "wb") as f:
                        f.write(seg.buf)
                finally:
                    seg.close()
            except OSError:
                return
            with self._lock:
                e2 = self._objects.get(oid)
                if e2 is None or e2.loc is None or e2.loc.shm_name != shm_name:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    continue  # deleted concurrently
                e2.loc.shm_name = None
                e2.loc.spilled_path = path
                had_replicas = bool(e2.replicas)
                e2.replicas.clear()
                self._bytes_used -= size
                self._num_spilled += 1
                bytes_used = self._bytes_used
            _events.emit("object_store", "spilled object to disk",
                         severity="WARNING", entity_id=oid.hex(),
                         size_mb=round(size / (1 << 20), 2),
                         bytes_used=bytes_used, capacity=self._capacity)
            ShmSegment.unlink(shm_name)
            if had_replicas and self.broadcast_unlink is not None:
                # replica copies share the segment name on other nodes;
                # after the spill nothing would ever reap them (delete only
                # sees the spilled file) — unlink them with the original
                self.broadcast_unlink(shm_name)

    @staticmethod
    def _where(e: "_Entry") -> str:
        loc = e.loc
        if loc is None:
            return "pending"
        if loc.inline is not None:
            return "inline"
        if loc.spilled_path:
            return "spilled"
        return loc.node_id or "head"

    @staticmethod
    def _pin_reason(e: "_Entry") -> str:
        """The dominant reason this object is still alive, in pin-strength
        order: a task-spec pin outlives handles, containment outlives a
        dropped handle."""
        for reason in ("task_arg", "lineage", "contained", "handle"):
            if e.pins.get(reason, 0) > 0:
                return reason
        return "unknown"

    # -- admin ---------------------------------------------------------
    def list_objects(self, limit: int = 1000) -> List[dict]:
        """State-API view of the object directory (list_objects analog)."""
        import itertools

        now = time.time()
        out = []
        with self._lock:
            for oid, e in itertools.islice(self._objects.items(), limit):
                loc = e.loc
                out.append({
                    "object_id": oid.hex(),
                    "sealed": e.sealed.is_set(),
                    "ref_count": e.ref_count,
                    "size": loc.size if loc else None,
                    "where": self._where(e),
                    "owner": e.owner,
                    "owner_kind": e.owner_kind,
                    "pin_reason": self._pin_reason(e),
                    "age_s": round(now - e.created, 1),
                })
        return out

    def memory_audit(self) -> List[dict]:
        """Every SEALED object with ownership/pin detail — the raw rows of
        the ``ray memory`` table.  Rows are fully materialized under the
        lock (pins is a live dict a concurrent add_ref mutates; copying
        it outside would race), sorted outside."""
        now = time.time()
        with self._lock:
            rows = [{
                "object_id": oid.hex(),
                "size": e.loc.size,
                "where": self._where(e),
                "owner": e.owner or "unknown",
                "owner_kind": e.owner_kind,
                "ref_count": e.ref_count,
                "pins": dict(e.pins),
                "pin_reason": self._pin_reason(e),
                "age_s": round(now - e.created, 1),
            } for oid, e in self._objects.items()
                if e.sealed.is_set() and e.loc is not None]
        rows.sort(key=lambda r: -r["size"])
        return rows

    def owner_summary(self) -> Dict[tuple, dict]:
        """Sealed bytes/objects by (owner, kind) from the incrementally-
        maintained aggregate — O(owners), never a table scan.  The shape
        the every-5s gauge refresh and ``top`` need; per-object rows and
        the pin-reason breakdown come from :meth:`memory_audit` (the
        explicit ``ray_tpu memory`` ask)."""
        with self._lock:
            return {key: {"bytes": agg[0], "objects": agg[1]}
                    for key, agg in self._owner_agg.items()}

    def stats(self) -> dict:
        with self._lock:
            return {
                "num_objects": len(self._objects),
                "bytes_used": self._bytes_used,
                "num_spilled": self._num_spilled,
            }

    def all_shm_names(self) -> List[str]:
        with self._lock:
            return [e.loc.shm_name for e in self._objects.values() if e.loc and e.loc.shm_name]

    def shutdown(self) -> None:
        for name in self.all_shm_names():
            ShmSegment.unlink(name)
        with self._lock:
            spilled = [e.loc.spilled_path for e in self._objects.values()
                       if e.loc and e.loc.spilled_path]
            self._objects.clear()
        for p in spilled:
            try:
                os.unlink(p)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# Producer / consumer helpers (run in any process)
# ---------------------------------------------------------------------------

_ATTACHED: Dict[str, ShmSegment] = {}
_ATTACHED_LOCK = make_lock("object_store.attached")


# Owner-side native arena (plasma analog); the head process sets this at
# Node init.  Worker processes keep the per-object-file path.
_OWNED_ARENA = None

# Above this size an arena put writes through the arena fd (os.pwrite,
# one kernel pass per page) instead of memcpy into the mapping: on
# never-faulted arena pages the mmap path pays fault+zero+copy per 4 KiB
# page, which is the 45x cliff multi-GiB (checkpoint-sized) values hit.
# Recycled (already-faulted) pages favor memcpy, and sub-64 MB objects
# mostly land on recycled slots, so the threshold keeps them there.
try:
    _ARENA_FD_WRITE_MIN = int(os.environ.get(
        "RAY_TPU_ARENA_FD_WRITE_MIN", str(64 << 20)))
except ValueError:  # malformed override: keep the default, don't die at import
    _ARENA_FD_WRITE_MIN = 64 << 20
# reader-side cache: arena path -> memoryview over its mmap
_ARENA_MAPS: Dict[str, memoryview] = {}
_ARENA_MAPS_LOCK = make_lock("object_store.arena_maps")


def set_owned_arena(arena) -> None:
    global _OWNED_ARENA
    _OWNED_ARENA = arena


class _ArenaPin:
    """Holds one head-side reference on an arena object for as long as any
    zero-copy view of it is alive (the plasma client-pin analog: the slot
    cannot be recycled under a live numpy array)."""

    __slots__ = ("_oid",)

    def __init__(self, oid: bytes):
        self._oid = oid

    def __del__(self):
        try:
            from ray_tpu._private.worker import global_worker

            client = global_worker.client
            if client is not None and not client.closed:
                client.remove_refs([self._oid])
        except Exception:
            pass


class _PinnedArenaMap(__import__("mmap").mmap):
    """mmap subclass that can carry attributes — see
    :func:`_pinned_arena_slice`."""


def _pinned_arena_slice(path: str, off: int, size: int,
                        pin: _ArenaPin) -> memoryview:
    """A zero-copy view of ``[off, off+size)`` of the arena file whose
    buffer chain owns ``pin``: a private mmap subclass instance carries the
    pin as an attribute, every exported memoryview keeps its exporting
    mmap alive, and the mmap's deallocation drops the pin — so the
    head-side reference lives exactly as long as any deserialized view
    (numpy array, bytes slice) over this object.  Works on every CPython
    (no PEP 688 ``__buffer__`` needed; plain classes can't export buffers
    before 3.12)."""
    import mmap as mmap_mod

    gran = mmap_mod.ALLOCATIONGRANULARITY
    base = (off // gran) * gran
    delta = off - base
    fd = os.open(path, os.O_RDONLY)
    try:
        mm = _PinnedArenaMap(fd, delta + size, prot=mmap_mod.PROT_READ,
                             offset=base)
    finally:
        os.close(fd)  # the mapping outlives the fd
    mm._pin = pin
    return memoryview(mm)[delta:delta + size]


def _arena_view(path: str) -> memoryview:
    import mmap as mmap_mod

    with _ARENA_MAPS_LOCK:
        view = _ARENA_MAPS.get(path)
        if view is None:
            if _OWNED_ARENA is not None and _OWNED_ARENA.path == path:
                view = _OWNED_ARENA.buf
            else:
                fd = os.open(path, os.O_RDONLY)
                try:
                    size = os.fstat(fd).st_size
                    mm = mmap_mod.mmap(fd, size, prot=mmap_mod.PROT_READ)
                finally:
                    os.close(fd)
                view = memoryview(mm)
            _ARENA_MAPS[path] = view
        return view


def store_value(ref: ObjectRef, value: Any, is_error: bool = False) -> Tuple[ObjectLocation, list]:
    """Serialize ``value``; write big payloads to shm. Returns (location, contained_refs)."""
    if not _events.ENABLED:
        return _store_value(ref, value, is_error)
    global _put_n
    t0 = time.perf_counter()
    out = _store_value(ref, value, is_error)
    size = out[0].size
    _put_n += 1
    if size > _SMALL_SAMPLE_MAX_BYTES or _put_n % _SMALL_SAMPLE == 1:
        _store_metrics()["put"].observe(time.perf_counter() - t0)
    if size >= _PUT_EVENT_MIN_BYTES:
        _events.emit("object_store", "large shm put", severity="DEBUG",
                     entity_id=ref.hex(), size_mb=round(size / (1 << 20), 2))
    return out


def _store_value(ref: ObjectRef, value: Any, is_error: bool = False) -> Tuple[ObjectLocation, list]:
    cfg = get_config()
    meta, buffers, refs = serialization.serialize(value)
    total = serialization.total_size(meta, buffers)
    if total <= cfg.max_direct_call_object_size:
        blob = serialization.to_bytes(meta, buffers)
        return ObjectLocation(inline=blob, is_error=is_error), refs
    name = session_shm_name(ref.hex())
    if _OWNED_ARENA is not None:
        # native path: allocate a slice of the session arena and write in
        # place (recycled pages skip the fresh-file fault-and-zero cost)
        key = ref.binary()
        off = _OWNED_ARENA.put(key, total)
        if off is None and _OWNED_ARENA.get(key) is not None:
            # a prior attempt of this task left an allocation (it may be
            # SEALED and live — never touch it); index this attempt under
            # a fresh key and let first-seal-wins pick the survivor
            key = os.urandom(16)  # raylint: disable=R3 (retry-only path)
            off = _OWNED_ARENA.put(key, total)
        if off is not None:
            if total >= _ARENA_FD_WRITE_MIN:
                # single-pass write for multi-GiB values (see threshold
                # comment above); coherent with every reader's arena mmap
                written = serialization.write_to_fd_at(
                    _OWNED_ARENA.fd, off, meta, buffers)
                assert written == total, (written, total)
            else:
                serialization.write_into(
                    _OWNED_ARENA.buf[off:off + total], meta, buffers)
            _OWNED_ARENA.seal(key)
            return ObjectLocation(
                shm_name=name, size=total, is_error=is_error,
                arena_path=_OWNED_ARENA.path, arena_off=off, arena_key=key,
            ), refs
        # arena full: fall through to the per-object-file path
        _events.emit("object_store", "arena full; per-object segment fallback",
                     severity="WARNING", entity_id=ref.hex(), size=total)
    # producer side writes through the fd (page-allocation path, ~2.4x the
    # mmap-memcpy bandwidth on tmpfs); consumers still mmap zero-copy
    name = _write_segment(
        name, lambda fd: serialization.write_to_fd(fd, meta, buffers), total
    )
    return ObjectLocation(shm_name=name, size=total, is_error=is_error), refs


def _write_segment(name: str, write_fn, expected: int) -> str:
    """Exclusive-create a named shm segment and fill it via ``write_fn(fd)``.

    A name collision means a prior attempt of the same task created the
    segment; it may be a SEALED live object — never unlink or rewrite it.
    This attempt publishes under a unique name and first-seal-wins reaps
    the loser.  Any write failure unlinks the partial file."""
    path = ShmSegment.path_for(name)
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
    except FileExistsError:
        name = f"{name}-r{os.urandom(3).hex()}"  # raylint: disable=R3 (collision retry)
        path = ShmSegment.path_for(name)
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
    try:
        written = write_fn(fd)
        assert written == expected, f"wrote {written}, expected {expected}"
    except BaseException:
        os.close(fd)
        os.unlink(path)
        raise
    os.close(fd)
    return name


def store_blob(ref: ObjectRef, blob: bytes, is_error: bool = False) -> ObjectLocation:
    """Store an already-serialized payload (thin-client put: the client
    shipped the bytes over the control socket because it shares no shm with
    this host).  Small blobs stay inline; big ones land in local shm."""
    cfg = get_config()
    if len(blob) <= cfg.max_direct_call_object_size:
        return ObjectLocation(inline=bytes(blob), is_error=is_error)

    def write_all(fd: int) -> int:
        view = memoryview(blob)
        total = 0
        while view:  # os.write caps single writes (~2 GiB on Linux)
            n = os.write(fd, view)
            view = view[n:]
            total += n
        return total

    name = _write_segment(session_shm_name(ref.hex()), write_all, len(blob))
    return ObjectLocation(shm_name=name, size=len(blob), is_error=is_error)


def payload_bytes(loc: ObjectLocation) -> bytes:
    """The serialized payload at ``loc`` as bytes (thin-client get: the
    caller can't attach this host's shm, so the head reads the bytes out
    and ships them over the socket).  Remote-node segments are pulled into
    the local namespace first, exactly like :func:`read_value`."""
    if loc.inline is not None:
        return loc.inline
    if loc.spilled_path is not None:
        with open(loc.spilled_path, "rb") as f:
            return f.read()
    arena_src = None
    if loc.arena_path is not None:
        try:
            view = _arena_view(loc.arena_path)
            return bytes(view[loc.arena_off:loc.arena_off + loc.size])
        except FileNotFoundError:
            if not loc.fetch_addr:
                raise
            # remote arena-backed object: the origin serves the arena slice
            # under the object's shm name (same pull read_value does)
            arena_src = (loc.arena_path, loc.arena_off)
    with _ATTACHED_LOCK:
        seg = _ATTACHED.get(loc.shm_name)
    if seg is None:
        try:
            seg = ShmSegment.attach(loc.shm_name, loc.size)
        except FileNotFoundError:
            if not loc.fetch_addr:
                raise
            from ray_tpu._private import object_transfer

            object_transfer.pull_object(
                loc.shm_name, loc.fetch_addr, loc.size, arena=arena_src
            )
            seg = ShmSegment.attach(loc.shm_name, loc.size)
        with _ATTACHED_LOCK:
            seg = _ATTACHED.setdefault(loc.shm_name, seg)
    return bytes(seg.buf)


def _report_replica(oid: Optional[bytes]) -> None:
    """Tell the head this node now holds a copy (location-set update; the
    head records it only for real agent nodes)."""
    if oid is None:
        return
    try:
        from ray_tpu._private.worker import global_worker

        client = global_worker.client
        if client is not None and not client.closed:
            client.send({"type": "replica_added", "oid": oid})
    except Exception:
        pass  # best-effort: the directory just misses one source


def read_value(loc: ObjectLocation, oid: Optional[bytes] = None) -> Any:
    """Deserialize an object from its location (zero-copy for shm payloads;
    spilled objects are read back from disk; remote segments are pulled
    into the local shm namespace first — ``ray.get`` step 3 in SURVEY §3.3).

    ``oid`` enables zero-copy reads of arena-backed objects: the views are
    pinned with a head-side reference so the slot can't be recycled under
    them.  Without an oid, arena payloads are copied out for safety."""
    if not _events.ENABLED:
        return _read_value(loc, oid)
    global _get_n
    t0 = time.perf_counter()
    value = _read_value(loc, oid)
    _get_n += 1
    if loc.size > _SMALL_SAMPLE_MAX_BYTES or _get_n % _SMALL_SAMPLE == 1:
        _store_metrics()["get"].observe(time.perf_counter() - t0)
    return value


def _read_value(loc: ObjectLocation, oid: Optional[bytes] = None) -> Any:
    if loc.inline is not None:
        value = serialization.deserialize(memoryview(loc.inline))
    elif loc.spilled_path is not None:
        with open(loc.spilled_path, "rb") as f:
            value = serialization.deserialize(memoryview(f.read()))
    elif loc.arena_path is not None:
        try:
            payload = None
            if oid is not None:
                from ray_tpu._private.worker import global_worker

                client = global_worker.client
                if client is not None and not client.closed:
                    # the caller's handle is live right now, so this
                    # add_ref cannot race the object's deletion
                    client.add_refs([oid])
                    payload = _pinned_arena_slice(
                        loc.arena_path, loc.arena_off, loc.size,
                        _ArenaPin(oid))
            if payload is None:
                view = _arena_view(loc.arena_path)
                payload = memoryview(
                    bytes(view[loc.arena_off:loc.arena_off + loc.size]))
            value = serialization.deserialize(payload)
        except FileNotFoundError:
            # remote node: pull a private copy named loc.shm_name
            if not loc.fetch_addr:
                raise
            from ray_tpu._private import object_transfer

            object_transfer.pull_object(
                loc.shm_name, loc.fetch_addr, loc.size,
                arena=(loc.arena_path, loc.arena_off),
            )
            _report_replica(oid)
            seg = ShmSegment.attach(loc.shm_name, loc.size)
            with _ATTACHED_LOCK:
                seg = _ATTACHED.setdefault(loc.shm_name, seg)
            value = serialization.deserialize(seg.buf)
    else:
        with _ATTACHED_LOCK:
            seg = _ATTACHED.get(loc.shm_name)
        if seg is None:
            try:
                seg = ShmSegment.attach(loc.shm_name, loc.size)
            except FileNotFoundError:
                if not loc.fetch_addr:
                    raise
                from ray_tpu._private import object_transfer

                object_transfer.pull_object(loc.shm_name, loc.fetch_addr, loc.size)
                _report_replica(oid)
                seg = ShmSegment.attach(loc.shm_name, loc.size)
            with _ATTACHED_LOCK:
                seg = _ATTACHED.setdefault(loc.shm_name, seg)
        value = serialization.deserialize(seg.buf)
    if loc.is_error:
        raise value
    return value
