"""Object store: registry + producer/consumer helpers.

Splits the reference's design across the same seams:

- ``ObjectRegistry`` lives in the head process and plays the role of the
  plasma store's directory + ``ObjectLifecycleManager``
  (``src/ray/object_manager/plasma/store.h:55``,
  ``object_lifecycle_manager.h:101``): it maps object id -> location, tracks
  sealing, sizes, and reference counts, and unlinks segments on eviction.
- Producers (workers/driver) serialize into a fresh shm segment themselves
  and then *seal* it with the registry — the plasma create/seal protocol
  without copying payloads through a socket.
- Small objects are carried inline, the analog of the core worker's
  in-process memory store for direct returns
  (``src/ray/core_worker/store_provider/memory_store/memory_store.h``).

Each consumer process keeps attached segments alive in ``_ATTACHED`` for the
life of the process, like plasma clients holding their mmaps.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import serialization
from ray_tpu._private.config import get_config
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.shm import ShmSegment


@dataclass
class ObjectLocation:
    """Where an object's payload lives. Exactly one of inline/shm is set."""

    inline: Optional[bytes] = None
    shm_name: Optional[str] = None
    size: int = 0
    # Serialized error objects raise on get (RayTaskError analog).
    is_error: bool = False

    def __post_init__(self):
        if self.inline is not None:
            self.size = len(self.inline)


@dataclass
class _Entry:
    loc: Optional[ObjectLocation] = None
    sealed: threading.Event = field(default_factory=threading.Event)
    ref_count: int = 1


class ObjectRegistry:
    """Head-process directory of all objects in the session."""

    def __init__(self):
        self._lock = threading.Lock()
        self._objects: Dict[bytes, _Entry] = {}
        self._bytes_used = 0

    def create_pending(self, oid: bytes) -> None:
        """Declare an object that a task will produce (return slot)."""
        with self._lock:
            self._objects.setdefault(oid, _Entry())

    def seal(self, oid: bytes, loc: ObjectLocation) -> None:
        unlink = None
        with self._lock:
            e = self._objects.setdefault(oid, _Entry())
            if e.loc is not None:
                # First seal wins (objects are immutable).  A re-seal happens
                # when a task retried after its worker sealed a return and
                # then crashed — drop the duplicate payload.  Checked and
                # set under the lock so two concurrent seals can't both win.
                unlink = loc.shm_name
            else:
                e.loc = loc
                self._bytes_used += loc.size
            e.sealed.set()
        if unlink:
            ShmSegment.unlink(unlink)

    def is_sealed(self, oid: bytes) -> bool:
        with self._lock:
            e = self._objects.get(oid)
        return e is not None and e.sealed.is_set()

    def wait_sealed(self, oid: bytes, timeout: Optional[float]) -> Optional[ObjectLocation]:
        with self._lock:
            e = self._objects.setdefault(oid, _Entry())
        if not e.sealed.wait(timeout):
            return None
        return e.loc

    def get_location(self, oid: bytes) -> Optional[ObjectLocation]:
        with self._lock:
            e = self._objects.get(oid)
        if e is None or not e.sealed.is_set():
            return None
        return e.loc

    def add_ref(self, oid: bytes, n: int = 1) -> None:
        with self._lock:
            e = self._objects.get(oid)
            if e is not None:
                e.ref_count += n

    def remove_ref(self, oid: bytes, n: int = 1) -> None:
        """Distributed-ref-counting-lite (ReferenceCounter, reference_count.h:61)."""
        unlink = None
        with self._lock:
            e = self._objects.get(oid)
            if e is None:
                return
            e.ref_count -= n
            if e.ref_count <= 0 and e.sealed.is_set():
                if e.loc and e.loc.shm_name:
                    unlink = e.loc.shm_name
                    self._bytes_used -= e.loc.size
                del self._objects[oid]
        if unlink:
            ShmSegment.unlink(unlink)

    def stats(self) -> dict:
        with self._lock:
            return {
                "num_objects": len(self._objects),
                "bytes_used": self._bytes_used,
            }

    def all_shm_names(self) -> List[str]:
        with self._lock:
            return [e.loc.shm_name for e in self._objects.values() if e.loc and e.loc.shm_name]

    def shutdown(self) -> None:
        for name in self.all_shm_names():
            ShmSegment.unlink(name)
        with self._lock:
            self._objects.clear()


# ---------------------------------------------------------------------------
# Producer / consumer helpers (run in any process)
# ---------------------------------------------------------------------------

_ATTACHED: Dict[str, ShmSegment] = {}
_ATTACHED_LOCK = threading.Lock()


def store_value(ref: ObjectRef, value: Any, is_error: bool = False) -> Tuple[ObjectLocation, list]:
    """Serialize ``value``; write big payloads to shm. Returns (location, contained_refs)."""
    cfg = get_config()
    meta, buffers, refs = serialization.serialize(value)
    total = serialization.total_size(meta, buffers)
    if total <= cfg.max_direct_call_object_size:
        blob = serialization.to_bytes(meta, buffers)
        return ObjectLocation(inline=blob, is_error=is_error), refs
    name = f"{cfg.shm_prefix}-{ref.hex()}"
    seg = ShmSegment.create(name, total)
    try:
        serialization.write_into(seg.buf, meta, buffers)
    finally:
        seg.close()
    return ObjectLocation(shm_name=name, size=total, is_error=is_error), refs


def read_value(loc: ObjectLocation) -> Any:
    """Deserialize an object from its location (zero-copy for shm payloads)."""
    if loc.inline is not None:
        value = serialization.deserialize(memoryview(loc.inline))
    else:
        with _ATTACHED_LOCK:
            seg = _ATTACHED.get(loc.shm_name)
            if seg is None:
                seg = ShmSegment.attach(loc.shm_name, loc.size)
                _ATTACHED[loc.shm_name] = seg
        value = serialization.deserialize(seg.buf)
    if loc.is_error:
        raise value
    return value
