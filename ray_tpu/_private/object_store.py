"""Object store: registry + producer/consumer helpers.

Splits the reference's design across the same seams:

- ``ObjectRegistry`` lives in the head process and plays the role of the
  plasma store's directory + ``ObjectLifecycleManager``
  (``src/ray/object_manager/plasma/store.h:55``,
  ``object_lifecycle_manager.h:101``) plus the owner-side
  ``ReferenceCounter`` (``src/ray/core_worker/reference_count.h:61``):
  object id -> location, sealing, sizes, reference counts (handle refs +
  contained-in-object refs + task-spec pins), eviction-by-spilling at the
  ``object_store_memory`` cap (``local_object_manager.h:41`` analog), and
  segment unlinking when the count hits zero.
- Producers (workers/driver) serialize into a fresh shm segment themselves
  and then *seal* it with the registry — the plasma create/seal protocol
  without copying payloads through a socket.
- Small objects are carried inline, the analog of the core worker's
  in-process memory store for direct returns
  (``src/ray/core_worker/store_provider/memory_store/memory_store.h``).

Each consumer process keeps attached segments alive in ``_ATTACHED`` for the
life of the process, like plasma clients holding their mmaps (zero-copy
views of values alias the mapping, so it cannot be unmapped eagerly).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple, Union

from ray_tpu._private import events as _events
from ray_tpu._private import serialization
from ray_tpu._private.config import get_config
from ray_tpu._private.locks import make_lock
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.shm import ShmSegment, session_shm_name

# Lazy put/get latency histograms (registered on first use; observation is
# skipped entirely when the observability layer is disabled).
_STORE_METRICS = None
# shm puts at least this big get a flight-recorder event (arena/ingest
# pressure visibility without an event per small put)
_PUT_EVENT_MIN_BYTES = 1 << 20
# Payloads below this observe their latency 1:_SMALL_SAMPLE (a histogram
# lock on EVERY inline return/get rides the task hot path; big payloads —
# the interesting tail — always record).  Unlocked counters: a lost race
# just shifts which call samples.
_SMALL_SAMPLE_MAX_BYTES = 64 << 10
_SMALL_SAMPLE = 8
_put_n = 0
_get_n = 0


def _store_metrics():
    global _STORE_METRICS
    if _STORE_METRICS is None:
        from ray_tpu.util.metrics import Histogram

        bounds = [0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5]
        note = " (payloads <64KiB sampled 1:8)"
        _STORE_METRICS = {
            "put": Histogram("ray_tpu_object_put_latency_s",
                             "serialize+store latency per object (s)" + note,
                             boundaries=bounds),
            "get": Histogram("ray_tpu_object_get_latency_s",
                             "attach+deserialize latency per object (s)" + note,
                             boundaries=bounds),
        }
    return _STORE_METRICS


@dataclass
class ObjectLocation:
    """Where an object's payload lives.  Exactly one of inline/shm_name/
    spilled_path is set."""

    inline: Optional[bytes] = None
    shm_name: Optional[str] = None
    spilled_path: Optional[str] = None
    size: int = 0
    # Serialized error objects raise on get (RayTaskError analog).
    is_error: bool = False
    # Which cluster node holds the shm segment ("" = head) and that node's
    # object-server address — consumers on other nodes pull through it
    # (the head fills fetch_addr when serving locations cross-node).
    node_id: str = ""
    fetch_addr: Optional[tuple] = None
    # Native arena backing (plasma analog): the payload is the
    # [arena_off, arena_off+size) slice of the arena file.  shm_name is
    # still set — it names the pulled copy on remote consumers.
    arena_path: Optional[str] = None
    arena_off: int = 0
    # the arena index key (== oid normally; a fresh key when a retried
    # task re-produced a return whose first attempt left an allocation)
    arena_key: Optional[bytes] = None

    def __post_init__(self):
        if self.inline is not None:
            self.size = len(self.inline)

    def __reduce__(self):
        # Locations ride in every seal/location-reply message; positional
        # reconstruction skips dataclass-by-__dict__ pickling (~3x faster,
        # and the common inline case pickles only two live fields).
        return (ObjectLocation, (
            self.inline, self.shm_name, self.spilled_path, self.size,
            self.is_error, self.node_id, self.fetch_addr, self.arena_path,
            self.arena_off, self.arena_key,
        ))


@dataclass
class _Entry:
    """COLD per-object metadata (payload location, owner attribution,
    containment, the waiters' Event).  The HOT per-object state —
    ref_count, per-reason pin counts, the replica location set — lives in
    the session's ref index (C++ ``RefIndex`` in src/store_core, or the
    pure-Python ``_PyRefs`` twin), keyed by the same oid."""

    loc: Optional[ObjectLocation] = None
    sealed: threading.Event = field(default_factory=threading.Event)
    contained: List[bytes] = field(default_factory=list)
    last_access: float = field(default_factory=time.monotonic)
    # ownership audit (`ray memory` analog): who sealed the payload —
    # "driver", a worker id hex, or an actor id hex — plus wall-clock
    # creation time for age.
    owner: Optional[str] = None
    owner_kind: str = "unknown"  # driver | worker | actor | head
    created: float = field(default_factory=time.time)


# ---------------------------------------------------------------------------
# Ref index: the registry's hot maps (refcounts, pin reasons, location sets)
# ---------------------------------------------------------------------------
#
# Pin-reason slots are fixed across the C and Python implementations; the
# audit's pins breakdown is rebuilt from them (an unknown reason folds
# into "other" — lifetime accounting is reason-agnostic either way).
PIN_REASONS = ("handle", "task_arg", "contained", "lineage",
               "pending_demand", "reserved5", "reserved6", "other")
_REASON_IDX = {name: i for i, name in enumerate(PIN_REASONS)}
_OTHER_IDX = len(PIN_REASONS) - 1


def _reason_idx(reason: str) -> int:
    return _REASON_IDX.get(reason, _OTHER_IDX)


def _pins_dict(pins) -> Dict[str, int]:
    return {PIN_REASONS[i]: v for i, v in enumerate(pins) if v > 0}


class _PyRefs:
    """Pure-Python twin of the native RefIndex (store_core.cc) — same
    contract, same slot semantics, used when the toolchain can't build
    the .so or ``RAY_TPU_NATIVE_REFS=0`` forces it.  One lock, batch
    methods, erase-at-zero atomic with the decrement."""

    MAX_SLOTS = 64

    def __init__(self):
        self._lock = make_lock("object_store.refs")
        # oid -> [count, pins(list[8]), sealed, origin_slot, replica_mask, rr]
        self._m: Dict[bytes, list] = {}

    def ensure(self, oids, reason: str = "handle") -> None:
        ridx = _reason_idx(reason)
        with self._lock:
            m = self._m
            for oid in oids:
                if oid not in m:
                    pins = [0] * len(PIN_REASONS)
                    pins[ridx] = 1
                    m[oid] = [1, pins, False, -1, 0, 0]

    def contains(self, oid: bytes) -> bool:
        with self._lock:
            return oid in self._m

    def add(self, oids, reason: str, delta: int) -> None:
        ridx = _reason_idx(reason)
        with self._lock:
            m = self._m
            for oid in oids:
                e = m.get(oid)
                if e is not None:
                    e[0] += delta
                    e[1][ridx] += delta

    def remove(self, oids, reason: str, delta: int) -> List[bytes]:
        ridx = _reason_idx(reason)
        dead: List[bytes] = []
        with self._lock:
            m = self._m
            for oid in oids:
                e = m.get(oid)
                if e is None:
                    continue
                e[0] -= delta
                left = e[1][ridx] - delta
                e[1][ridx] = left if left > 0 else 0
                if e[0] <= 0 and e[2]:
                    dead.append(oid)
                    del m[oid]
        return dead

    def seal(self, oid: bytes) -> int:
        with self._lock:
            e = self._m.get(oid)
            if e is None:
                return -1
            e[2] = True
            if e[0] <= 0:
                del self._m[oid]
                return 1
            return 0

    def unseal(self, oid: bytes) -> int:
        with self._lock:
            e = self._m.get(oid)
            if e is None:
                return -1
            e[2] = False
            e[3] = -1
            e[4] = 0
            return 0

    def erase(self, oid: bytes) -> int:
        with self._lock:
            return 0 if self._m.pop(oid, None) is not None else -1

    def get(self, oid: bytes):
        with self._lock:
            e = self._m.get(oid)
            if e is None:
                return None
            return e[0], e[2], list(e[1])

    def get_batch(self, oids):
        counts, pins = [], []
        with self._lock:
            for oid in oids:
                e = self._m.get(oid)
                if e is None:
                    counts.append(None)
                    pins.append([0] * len(PIN_REASONS))
                else:
                    counts.append(e[0])
                    pins.append(list(e[1]))
        return counts, pins

    def size(self) -> int:
        with self._lock:
            return len(self._m)

    # -- location sets --
    def set_origin(self, oid: bytes, slot: int) -> int:
        with self._lock:
            e = self._m.get(oid)
            if e is None:
                return -1
            e[3] = slot
            return 0

    def add_replica(self, oid: bytes, slot: int) -> int:
        if not 0 <= slot < self.MAX_SLOTS:
            return -2
        with self._lock:
            e = self._m.get(oid)
            if e is None:
                return -1
            if slot == e[3] or e[4] & (1 << slot):
                return 0
            e[4] |= 1 << slot
            return 1

    def pop_replica(self, oid: bytes) -> int:
        with self._lock:
            e = self._m.get(oid)
            if e is None or not e[4]:
                return -1
            slot = (e[4] & -e[4]).bit_length() - 1
            e[4] &= e[4] - 1
            return slot

    def num_replicas(self, oid: bytes) -> int:
        with self._lock:
            e = self._m.get(oid)
            return -1 if e is None else bin(e[4]).count("1")

    def replica_mask(self, oid: bytes) -> int:
        with self._lock:
            e = self._m.get(oid)
            return 0 if e is None else e[4]

    def clear_replicas(self, oid: bytes) -> int:
        with self._lock:
            e = self._m.get(oid)
            if e is None:
                return -1
            e[4] = 0
            return 0

    def drop_slot(self, slot: int) -> None:
        mask = ~(1 << slot)
        with self._lock:
            for e in self._m.values():
                e[4] &= mask

    def locate(self, oids, prefer_slot: int) -> List[int]:
        out = []
        with self._lock:
            for oid in oids:
                e = self._m.get(oid)
                if e is None:
                    out.append(-2)
                    continue
                mask = e[4]
                if not mask:
                    out.append(-1)
                    continue
                if prefer_slot >= 0:
                    if prefer_slot == e[3]:
                        out.append(-1)
                        continue
                    if mask & (1 << prefer_slot):
                        out.append(prefer_slot)
                        continue
                n_rep = bin(mask).count("1")
                idx = e[5] % (1 + n_rep)
                e[5] += 1
                if idx == 0:
                    out.append(-1)
                    continue
                m = mask
                for _ in range(idx - 1):
                    m &= m - 1
                out.append((m & -m).bit_length() - 1)
        return out

    def clear(self) -> None:
        with self._lock:
            self._m.clear()


class _NativeRefs:
    """GIL-released C ref index.  Batch calls pack 16-byte oids into one
    contiguous buffer (one mutex hop per message); the rare odd-size id
    (tests, fixed sentinel ids) routes to an embedded pure-Python twin so
    the contract holds for every key."""

    def __init__(self):
        from ray_tpu._private import native

        self._ix = native.RefIndex()
        self._odd = _PyRefs()

    @staticmethod
    def _split(oids):
        """(packed-16B-bytes, n16, odd-list) preserving per-group order."""
        n = len(oids)
        if all(len(o) == 16 for o in oids):
            # total-length alone can't gate this: a mixed batch (8B+24B)
            # sums to n*16 and would re-chunk into garbage keys
            return b"".join(oids), n, ()
        std = [o for o in oids if len(o) == 16]
        odd = [o for o in oids if len(o) != 16]
        return b"".join(std), len(std), odd

    def ensure(self, oids, reason: str = "handle") -> None:
        packed, n, odd = self._split(oids)
        if n:
            self._ix.ensure(packed, n, _reason_idx(reason))
        if odd:
            self._odd.ensure(odd, reason)

    def contains(self, oid: bytes) -> bool:
        if len(oid) == 16:
            return self._ix.contains(oid)
        return self._odd.contains(oid)

    def add(self, oids, reason: str, delta: int) -> None:
        packed, n, odd = self._split(oids)
        if n:
            self._ix.add(packed, n, _reason_idx(reason), delta)
        if odd:
            self._odd.add(odd, reason, delta)

    def remove(self, oids, reason: str, delta: int) -> List[bytes]:
        packed, n, odd = self._split(oids)
        dead: List[bytes] = []
        if n:
            dead = self._ix.remove(packed, n, _reason_idx(reason), delta)
        if odd:
            dead.extend(self._odd.remove(odd, reason, delta))
        return dead

    def seal(self, oid: bytes) -> int:
        if len(oid) == 16:
            return self._ix.seal(oid)
        return self._odd.seal(oid)

    def unseal(self, oid: bytes) -> int:
        if len(oid) == 16:
            return self._ix.unseal(oid)
        return self._odd.unseal(oid)

    def erase(self, oid: bytes) -> int:
        if len(oid) == 16:
            return self._ix.erase(oid)
        return self._odd.erase(oid)

    def get(self, oid: bytes):
        if len(oid) == 16:
            return self._ix.get(oid)
        return self._odd.get(oid)

    def get_batch(self, oids):
        packed, n, odd = self._split(oids)
        if not odd:
            return self._ix.get_batch(packed, n) if n else ([], [])
        # mixed batch (audit pages): per-oid lookups keep row order
        counts, pins = [], []
        for oid in oids:
            got = self.get(oid)
            if got is None:
                counts.append(None)
                pins.append([0] * len(PIN_REASONS))
            else:
                counts.append(got[0])
                pins.append(got[2])
        return counts, pins

    def size(self) -> int:
        return self._ix.size() + self._odd.size()

    def set_origin(self, oid: bytes, slot: int) -> int:
        if len(oid) == 16:
            return self._ix.set_origin(oid, slot)
        return self._odd.set_origin(oid, slot)

    def add_replica(self, oid: bytes, slot: int) -> int:
        if len(oid) == 16:
            return self._ix.add_replica(oid, slot)
        return self._odd.add_replica(oid, slot)

    def pop_replica(self, oid: bytes) -> int:
        if len(oid) == 16:
            return self._ix.pop_replica(oid)
        return self._odd.pop_replica(oid)

    def num_replicas(self, oid: bytes) -> int:
        if len(oid) == 16:
            return self._ix.num_replicas(oid)
        return self._odd.num_replicas(oid)

    def replica_mask(self, oid: bytes) -> int:
        if len(oid) == 16:
            return self._ix.replica_mask(oid)
        return self._odd.replica_mask(oid)

    def clear_replicas(self, oid: bytes) -> int:
        if len(oid) == 16:
            return self._ix.clear_replicas(oid)
        return self._odd.clear_replicas(oid)

    def drop_slot(self, slot: int) -> None:
        self._ix.drop_slot(slot)
        self._odd.drop_slot(slot)

    def locate(self, oids, prefer_slot: int) -> List[int]:
        packed, n, odd = self._split(oids)
        if not odd:
            return self._ix.locate(packed, n, prefer_slot) if n else []
        # mixed batch: per-oid dispatch keeps result order (odd-size ids
        # go to the Python twin, same as every other method here)
        return [
            (self._ix.locate(oid, 1, prefer_slot)[0] if len(oid) == 16
             else self._odd.locate((oid,), prefer_slot)[0])
            for oid in oids
        ]

    def clear(self) -> None:
        self._ix.clear()
        self._odd.clear()


def _make_refs():
    """The session ref index: native unless unavailable or disabled."""
    if os.environ.get("RAY_TPU_NATIVE_REFS", "1") != "0":
        try:
            from ray_tpu._private import native

            if native.available():
                return _NativeRefs()
        except Exception:
            pass
    return _PyRefs()


# Objects touched within this window are not spill candidates — closes the
# race where a get reply carrying an shm location is in flight while the
# head spills the segment out from under the consumer.
#
# Why eviction candidate selection is safe PYTHON-side (vs the reference's
# in-store eviction_policy.h): the native arena is single-writer — only
# the head process allocates/frees (store_core.cc's contract), and every
# registry mutation (create/seal/pin/spill) happens under this registry's
# lock in that same process.  A concurrent seal therefore cannot race a
# spill decision: both serialize on self._lock, and the C layer is only
# ever called while it is held.  Readers in other processes see sealed
# slices via control-plane locations and are protected by the idle window
# + pin counts, not by store-internal locking.
_SPILL_MIN_IDLE_S = 5.0


class ObjectRegistry:
    """Head-process directory of all objects in the session."""

    def __init__(self, capacity_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        self._lock = make_lock("object_store.registry")
        self._objects: Dict[bytes, _Entry] = {}
        self._bytes_used = 0  # head-local shm bytes (spilled/inline/remote don't count)
        self._capacity = capacity_bytes
        self._spill_dir = spill_dir
        self._num_spilled = 0
        # HOT maps (refcounts, pin reasons, location sets) live here —
        # native C++ with the GIL released, or the pure-Python twin.
        # add_refs/remove_refs never take self._lock: the ref index has
        # its own mutex, and only the oids it reports dead come back to
        # Python for metadata/payload reaping.
        self._refs = _make_refs()
        # node slot table for the location sets: slot <-> (node_id, addr).
        # The index speaks small ints; Python owns the mapping.
        self._node_slots: Dict[str, int] = {}
        self._slot_info: List[tuple] = []
        # fast path: until the first replica is ever recorded (single-node
        # sessions, i.e. almost always), get_location skips the index
        self._any_replicas = False
        # incrementally-maintained ownership aggregate: (owner, kind) ->
        # [bytes, objects] over SEALED entries, adjusted at seal /
        # node-loss unseal / delete.  owner_summary() reads it in
        # O(owners) — the every-5s gauge refresh and /metrics scrape must
        # never scan the full object table under this lock.
        self._owner_agg: Dict[Tuple[str, str], list] = {}
        # set by the head Node: shm_name -> ask every node agent to unlink.
        # Any node may hold the origin segment OR a pulled replica, so
        # deletion broadcasts (the head's own copy/replica is unlinked
        # locally either way).
        self.broadcast_unlink = None
        # set by the head Node when the native arena backs local objects:
        # oid -> free the arena allocation
        self.arena_delete = None
        # set by the head Node: called (without the registry lock) for each
        # fully-deleted object so lineage entries die with the object
        self.on_delete = None

    # -- creation / sealing --------------------------------------------
    def create_pending(self, oid: bytes) -> None:
        """Declare an object that a task will produce (return slot)."""
        with self._lock:
            if oid not in self._objects:
                self._objects[oid] = _Entry()
                self._refs.ensure((oid,))

    def create_pending_batch(self, oids) -> None:
        """One lock hop + one index call for a whole spec's return slots
        (a 1M-task submission wave creates 1M entries through here)."""
        with self._lock:
            new = [oid for oid in oids if oid not in self._objects]
            for oid in new:
                self._objects[oid] = _Entry()
            if new:
                self._refs.ensure(new)

    def seal(self, oid: bytes, loc: ObjectLocation,
             contained: Optional[List[bytes]] = None,
             only_if_live: bool = False, owner: Optional[str] = None,
             owner_kind: Optional[str] = None) -> bool:
        """Seal ``oid`` with ``loc``.  With ``only_if_live``, a concurrent
        deletion wins atomically: the prepared payload is discarded instead
        of resurrecting the entry (returns False).  Plain seal returns True."""
        unlink = None
        dead: List[tuple] = []
        missed = False
        fresh = False
        with self._lock:
            e = self._objects.get(oid)
            if e is None and not only_if_live:
                e = self._objects[oid] = _Entry()
                self._refs.ensure((oid,))
            if e is None:
                # entry died between the caller's decision and this seal:
                # reap the orphaned payload (outside the lock — reap
                # callbacks may take the node lock), don't resurrect
                missed = True
                if loc.arena_path:
                    dead.append(("arena", (loc.arena_key, loc.shm_name)))
                elif loc.shm_name:
                    dead.append(("shm", loc.shm_name))
                elif loc.spilled_path:
                    dead.append(("file", loc.spilled_path))
            elif e.loc is not None:
                # First seal wins (objects are immutable).  A re-seal happens
                # when a task retried after its worker sealed a return and
                # then crashed — drop the duplicate payload.  Checked and
                # set under the lock so two concurrent seals can't both win.
                if loc.arena_path:
                    dead.append(("arena", (loc.arena_key, None)))
                    unlink = None
                elif loc.shm_name == e.loc.shm_name:
                    unlink = None  # same segment as the winner: never unlink
                else:
                    unlink = loc.shm_name
            else:
                fresh = True
                e.loc = loc
                e.contained = list(contained or [])
                # first seal records the producer as owner; a re-seal after
                # lineage reconstruction keeps the original attribution
                if owner is not None and e.owner is None:
                    e.owner = owner
                    e.owner_kind = owner_kind or "unknown"
                e.created = time.time()
                self._owner_agg_add(e, 1)
                if loc.shm_name and not loc.node_id:
                    self._bytes_used += loc.size
            # The containment pins, Event set, and index sealed flag stay
            # UNDER the registry lock (the index mutex nests inside it,
            # never the reverse): a concurrent mark_node_lost must never
            # observe e.contained populated while the +1s are missing, or
            # replace the Event between the loc write and the set.
            dead_at_seal = False
            if not missed:
                if fresh and e.contained:
                    # +1 per child; no-op for already-deleted children,
                    # same as the old existing-entry check
                    self._refs.add(e.contained, "contained", 1)
                e.sealed.set()
                # the index's sealed flag is the delete-at-zero gate: a 1
                # return means every handle died before the producer
                # finished (fire-and-forget) — reclaim below
                dead_at_seal = self._refs.seal(oid) == 1
        if missed:
            self._reap(dead)
            self._maybe_spill()
            return False
        if dead_at_seal:
            self._reap_dead_entries([oid])
        if unlink:
            self._reap([("shm", unlink)])
        self._reap(dead)
        self._maybe_spill()
        return True

    def mark_node_lost(self, node_id: str) -> List[bytes]:
        """Un-seal every object whose only copy lived on a dead node, so
        lineage reconstruction (or an ObjectLostError seal) can refill the
        slot; consumers block on the cleared event meanwhile.  Returns the
        lost oids (reference: ObjectRecoveryManager's lost-object scan,
        ``object_recovery_manager.h:41``)."""
        if not node_id:
            return []  # head-local objects die with the session, not here
        lost: List[bytes] = []
        orphaned_children: List[bytes] = []
        with self._lock:
            slot = self._node_slots.get(node_id, -1)
            if slot >= 0:
                # the dead node's pulled copies leave every location set
                self._refs.drop_slot(slot)
            for oid, e in list(self._objects.items()):
                if e.loc is None or e.loc.node_id != node_id:
                    continue
                surv = self._refs.pop_replica(oid)
                if surv >= 0:
                    # a surviving copy exists: promote it to primary —
                    # no un-seal, no lineage reconstruction (the payoff
                    # of the location set)
                    nid, addr = self._slot_info[surv]
                    e.loc = ObjectLocation(
                        shm_name=e.loc.shm_name, size=e.loc.size,
                        is_error=e.loc.is_error, node_id=nid,
                        fetch_addr=tuple(addr))
                    continue
                # drop contained-ref increments this payload made; a
                # successful re-seal will re-add them
                orphaned_children.extend(e.contained)
                e.contained = []
                self._owner_agg_add(e, -1)  # a re-seal re-adds
                e.loc = None
                e.sealed = threading.Event()  # fresh event: old waiters
                # saw the sealed one; new waiters block until refill
                self._refs.unseal(oid)
                lost.append(oid)
        if orphaned_children:
            self.remove_refs(orphaned_children, reason="contained")
        return lost

    def contains(self, oid: bytes) -> bool:
        with self._lock:
            return oid in self._objects

    # -- lookup --------------------------------------------------------
    def is_sealed(self, oid: bytes) -> bool:
        with self._lock:
            e = self._objects.get(oid)
        return e is not None and e.sealed.is_set()

    def wait_sealed_existing(
        self, oid: bytes, timeout: Optional[float]
    ) -> Union[ObjectLocation, None, str]:
        """Like :meth:`wait_sealed` but never creates an entry: returns the
        sentinel ``"missing"`` for unknown/deleted oids instead of parking a
        phantom _Entry nobody owns (thin-client get path)."""
        with self._lock:
            e = self._objects.get(oid)
        if e is None:
            return "missing"
        if not e.sealed.wait(timeout):
            return None
        e.last_access = time.monotonic()
        return e.loc

    def wait_sealed(self, oid: bytes, timeout: Optional[float]) -> Optional[ObjectLocation]:
        with self._lock:
            e = self._objects.get(oid)
            if e is None:
                e = self._objects[oid] = _Entry()
                self._refs.ensure((oid,))
        if not e.sealed.wait(timeout):
            return None
        e.last_access = time.monotonic()
        return e.loc

    def get_location(self, oid: bytes,
                     prefer_node: Optional[str] = None) -> Optional[ObjectLocation]:
        """Location for a consumer.  ``prefer_node`` is the consumer's node
        ("" = head / emulated): a copy on the consumer's own node wins
        (zero-copy attach); otherwise the pull source round-robins across
        origin + replicas (the location-set payoff: reads spread over every
        node holding a copy)."""
        with self._lock:
            e = self._objects.get(oid)
            if e is None or not e.sealed.is_set():
                return None
            e.last_access = time.monotonic()
            loc = e.loc
        if not (self._any_replicas and loc is not None and loc.shm_name
                and loc.fetch_addr):
            return loc
        return self._choose_source(oid, loc, prefer_node)

    def get_locations_batch(
        self, oids, prefer_node: Optional[str] = None,
    ) -> Dict[bytes, Optional[ObjectLocation]]:
        """One lock hop for a whole dep set (the dispatch path resolves
        every argument location through here)."""
        out: Dict[bytes, Optional[ObjectLocation]] = {}
        now = time.monotonic()
        with self._lock:
            for oid in oids:
                e = self._objects.get(oid)
                if e is None or not e.sealed.is_set():
                    out[oid] = None
                    continue
                e.last_access = now
                out[oid] = e.loc
        if self._any_replicas:
            for oid, loc in out.items():
                if loc is not None and loc.shm_name and loc.fetch_addr:
                    out[oid] = self._choose_source(oid, loc, prefer_node)
        return out

    def _choose_source(self, oid: bytes, loc: ObjectLocation,
                       prefer_node: Optional[str]) -> ObjectLocation:
        """Replica-set pull spreading: ask the ref index which copy this
        consumer should read (own node wins, else round-robin)."""
        prefer_slot = -1
        if prefer_node is not None:
            if prefer_node == (loc.node_id or ""):
                return loc  # own-node origin (incl. head arena payloads)
            prefer_slot = self._node_slots.get(prefer_node, -1)
        choice = self._refs.locate((oid,), prefer_slot)[0]
        if choice < 0:
            return loc
        nid, addr = self._slot_info[choice]
        if nid == (loc.node_id or "") or addr is None:
            return loc
        return self._replica_loc(loc, nid, addr)

    @staticmethod
    def _replica_loc(loc: ObjectLocation, node_id: str, addr) -> ObjectLocation:
        # replicas are plain files — no arena fields
        return ObjectLocation(
            shm_name=loc.shm_name, size=loc.size, is_error=loc.is_error,
            node_id=node_id, fetch_addr=tuple(addr))

    def _node_slot_locked(self, node_id: str, addr=None) -> int:
        """Slot for ``node_id`` (lock held), assigning one on first use;
        a provided address refreshes the slot's pull endpoint."""
        slot = self._node_slots.get(node_id)
        if slot is None:
            slot = len(self._slot_info)
            self._node_slots[node_id] = slot
            self._slot_info.append((node_id, tuple(addr) if addr else None))
        elif addr:
            self._slot_info[slot] = (node_id, tuple(addr))
        return slot

    def add_replica(self, oid: bytes, node_id: str, fetch_addr) -> None:
        """Record that ``node_id`` now holds a pulled copy (location-set
        update; reported by consumers after a successful pull or by the
        broadcast fan-out)."""
        if not node_id or not fetch_addr:
            return
        with self._lock:
            e = self._objects.get(oid)
            if not (
                e is not None and e.loc is not None and e.loc.shm_name
                and node_id != e.loc.node_id
            ):
                return
            slot = self._node_slot_locked(node_id, fetch_addr)
            origin = self._node_slot_locked(e.loc.node_id or "",
                                            e.loc.fetch_addr)
        self._refs.set_origin(oid, origin)
        if self._refs.add_replica(oid, slot) == 1:
            self._any_replicas = True

    def replica_nodes(self, oid: bytes) -> List[str]:
        mask = self._refs.replica_mask(oid)
        if not mask:
            return []
        with self._lock:
            return [info[0] for i, info in enumerate(self._slot_info)
                    if mask & (1 << i)]

    # -- reference counting --------------------------------------------
    # These never take the registry lock: the ref index has its own
    # (GIL-released, in the native case) mutex, and batch calls make one
    # hop per MESSAGE.  Only the oids the index erased (count<=0 while
    # sealed, atomic with the decrement) come back for metadata reaping.
    def add_ref(self, oid: bytes, n: int = 1, reason: str = "handle") -> None:
        """``reason`` feeds the audit's pin breakdown ("handle" = a live
        ObjectRef somewhere, "task_arg" = pinned by a pending task's spec,
        "contained" = referenced inside another sealed object)."""
        self._refs.add((oid,), reason, n)

    def add_refs(self, oids, n: int = 1, reason: str = "handle") -> None:
        self._refs.add(oids, reason, n)

    def remove_ref(self, oid: bytes, n: int = 1,
                   reason: str = "handle") -> None:
        """Owner-side count decrement; deletes (and cascades to contained
        refs) at zero.  Unsealed entries linger at count<=0 until their
        producer seals, then reclaim immediately."""
        self.remove_refs((oid,), n=n, reason=reason)

    def remove_refs(self, oids, n: int = 1, reason: str = "handle") -> None:
        dead = self._refs.remove(oids, reason, n)
        if dead:
            self._reap_dead_entries(dead)

    def _reap_dead_entries(self, dead_oids: List[bytes]) -> None:
        """Finish deletion for oids the ref index just erased: reap
        payloads, cascade containment pins (which can erase more entries),
        fire the on_delete hooks — the cold half of the old delete path."""
        reap: List[tuple] = []
        pending = list(dead_oids)
        while pending:
            children: List[bytes] = []
            with self._lock:
                for oid in pending:
                    e = self._objects.pop(oid, None)
                    if e is None:
                        continue
                    if e.loc is not None and e.sealed.is_set():
                        self._owner_agg_add(e, -1)
                    if e.loc is not None:
                        if e.loc.arena_path:
                            reap.append(("arena", (e.loc.arena_key,
                                                   e.loc.shm_name)))
                            if not e.loc.node_id:
                                self._bytes_used -= e.loc.size
                        elif e.loc.shm_name:
                            reap.append(("shm", e.loc.shm_name))
                            if not e.loc.node_id:
                                self._bytes_used -= e.loc.size
                        elif e.loc.spilled_path:
                            reap.append(("file", e.loc.spilled_path))
                    children.extend(e.contained)
                    if self.on_delete is not None:
                        reap.append(("hook", oid))
            pending = (self._refs.remove(children, "contained", 1)
                       if children else [])
        self._reap(reap)

    def _owner_agg_add(self, e: "_Entry", n: int) -> None:
        """Adjust the sealed-bytes-per-owner aggregate by ``n`` objects
        of the entry's current size (lock held; n is +1 on seal, -1 on
        unseal/delete — explicit, never inferred from a size sign that a
        zero-byte payload would break).  An object counts exactly while
        it is sealed with a location — the same filter a full
        owner_summary() scan would apply."""
        key = (e.owner or "unknown", e.owner_kind)
        agg = self._owner_agg.get(key)
        if agg is None:
            agg = self._owner_agg[key] = [0, 0]
        agg[0] += n * e.loc.size
        agg[1] += n
        if agg[1] <= 0:
            del self._owner_agg[key]

    def _reap(self, dead: List[tuple]) -> None:
        for kind, name in dead:
            if kind == "hook":
                if self.on_delete is not None:
                    self.on_delete(name)
            elif kind == "file":
                try:
                    os.unlink(name)
                except OSError:
                    pass
            elif kind == "arena":
                arena_key, copy_name = name
                if self.arena_delete is not None and arena_key:
                    self.arena_delete(arena_key)
                if copy_name:  # remote pulled copies use the shm name
                    ShmSegment.unlink(copy_name)
                    if self.broadcast_unlink is not None:
                        self.broadcast_unlink(copy_name)
            else:
                # origin copy or pulled replica in this process's namespace
                ShmSegment.unlink(name)
                if self.broadcast_unlink is not None:
                    self.broadcast_unlink(name)

    # -- capacity / spilling -------------------------------------------
    def _maybe_spill(self) -> None:
        """Move least-recently-accessed shm objects to disk until under the
        capacity (plasma eviction + LocalObjectManager spill analog).
        Spilled objects stay gettable — consumers read the file."""
        if self._capacity is None or self._spill_dir is None:
            return
        while True:
            with self._lock:
                if self._bytes_used <= self._capacity:
                    return
                now = time.monotonic()
                candidates = [
                    (e.last_access, oid, e)
                    for oid, e in self._objects.items()
                    if e.sealed.is_set() and e.loc is not None and e.loc.shm_name
                    and not e.loc.node_id  # remote segments aren't local files
                    and not e.loc.arena_path  # arena slices spill via delete
                    and now - e.last_access >= _SPILL_MIN_IDLE_S
                ]
                if not candidates:
                    return  # everything hot; stay over cap rather than race
                candidates.sort()
                _, oid, e = candidates[0]
                shm_name, size = e.loc.shm_name, e.loc.size
            os.makedirs(self._spill_dir, exist_ok=True)
            path = os.path.join(self._spill_dir, oid.hex())
            try:
                seg = ShmSegment.attach(shm_name, size)
                try:
                    with open(path, "wb") as f:
                        f.write(seg.buf)
                finally:
                    seg.close()
            except OSError:
                return
            with self._lock:
                e2 = self._objects.get(oid)
                if e2 is None or e2.loc is None or e2.loc.shm_name != shm_name:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    continue  # deleted concurrently
                e2.loc.shm_name = None
                e2.loc.spilled_path = path
                had_replicas = self._refs.num_replicas(oid) > 0
                if had_replicas:
                    self._refs.clear_replicas(oid)
                self._bytes_used -= size
                self._num_spilled += 1
                bytes_used = self._bytes_used
            _events.emit("object_store", "spilled object to disk",
                         severity="WARNING", entity_id=oid.hex(),
                         size_mb=round(size / (1 << 20), 2),
                         bytes_used=bytes_used, capacity=self._capacity)
            ShmSegment.unlink(shm_name)
            if had_replicas and self.broadcast_unlink is not None:
                # replica copies share the segment name on other nodes;
                # after the spill nothing would ever reap them (delete only
                # sees the spilled file) — unlink them with the original
                self.broadcast_unlink(shm_name)

    @staticmethod
    def _where(e: "_Entry") -> str:
        loc = e.loc
        if loc is None:
            return "pending"
        if loc.inline is not None:
            return "inline"
        if loc.spilled_path:
            return "spilled"
        return loc.node_id or "head"

    @staticmethod
    def _pin_reason(pins) -> str:
        """The dominant reason this object is still alive, in pin-strength
        order: a task-spec pin outlives handles, containment outlives a
        dropped handle.  ``pins`` is the ref index's slot list."""
        for reason in ("task_arg", "lineage", "contained", "handle"):
            if pins[_REASON_IDX[reason]] > 0:
                return reason
        return "unknown"

    # -- admin ---------------------------------------------------------
    def list_objects(self, limit: int = 1000) -> List[dict]:
        """State-API view of the object directory (list_objects analog)."""
        import itertools

        now = time.time()
        with self._lock:
            page = [
                (oid, e.sealed.is_set(), e.loc, self._where(e), e.owner,
                 e.owner_kind, e.created)
                for oid, e in itertools.islice(self._objects.items(), limit)
            ]
        counts, pins = self._refs.get_batch([row[0] for row in page])
        return [{
            "object_id": oid.hex(),
            "sealed": sealed,
            "ref_count": counts[i] if counts[i] is not None else 0,
            "size": loc.size if loc else None,
            "where": where,
            "owner": owner,
            "owner_kind": owner_kind,
            "pin_reason": self._pin_reason(pins[i]),
            "age_s": round(now - created, 1),
        } for i, (oid, sealed, loc, where, owner, owner_kind, created)
            in enumerate(page)]

    def memory_audit(self) -> List[dict]:
        """Every SEALED object with ownership/pin detail — the raw rows of
        the ``ray memory`` table.  Row fields snapshot under the lock;
        counts/pins come from one batch index call (its own mutex), so a
        full-table audit costs two lock hops, not one per row."""
        now = time.time()
        with self._lock:
            snap = [
                (oid, e.loc.size, self._where(e), e.owner or "unknown",
                 e.owner_kind, e.created)
                for oid, e in self._objects.items()
                if e.sealed.is_set() and e.loc is not None]
        counts, pins = self._refs.get_batch([row[0] for row in snap])
        rows = [{
            "object_id": oid.hex(),
            "size": size,
            "where": where,
            "owner": owner,
            "owner_kind": owner_kind,
            "ref_count": counts[i] if counts[i] is not None else 0,
            "pins": _pins_dict(pins[i]),
            "pin_reason": self._pin_reason(pins[i]),
            "age_s": round(now - created, 1),
        } for i, (oid, size, where, owner, owner_kind, created)
            in enumerate(snap)]
        rows.sort(key=lambda r: -r["size"])
        return rows

    def owner_summary(self) -> Dict[tuple, dict]:
        """Sealed bytes/objects by (owner, kind) from the incrementally-
        maintained aggregate — O(owners), never a table scan.  The shape
        the every-5s gauge refresh and ``top`` need; per-object rows and
        the pin-reason breakdown come from :meth:`memory_audit` (the
        explicit ``ray_tpu memory`` ask)."""
        with self._lock:
            return {key: {"bytes": agg[0], "objects": agg[1]}
                    for key, agg in self._owner_agg.items()}

    def stats(self) -> dict:
        with self._lock:
            return {
                "num_objects": len(self._objects),
                "bytes_used": self._bytes_used,
                "num_spilled": self._num_spilled,
            }

    def all_shm_names(self) -> List[str]:
        with self._lock:
            return [e.loc.shm_name for e in self._objects.values() if e.loc and e.loc.shm_name]

    def shutdown(self) -> None:
        for name in self.all_shm_names():
            ShmSegment.unlink(name)
        with self._lock:
            spilled = [e.loc.spilled_path for e in self._objects.values()
                       if e.loc and e.loc.spilled_path]
            self._objects.clear()
            self._refs.clear()
        for p in spilled:
            try:
                os.unlink(p)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# Producer / consumer helpers (run in any process)
# ---------------------------------------------------------------------------

_ATTACHED: Dict[str, ShmSegment] = {}
_ATTACHED_LOCK = make_lock("object_store.attached")


# Owner-side native arena (plasma analog); the head process sets this at
# Node init.  Worker processes keep the per-object-file path.
_OWNED_ARENA = None

# Above this size an arena put writes through the arena fd (os.pwrite,
# one kernel pass per page) instead of memcpy into the mapping: on
# never-faulted arena pages the mmap path pays fault+zero+copy per 4 KiB
# page, which is the 45x cliff multi-GiB (checkpoint-sized) values hit.
# Recycled (already-faulted) pages favor memcpy, and sub-64 MB objects
# mostly land on recycled slots, so the threshold keeps them there.
try:
    _ARENA_FD_WRITE_MIN = int(os.environ.get(
        "RAY_TPU_ARENA_FD_WRITE_MIN", str(64 << 20)))
except ValueError:  # malformed override: keep the default, don't die at import
    _ARENA_FD_WRITE_MIN = 64 << 20
# reader-side cache: arena path -> memoryview over its mmap
_ARENA_MAPS: Dict[str, memoryview] = {}
_ARENA_MAPS_LOCK = make_lock("object_store.arena_maps")


def set_owned_arena(arena) -> None:
    global _OWNED_ARENA
    _OWNED_ARENA = arena


class _ArenaPin:
    """Holds one head-side reference on an arena object for as long as any
    zero-copy view of it is alive (the plasma client-pin analog: the slot
    cannot be recycled under a live numpy array)."""

    __slots__ = ("_oid",)

    def __init__(self, oid: bytes):
        self._oid = oid

    def __del__(self):
        try:
            from ray_tpu._private.worker import global_worker

            client = global_worker.client
            if client is not None and not client.closed:
                client.remove_refs([self._oid])
        except Exception:
            pass


class _PinnedArenaMap(__import__("mmap").mmap):
    """mmap subclass that can carry attributes — see
    :func:`_pinned_arena_slice`."""


def _pinned_arena_slice(path: str, off: int, size: int,
                        pin: _ArenaPin) -> memoryview:
    """A zero-copy view of ``[off, off+size)`` of the arena file whose
    buffer chain owns ``pin``: a private mmap subclass instance carries the
    pin as an attribute, every exported memoryview keeps its exporting
    mmap alive, and the mmap's deallocation drops the pin — so the
    head-side reference lives exactly as long as any deserialized view
    (numpy array, bytes slice) over this object.  Works on every CPython
    (no PEP 688 ``__buffer__`` needed; plain classes can't export buffers
    before 3.12)."""
    import mmap as mmap_mod

    gran = mmap_mod.ALLOCATIONGRANULARITY
    base = (off // gran) * gran
    delta = off - base
    fd = os.open(path, os.O_RDONLY)
    try:
        mm = _PinnedArenaMap(fd, delta + size, prot=mmap_mod.PROT_READ,
                             offset=base)
    finally:
        os.close(fd)  # the mapping outlives the fd
    mm._pin = pin
    return memoryview(mm)[delta:delta + size]


def _arena_view(path: str) -> memoryview:
    import mmap as mmap_mod

    with _ARENA_MAPS_LOCK:
        view = _ARENA_MAPS.get(path)
        if view is None:
            if _OWNED_ARENA is not None and _OWNED_ARENA.path == path:
                view = _OWNED_ARENA.buf
            else:
                fd = os.open(path, os.O_RDONLY)
                try:
                    size = os.fstat(fd).st_size
                    mm = mmap_mod.mmap(fd, size, prot=mmap_mod.PROT_READ)
                finally:
                    os.close(fd)
                view = memoryview(mm)
            _ARENA_MAPS[path] = view
        return view


def store_value(ref: ObjectRef, value: Any, is_error: bool = False) -> Tuple[ObjectLocation, list]:
    """Serialize ``value``; write big payloads to shm. Returns (location, contained_refs)."""
    if not _events.ENABLED:
        return _store_value(ref, value, is_error)
    global _put_n
    t0 = time.perf_counter()
    out = _store_value(ref, value, is_error)
    size = out[0].size
    _put_n += 1
    if size > _SMALL_SAMPLE_MAX_BYTES or _put_n % _SMALL_SAMPLE == 1:
        _store_metrics()["put"].observe(time.perf_counter() - t0)
    if size >= _PUT_EVENT_MIN_BYTES:
        _events.emit("object_store", "large shm put", severity="DEBUG",
                     entity_id=ref.hex(), size_mb=round(size / (1 << 20), 2))
    return out


def _store_value(ref: ObjectRef, value: Any, is_error: bool = False) -> Tuple[ObjectLocation, list]:
    cfg = get_config()
    meta, buffers, refs = serialization.serialize(value)
    total = serialization.total_size(meta, buffers)
    if total <= cfg.max_direct_call_object_size:
        blob = serialization.to_bytes(meta, buffers)
        return ObjectLocation(inline=blob, is_error=is_error), refs
    name = session_shm_name(ref.hex())
    if _OWNED_ARENA is not None:
        # native path: allocate a slice of the session arena and write in
        # place (recycled pages skip the fresh-file fault-and-zero cost)
        key = ref.binary()
        off = _OWNED_ARENA.put(key, total)
        if off is None and _OWNED_ARENA.get(key) is not None:
            # a prior attempt of this task left an allocation (it may be
            # SEALED and live — never touch it); index this attempt under
            # a fresh key and let first-seal-wins pick the survivor
            key = os.urandom(16)  # raylint: disable=R3 (retry-only path)
            off = _OWNED_ARENA.put(key, total)
        if off is not None:
            if total >= _ARENA_FD_WRITE_MIN:
                # single-pass write for multi-GiB values (see threshold
                # comment above); coherent with every reader's arena mmap
                written = serialization.write_to_fd_at(
                    _OWNED_ARENA.fd, off, meta, buffers)
                assert written == total, (written, total)
            else:
                serialization.write_into(
                    _OWNED_ARENA.buf[off:off + total], meta, buffers)
            _OWNED_ARENA.seal(key)
            return ObjectLocation(
                shm_name=name, size=total, is_error=is_error,
                arena_path=_OWNED_ARENA.path, arena_off=off, arena_key=key,
            ), refs
        # arena full: fall through to the per-object-file path
        _events.emit("object_store", "arena full; per-object segment fallback",
                     severity="WARNING", entity_id=ref.hex(), size=total)
    # producer side writes through the fd (page-allocation path, ~2.4x the
    # mmap-memcpy bandwidth on tmpfs); consumers still mmap zero-copy
    name = _write_segment(
        name, lambda fd: serialization.write_to_fd(fd, meta, buffers), total
    )
    return ObjectLocation(shm_name=name, size=total, is_error=is_error), refs


def _write_segment(name: str, write_fn, expected: int) -> str:
    """Exclusive-create a named shm segment and fill it via ``write_fn(fd)``.

    A name collision means a prior attempt of the same task created the
    segment; it may be a SEALED live object — never unlink or rewrite it.
    This attempt publishes under a unique name and first-seal-wins reaps
    the loser.  Any write failure unlinks the partial file."""
    path = ShmSegment.path_for(name)
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
    except FileExistsError:
        name = f"{name}-r{os.urandom(3).hex()}"  # raylint: disable=R3 (collision retry)
        path = ShmSegment.path_for(name)
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
    try:
        written = write_fn(fd)
        assert written == expected, f"wrote {written}, expected {expected}"
    except BaseException:
        os.close(fd)
        os.unlink(path)
        raise
    os.close(fd)
    return name


def store_blob(ref: ObjectRef, blob: bytes, is_error: bool = False) -> ObjectLocation:
    """Store an already-serialized payload (thin-client put: the client
    shipped the bytes over the control socket because it shares no shm with
    this host).  Small blobs stay inline; big ones land in local shm."""
    cfg = get_config()
    if len(blob) <= cfg.max_direct_call_object_size:
        return ObjectLocation(inline=bytes(blob), is_error=is_error)

    def write_all(fd: int) -> int:
        view = memoryview(blob)
        total = 0
        while view:  # os.write caps single writes (~2 GiB on Linux)
            n = os.write(fd, view)
            view = view[n:]
            total += n
        return total

    name = _write_segment(session_shm_name(ref.hex()), write_all, len(blob))
    return ObjectLocation(shm_name=name, size=len(blob), is_error=is_error)


def payload_bytes(loc: ObjectLocation) -> bytes:
    """The serialized payload at ``loc`` as bytes (thin-client get: the
    caller can't attach this host's shm, so the head reads the bytes out
    and ships them over the socket).  Remote-node segments are pulled into
    the local namespace first, exactly like :func:`read_value`."""
    if loc.inline is not None:
        return loc.inline
    if loc.spilled_path is not None:
        with open(loc.spilled_path, "rb") as f:
            return f.read()
    arena_src = None
    if loc.arena_path is not None:
        try:
            view = _arena_view(loc.arena_path)
            return bytes(view[loc.arena_off:loc.arena_off + loc.size])
        except FileNotFoundError:
            if not loc.fetch_addr:
                raise
            # remote arena-backed object: the origin serves the arena slice
            # under the object's shm name (same pull read_value does)
            arena_src = (loc.arena_path, loc.arena_off)
    with _ATTACHED_LOCK:
        seg = _ATTACHED.get(loc.shm_name)
    if seg is None:
        try:
            seg = ShmSegment.attach(loc.shm_name, loc.size)
        except FileNotFoundError:
            if not loc.fetch_addr:
                raise
            from ray_tpu._private import object_transfer

            object_transfer.pull_object(
                loc.shm_name, loc.fetch_addr, loc.size, arena=arena_src
            )
            seg = ShmSegment.attach(loc.shm_name, loc.size)
        with _ATTACHED_LOCK:
            seg = _ATTACHED.setdefault(loc.shm_name, seg)
    return bytes(seg.buf)


def _report_replica(oid: Optional[bytes]) -> None:
    """Tell the head this node now holds a copy (location-set update; the
    head records it only for real agent nodes)."""
    if oid is None:
        return
    try:
        from ray_tpu._private.worker import global_worker

        client = global_worker.client
        if client is not None and not client.closed:
            client.send({"type": "replica_added", "oid": oid})
    except Exception:
        pass  # best-effort: the directory just misses one source


def read_value(loc: ObjectLocation, oid: Optional[bytes] = None) -> Any:
    """Deserialize an object from its location (zero-copy for shm payloads;
    spilled objects are read back from disk; remote segments are pulled
    into the local shm namespace first — ``ray.get`` step 3 in SURVEY §3.3).

    ``oid`` enables zero-copy reads of arena-backed objects: the views are
    pinned with a head-side reference so the slot can't be recycled under
    them.  Without an oid, arena payloads are copied out for safety."""
    if not _events.ENABLED:
        return _read_value(loc, oid)
    global _get_n
    t0 = time.perf_counter()
    value = _read_value(loc, oid)
    _get_n += 1
    if loc.size > _SMALL_SAMPLE_MAX_BYTES or _get_n % _SMALL_SAMPLE == 1:
        _store_metrics()["get"].observe(time.perf_counter() - t0)
    return value


def _read_value(loc: ObjectLocation, oid: Optional[bytes] = None) -> Any:
    if loc.inline is not None:
        value = serialization.deserialize(memoryview(loc.inline))
    elif loc.spilled_path is not None:
        with open(loc.spilled_path, "rb") as f:
            value = serialization.deserialize(memoryview(f.read()))
    elif loc.arena_path is not None:
        try:
            payload = None
            if oid is not None:
                from ray_tpu._private.worker import global_worker

                client = global_worker.client
                if client is not None and not client.closed:
                    # the caller's handle is live right now, so this
                    # add_ref cannot race the object's deletion
                    client.add_refs([oid])
                    payload = _pinned_arena_slice(
                        loc.arena_path, loc.arena_off, loc.size,
                        _ArenaPin(oid))
            if payload is None:
                view = _arena_view(loc.arena_path)
                payload = memoryview(
                    bytes(view[loc.arena_off:loc.arena_off + loc.size]))
            value = serialization.deserialize(payload)
        except FileNotFoundError:
            # remote node: pull a private copy named loc.shm_name
            if not loc.fetch_addr:
                raise
            from ray_tpu._private import object_transfer

            object_transfer.pull_object(
                loc.shm_name, loc.fetch_addr, loc.size,
                arena=(loc.arena_path, loc.arena_off),
            )
            _report_replica(oid)
            seg = ShmSegment.attach(loc.shm_name, loc.size)
            with _ATTACHED_LOCK:
                seg = _ATTACHED.setdefault(loc.shm_name, seg)
            value = serialization.deserialize(seg.buf)
    else:
        with _ATTACHED_LOCK:
            seg = _ATTACHED.get(loc.shm_name)
        if seg is None:
            try:
                seg = ShmSegment.attach(loc.shm_name, loc.size)
            except FileNotFoundError:
                if not loc.fetch_addr:
                    raise
                from ray_tpu._private import object_transfer

                object_transfer.pull_object(loc.shm_name, loc.fetch_addr, loc.size)
                _report_replica(oid)
                seg = ShmSegment.attach(loc.shm_name, loc.size)
            with _ATTACHED_LOCK:
                seg = _ATTACHED.setdefault(loc.shm_name, seg)
        value = serialization.deserialize(seg.buf)
    if loc.is_error:
        raise value
    return value
