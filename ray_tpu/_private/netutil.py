"""Socket teardown helpers.

On Linux, ``close()`` on a socket fd does NOT wake another thread blocked
in ``accept()``/``recv()`` on it — the thread stays parked forever.  Every
session teardown therefore leaked its accept loops, per-connection reader
threads, and client recv threads (~5 threads + 3 fds per init/shutdown in
one process; a full test suite accumulated ~1500 threads and starved the
scheduler).  ``shutdown(SHUT_RDWR)`` is the call that interrupts blocked
socket syscalls; these helpers apply it through the stdlib's private
attributes with best-effort fallbacks.
"""

from __future__ import annotations

import socket


def force_close_connection(conn) -> None:
    """Shut down + close a multiprocessing.Connection so any thread
    blocked in ``recv`` on it wakes with EOF."""
    try:
        # fromfd DUPS the fd; shutdown() acts on the shared underlying
        # socket, so the blocked thread's recv returns immediately
        dup = socket.fromfd(conn.fileno(), socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            dup.shutdown(socket.SHUT_RDWR)
        finally:
            dup.close()
    except Exception:
        pass
    try:
        conn.close()
    except Exception:
        pass


def unblock_listener(listener) -> None:
    """Wake a thread blocked in ``Listener.accept()`` so its loop can see
    the shutdown flag (call BEFORE/with ``listener.close()``)."""
    try:
        sock = listener._listener._socket  # SocketListener private attr
        sock.shutdown(socket.SHUT_RDWR)
    except Exception:
        pass
    try:
        listener.close()
    except Exception:
        pass
