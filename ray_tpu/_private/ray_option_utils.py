"""Validation of ``@remote``/``.options`` arguments.

Single source of truth for task/actor options, mirroring
``python/ray/_private/ray_option_utils.py:118-184`` (num_cpus/num_tpus/
max_retries/max_restarts/num_returns/resources/...).
"""

from __future__ import annotations

from typing import Any, Dict

TASK_OPTIONS = {
    "num_cpus", "num_tpus", "resources", "num_returns", "max_retries",
    "scheduling_strategy", "name", "runtime_env", "memory",
}
ACTOR_OPTIONS = {
    "num_cpus", "num_tpus", "resources", "max_restarts", "max_task_retries",
    "scheduling_strategy", "name", "lifetime", "runtime_env", "memory",
    "max_concurrency",
}


def validate_options(opts: Dict[str, Any], for_actor: bool) -> Dict[str, Any]:
    allowed = ACTOR_OPTIONS if for_actor else TASK_OPTIONS
    for k in opts:
        if k not in allowed:
            raise ValueError(
                f"Invalid option {k!r} for {'actor' if for_actor else 'task'}; "
                f"allowed: {sorted(allowed)}"
            )
    for k in ("num_cpus", "num_tpus", "memory"):
        v = opts.get(k)
        if v is not None and (not isinstance(v, (int, float)) or v < 0):
            raise ValueError(f"{k} must be a non-negative number, got {v!r}")
    nr = opts.get("num_returns")
    if nr is not None and (not isinstance(nr, int) or nr < 1):
        raise ValueError(f"num_returns must be an int >= 1, got {nr!r}")
    return opts


def resources_from_options(opts: Dict[str, Any], default_num_cpus: float) -> Dict[str, float]:
    res: Dict[str, float] = dict(opts.get("resources") or {})
    if "CPU" in res or "TPU" in res:
        raise ValueError("Use num_cpus/num_tpus instead of resources={'CPU': ...}")
    num_cpus = opts.get("num_cpus")
    res["CPU"] = float(default_num_cpus if num_cpus is None else num_cpus)
    num_tpus = opts.get("num_tpus")
    if num_tpus:
        res["TPU"] = float(num_tpus)
    if opts.get("memory"):
        res["memory"] = float(opts["memory"])
    return {k: v for k, v in res.items() if v != 0}
