"""Validation of ``@remote``/``.options`` arguments.

Single source of truth for task/actor options, mirroring
``python/ray/_private/ray_option_utils.py:118-184`` (num_cpus/num_tpus/
max_retries/max_restarts/num_returns/resources/...).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

TASK_OPTIONS = {
    "num_cpus", "num_tpus", "resources", "num_returns", "max_retries",
    "scheduling_strategy", "name", "runtime_env", "memory",
}
ACTOR_OPTIONS = {
    "num_cpus", "num_tpus", "resources", "max_restarts", "max_task_retries",
    "scheduling_strategy", "name", "lifetime", "runtime_env", "memory",
    "max_concurrency", "namespace", "concurrency_groups",
}

# env_vars/working_dir apply at spawn; pip/conda build hash-keyed cached
# envs in the worker's bootstrap (``runtime_env_setup.py``; reference
# ``python/ray/_private/runtime_env/{pip,conda}.py``); working_dir and
# py_modules local paths are zipped into content-addressed ``gcs://``
# packages shipped through the cluster KV
# (``runtime_env_packaging.py``; reference ``runtime_env/packaging.py``).
# ``container`` (podman rootless containers) is rejected loudly instead
# of silently dropped — no container runtime in the TPU image.
SUPPORTED_RUNTIME_ENV_KEYS = {
    "env_vars", "working_dir", "pip", "conda", "py_modules", "excludes",
}


def validate_runtime_env(runtime_env: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    if runtime_env is None:
        return None
    if not isinstance(runtime_env, dict):
        raise TypeError(f"runtime_env must be a dict, got {type(runtime_env)}")
    unsupported = set(runtime_env) - SUPPORTED_RUNTIME_ENV_KEYS
    if unsupported:
        hint = (" ('container' needs a container runtime, absent from the "
                "TPU image — use 'pip'/'conda' + 'py_modules' instead)"
                if "container" in unsupported else "")
        raise ValueError(
            f"Unsupported runtime_env keys {sorted(unsupported)}; this build "
            f"supports {sorted(SUPPORTED_RUNTIME_ENV_KEYS)}{hint}"
        )
    env_vars = runtime_env.get("env_vars")
    if env_vars is not None:
        if not isinstance(env_vars, dict) or not all(
            isinstance(k, str) and isinstance(v, str) for k, v in env_vars.items()
        ):
            raise TypeError("runtime_env['env_vars'] must be a Dict[str, str]")
    working_dir = runtime_env.get("working_dir")
    if working_dir is not None:
        ok = isinstance(working_dir, str) and (
            working_dir.startswith("gcs://")  # already-packaged URI
            or os.path.isdir(working_dir)
            or (working_dir.endswith(".zip") and os.path.isfile(working_dir)))
        if not ok:
            raise ValueError(
                f"runtime_env['working_dir'] must be an existing local "
                f"directory, a .zip file, or a gcs:// package URI, "
                f"got {working_dir!r}"
            )
    py_modules = runtime_env.get("py_modules")
    if py_modules is not None:
        if not isinstance(py_modules, list):
            raise TypeError("runtime_env['py_modules'] must be a list of "
                            "local dirs / .zip files / gcs:// URIs")
        for m in py_modules:
            ok = isinstance(m, str) and (
                m.startswith("gcs://") or os.path.isdir(m)
                or (m.endswith(".zip") and os.path.isfile(m)))
            if not ok:
                raise ValueError(
                    f"runtime_env['py_modules'] entry {m!r} is not an "
                    f"existing local directory, .zip file, or gcs:// URI")
    excludes = runtime_env.get("excludes")
    if excludes is not None and not (
            isinstance(excludes, list)
            and all(isinstance(e, str) for e in excludes)):
        raise TypeError("runtime_env['excludes'] must be List[str] of "
                        "glob patterns")
    conda = runtime_env.get("conda")
    if conda is not None:
        if runtime_env.get("pip"):
            raise ValueError(
                "runtime_env cannot specify both 'pip' and 'conda' "
                "(build one env: put pip packages under the conda spec)")
        if not isinstance(conda, (str, dict)):
            raise TypeError(
                "runtime_env['conda'] must be an env NAME (str) or an "
                "environment.yml dict")
    pip = runtime_env.get("pip")
    if pip is not None:
        # list of requirements, or {"packages": [...], "pip_install_options":
        # [...]} (reference python/ray/_private/runtime_env/pip.py surface)
        if isinstance(pip, dict):
            unknown = set(pip) - {"packages", "pip_install_options"}
            if unknown:
                raise ValueError(
                    f"unsupported runtime_env['pip'] keys {sorted(unknown)}; "
                    f"supported: ['packages', 'pip_install_options']")
            pkgs = pip.get("packages")
            opts_ = pip.get("pip_install_options", [])
            if not isinstance(pkgs, list) or not all(isinstance(p, str) for p in pkgs):
                raise TypeError("runtime_env['pip']['packages'] must be List[str]")
            if not isinstance(opts_, list) or not all(isinstance(o, str) for o in opts_):
                raise TypeError(
                    "runtime_env['pip']['pip_install_options'] must be List[str]")
        elif not (isinstance(pip, list) and all(isinstance(p, str) for p in pip)):
            raise TypeError(
                "runtime_env['pip'] must be a List[str] of requirements or a "
                "dict with 'packages'")
    return runtime_env


def validate_options(opts: Dict[str, Any], for_actor: bool) -> Dict[str, Any]:
    allowed = ACTOR_OPTIONS if for_actor else TASK_OPTIONS
    for k in opts:
        if k not in allowed:
            raise ValueError(
                f"Invalid option {k!r} for {'actor' if for_actor else 'task'}; "
                f"allowed: {sorted(allowed)}"
            )
    for k in ("num_cpus", "num_tpus", "memory"):
        v = opts.get(k)
        if v is not None and (not isinstance(v, (int, float)) or v < 0):
            raise ValueError(f"{k} must be a non-negative number, got {v!r}")
    nr = opts.get("num_returns")
    if nr is not None and nr != "dynamic" and (not isinstance(nr, int) or nr < 1):
        raise ValueError(
            f"num_returns must be an int >= 1 or \"dynamic\", got {nr!r}")
    if nr == "dynamic" and for_actor:
        raise ValueError("num_returns=\"dynamic\" is not supported for actors")
    mc = opts.get("max_concurrency")
    if mc is not None and (not isinstance(mc, int) or mc < 1):
        raise ValueError(f"max_concurrency must be an int >= 1, got {mc!r}")
    lt = opts.get("lifetime")
    if lt not in (None, "detached"):
        raise ValueError(
            f'lifetime must be None or "detached", got {lt!r}')
    ns = opts.get("namespace")
    if ns is not None and (not isinstance(ns, str) or not ns):
        raise ValueError(f"namespace must be a non-empty string, got {ns!r}")
    cg = opts.get("concurrency_groups")
    if cg is not None:
        if (not isinstance(cg, dict) or not cg or not all(
                isinstance(k, str) and k and isinstance(v, int) and v >= 1
                for k, v in cg.items())):
            raise ValueError(
                "concurrency_groups must be a non-empty Dict[str, int>=1] "
                f"of group name -> max concurrency, got {cg!r}")
        if "_default" in cg:
            raise ValueError(
                '"_default" is reserved (the unnamed max_concurrency pool)')
    if "runtime_env" in opts:
        validate_runtime_env(opts["runtime_env"])
    return opts


def resources_from_options(opts: Dict[str, Any], default_num_cpus: float) -> Dict[str, float]:
    # coerce custom amounts at the source: a str amount (e.g. {"accel":
    # "1"}) must become a float HERE, or the head's scheduler compares
    # float >= str and dies; a non-numeric amount errors at submission
    try:
        res: Dict[str, float] = {
            k: float(v) for k, v in (opts.get("resources") or {}).items()
        }
    except (TypeError, ValueError) as e:
        raise TypeError(
            f"resources amounts must be numeric: {opts.get('resources')!r}"
        ) from e
    if "CPU" in res or "TPU" in res:
        raise ValueError("Use num_cpus/num_tpus instead of resources={'CPU': ...}")
    num_cpus = opts.get("num_cpus")
    res["CPU"] = float(default_num_cpus if num_cpus is None else num_cpus)
    num_tpus = opts.get("num_tpus")
    if num_tpus:
        res["TPU"] = float(num_tpus)
    if opts.get("memory"):
        res["memory"] = float(opts["memory"])
    return {k: v for k, v in res.items() if v != 0}
