"""Cluster flight recorder: bounded structured-event ring per process.

Analog of the reference's event framework (``dashboard/modules/event/`` +
``src/ray/util/event.h``): every process keeps a small ring buffer of
structured events (ts, severity, source, entity id, message, data) that
subsystems emit on their hot paths — dispatch decisions, spills, OOM
kills, backpressure stalls, slot admissions.  Dapper's rules apply:
always-on, bounded memory (O(capacity), never O(events)), and cheap
enough to leave enabled (<3% of task throughput, gated by the
``observability_overhead`` bench row).

Transport: workers batch-ship new events to the head over the control
connection (the ``metrics_report`` path) via :class:`EventsPusher`; the
head folds them into a capped per-source :class:`EventTable` served by
``ray_tpu events`` / ``experimental.state.api.list_events`` / the
dashboard's ``/api/events``.  The pusher also rewrites a crash-dump file
under the session log dir each cycle, so even a SIGKILL'd process leaves
its last-flushed ring on disk.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR")

# Well-known event sources (informational — the table accepts any source).
# Kept current so `ray_tpu events --source X` is discoverable; matches the
# emit sites and the cli.py --source help.  compiled_dag (dag/compiled.py)
# carries per-node exec spans + channel-wait spans of compiled execution
# graphs.
KNOWN_SOURCES = (
    "scheduler", "node", "actor", "worker_pool", "object_store",
    "streaming", "serve", "serve_llm", "train", "collective",
    "compiled_dag", "trace",
    # "serve" carries the ingress fault-tolerance signal set doctor's
    # ingress_shedding / drain_stuck rules read: `ingress shedding
    # started`/`stopped` (router watermark + proxy in-flight cap, with
    # hysteresis so an episode is two events, not one per refused
    # request), `replica draining`/`drained`/`drain timeout`, `request
    # retried after replica death`, `routing refresh failed`, and
    # `deployment scaled`; shed/retry volume rides the
    # ray_tpu_serve_shed_total counter and ingress_stats()
    # slice failure domain: P2P mesh observations (_private/syncer.py),
    # fault injections (devtools/chaos), scale/replace decisions
    # (autoscaler/policy.py) — doctor and the timeline correlate cause
    # (chaos) with symptom (syncer/node) and remedy (autoscaler)
    "syncer", "chaos", "autoscaler",
    # device-time performance attribution (util/perf.py + serve/llm.py):
    # step-phase spans, jit compile events, prefill-interference meters
    # — what `ray_tpu perf` and the doctor's perf rules read
    "perf",
    # multi-tenancy lifecycle (util/client proxier + node.py tenant reap):
    # tenant registered/driver spawned/driver died/reaped — what doctor's
    # tenant_killed rule and the tenant-kill chaos scenario read
    "client_proxy",
    # RL sample/train/inference spans (rllib/rollout_worker.py,
    # algorithm.py train_one_step, policy_server.py): per-fragment
    # env/inference/connector/postprocess attribution — what the
    # rl_env_steps_scaling knee attribution and the timeline read
    "rllib",
    # continuous-profiling lifecycle (_private/sampling_profiler.py +
    # node.py ProfileStore retirement): profiler started/stopped, interval
    # backoff/reset, profile ship failures, dead-origin retirement — the
    # audit trail for why a window has thin (backed-off) or missing
    # (retired origin) flamegraph coverage
    "profile",
    # log plane (_private/log_plane.py + util/log_store.py + node.py):
    # error/traceback bursts from a single stream, worker-died-with-
    # uncollected-stderr crash explanations, dead-stream retirement —
    # what doctor's log_error_burst / worker_stderr_at_death rules read
    "log",
    # watchdog incident lifecycle (util/watchdog.py + util/incidents.py):
    # every open/ack/escalate/resolve transition of a tracked incident,
    # carrying the incident id, rule, and entity — the flight-recorder
    # audit trail `ray_tpu incidents --history` and post-mortem bundles
    # cross-reference
    "incident",
)

# Kill switch for the whole observability layer (events + hot-path metric
# observations).  Initialized from the env, but MUTABLE module state read
# per-emit: the observability_overhead bench flips it at runtime in a live
# cluster (head + workers), so new instrumentation must not cache it.
ENABLED = os.environ.get("RAY_TPU_EVENTS", "1") not in ("0", "false", "no")


def _int_env(name: str, default: int) -> int:
    """Shared env-int parse-with-fallback (util/tsdb.py imports these two
    rather than growing a third copy)."""
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _float_env(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


DEFAULT_CAPACITY = _int_env("RAY_TPU_EVENTS_CAPACITY", 4096)
# per-source cap at the head (one cluster-wide table, bounded per source)
DEFAULT_TABLE_CAPACITY = _int_env("RAY_TPU_EVENTS_TABLE_CAPACITY", 10_000)
DEFAULT_FLUSH_S = _float_env("RAY_TPU_EVENTS_FLUSH_S", 2.0)


class EventBuffer:
    """Bounded ring of event records; memory stays O(capacity) forever
    (deque maxlen eviction).

    The hot half is :meth:`emit`: it appends one TUPLE (no dict build, no
    string formatting) so the per-event cost on instrumented paths like
    task dispatch stays ~1-2us; records materialize as dicts only when
    read (snapshot/ship), which happens at the pusher's cadence, not the
    workload's."""

    # tuple layout: (seq, ts, severity, source, message, entity_id,
    #               span_dur, data)
    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(1, int(capacity))
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0  # monotone id; lets the pusher ship only new events

    def emit(self, source: str, message: str, severity: str = "INFO",
             entity_id: Optional[str] = None, span_dur: Optional[float] = None,
             ts: Optional[float] = None, **data) -> None:
        # ts override: for span events recorded AFTER the fact (e.g. a
        # node loop emitting several input-edge waits once their trace
        # lineage is known), the caller passes the span's true end time
        if ts is None:
            ts = time.time()
        with self._lock:
            self._seq += 1
            self._ring.append((self._seq, ts, severity, source, message,
                               entity_id, span_dur, data or None))

    @staticmethod
    def _to_dict(rec) -> dict:
        seq, ts, severity, source, message, entity_id, span_dur, data = rec
        out = {"ts": ts, "severity": severity, "source": source,
               "message": message, "pid": os.getpid(), "seq": seq}
        if entity_id is not None:
            out["entity_id"] = entity_id
        if span_dur is not None:
            # span events: [ts - span_dur, ts] renders as a timeline slice
            out["span_dur"] = span_dur
        if data:
            out["data"] = data
        return out

    def snapshot(self, limit: Optional[int] = None) -> List[dict]:
        with self._lock:
            rows = list(self._ring)
        if limit:
            rows = rows[-limit:]
        return [self._to_dict(r) for r in rows]

    def since(self, seq: int) -> List[dict]:
        """Events with seq > ``seq`` (the pusher's incremental cursor)."""
        with self._lock:
            rows = [r for r in self._ring if r[0] > seq]
        return [self._to_dict(r) for r in rows]

    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


_BUFFER = EventBuffer()


def buffer() -> EventBuffer:
    return _BUFFER


def emit(source: str, message: str, severity: str = "INFO",
         entity_id: Optional[str] = None, span_dur: Optional[float] = None,
         ts: Optional[float] = None, **data) -> None:
    """Record one structured event in this process's ring (no-op when the
    observability layer is disabled)."""
    if not ENABLED:
        return
    _BUFFER.emit(source, message, severity, entity_id, span_dur, ts, **data)


def enabled() -> bool:
    return ENABLED


def local_events(limit: Optional[int] = None) -> List[dict]:
    return _BUFFER.snapshot(limit)


# Crash-dump files rotate (path -> path.1) past this size so a long-lived
# process's trail stays bounded on disk.
_DUMP_ROTATE_BYTES = 4 << 20


def append_dump(path: str, rows: List[dict]) -> Optional[str]:
    """Append events to the JSONL crash-dump file (one event per line).

    Incremental by design: rewriting the whole ring as one JSON blob every
    flush cycle held the GIL for tens of ms per rewrite and cost ~4% of
    task throughput on the head — appending only the NEW events is
    O(new), which is what makes the always-on crash dump affordable.

    Never raises: emit(**data) accepts arbitrary app payloads (numpy
    scalars included — hence ``default=repr``), and a dump failure must
    not kill the calling thread (the head's gcs-flush loop, a worker's
    pusher)."""
    if not rows:
        return None
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        try:
            if os.path.getsize(path) > _DUMP_ROTATE_BYTES:
                os.replace(path, path + ".1")
        except OSError:
            pass  # no file yet
        with open(path, "a") as f:
            f.write("\n".join(json.dumps(r, default=repr) for r in rows)
                    + "\n")
        return path
    except Exception:
        return None


def load_dump(path: str) -> List[dict]:
    """Read a JSONL crash-dump file back (skipping any torn final line a
    SIGKILL mid-write may have left)."""
    rows: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except ValueError:
                continue
    return rows


def dump_now(path: str) -> Optional[str]:
    """One-shot append of the WHOLE current ring (debug path — the
    periodic pushers use the incremental cursor instead)."""
    return append_dump(path, _BUFFER.snapshot())


class EventTable:
    """Head-side capped event directory: one ring per source so a chatty
    subsystem can never evict another's history."""

    def __init__(self, capacity_per_source: int = DEFAULT_TABLE_CAPACITY):
        self._cap = max(1, int(capacity_per_source))
        self._by_source: Dict[str, deque] = {}
        self._lock = threading.Lock()
        # monotonically increasing per-row counter + a ring of recent
        # (version, row) pairs: the watchdog's incremental cursor.  A
        # reader remembers the version it last saw and `since()` hands it
        # only the delta — no full-table pull per tick.
        self._version = 0
        self._recent: deque = deque(maxlen=self._cap)

    def add(self, origin: str, rows: List[dict]) -> None:
        with self._lock:
            for r in rows:
                if not isinstance(r, dict) or "source" not in r:
                    continue
                r = dict(r)
                r["origin"] = origin
                q = self._by_source.get(r["source"])
                if q is None:
                    q = self._by_source[r["source"]] = deque(maxlen=self._cap)
                q.append(r)
                self._version += 1
                self._recent.append((self._version, r))

    def version(self) -> int:
        """Monotonic ingest counter — unchanged version means no new rows
        since the caller's last look (the watchdog's cheap no-op check)."""
        with self._lock:
            return self._version

    def since(self, cursor: int) -> Tuple[List[dict], int]:
        """(rows ingested after ``cursor``, new cursor).  Bounded by the
        recent ring: a reader that falls further behind than the ring
        keeps only what is still resident (same contract as the per-source
        rings themselves — old rows are gone either way)."""
        with self._lock:
            rows = [r for v, r in self._recent if v > cursor]
            return rows, self._version

    def list(self, limit: int = 1000, source: Optional[str] = None,
             severity: Optional[str] = None) -> List[dict]:
        return self.list_with_total(limit, source, severity)[0]

    def list_with_total(self, limit: int = 1000, source: Optional[str] = None,
                        severity: Optional[str] = None,
                        ) -> Tuple[List[dict], int]:
        """(newest ``limit`` filtered rows, filtered total) in one pass —
        the state API's truncation marker needs the total, and computing
        it by listing the whole table a second time doubled the sort on
        every dashboard poll."""
        with self._lock:
            if source is not None:
                rows = list(self._by_source.get(source, ()))
            else:
                rows = [r for q in self._by_source.values() for r in q]
        if severity is not None:
            rows = [r for r in rows if r.get("severity") == severity]
        total = len(rows)
        rows.sort(key=lambda r: r.get("ts", 0.0))
        return rows[-limit:], total

    def sources(self) -> List[str]:
        with self._lock:
            return sorted(self._by_source)

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return {s: len(q) for s, q in self._by_source.items()}


DEFAULT_TRACE_CAPACITY = _int_env("RAY_TPU_TRACE_CAPACITY", 512)
DEFAULT_TRACE_SPANS = _int_env("RAY_TPU_TRACE_SPANS", 2048)

# event-data keys that are span LINEAGE (hoisted onto the span record);
# everything else in data stays as span attributes
_SPAN_KEYS = ("trace_id", "span_id", "parent_span_id", "phase")


class TraceTable:
    """Head-side per-trace span directory (``dashboard/state_aggregator``
    + OpenTelemetry-collector analog): any shipped event whose data
    carries a ``trace_id`` — ``trace``-source spans, traced compiled-graph
    node/channel spans — is folded into its trace's span list.

    Bounded both ways: at most ``max_traces`` traces (least-recently
    UPDATED evicted first, so a long-running trace stays resident while
    one-shot traces age out) and ``max_spans`` spans per trace, keeping
    the LAST N: spans are emitted when they CLOSE, so parents always
    arrive after their children and the root/ingress span arrives last
    of all — keep-last preserves the root and upper tree (what the span
    tree and wall-time attribution hang off), shedding the oldest leaf
    spans first.  ``dropped`` counts what was shed."""

    def __init__(self, max_traces: int = DEFAULT_TRACE_CAPACITY,
                 max_spans: int = DEFAULT_TRACE_SPANS):
        from collections import OrderedDict

        self._max_traces = max(1, int(max_traces))
        self._max_spans = max(1, int(max_spans))
        self._traces: "OrderedDict[str, dict]" = OrderedDict()
        self._lock = threading.Lock()

    @staticmethod
    def span_from_event(row: dict, origin: str) -> Optional[dict]:
        """Normalize one event row into a span record (None if the row
        carries no trace lineage)."""
        data = row.get("data") or {}
        tid = data.get("trace_id")
        ts = row.get("ts")
        if not tid or ts is None:
            return None
        dur = row.get("span_dur") or 0.0
        attrs = {k: v for k, v in data.items() if k not in _SPAN_KEYS}
        span = {
            "name": row.get("message", ""),
            "trace_id": tid,
            "span_id": data.get("span_id", ""),
            "parent_span_id": data.get("parent_span_id", ""),
            "phase": data.get("phase") or row.get("source", "span"),
            "source": row.get("source"),
            "origin": origin,
            "start": ts - dur,
            "end": ts,
        }
        if attrs:
            span["data"] = attrs
        return span

    def add(self, origin: str, rows: List[dict]) -> None:
        spans = []
        for r in rows:
            if isinstance(r, dict):
                span = self.span_from_event(r, origin)
                if span is not None:
                    spans.append(span)
        if not spans:
            return
        with self._lock:  # once per shipped batch, not per row
            for span in spans:
                tid = span["trace_id"]
                t = self._traces.get(tid)
                if t is None:
                    t = self._traces[tid] = {
                        "spans": deque(maxlen=self._max_spans),
                        "dropped": 0,
                        "first_ts": span["start"], "last_ts": span["end"],
                    }
                    while len(self._traces) > self._max_traces:
                        self._traces.popitem(last=False)
                else:
                    self._traces.move_to_end(tid)
                t["first_ts"] = min(t["first_ts"], span["start"])
                t["last_ts"] = max(t["last_ts"], span["end"])
                if len(t["spans"]) == self._max_spans:
                    t["dropped"] += 1  # maxlen evicts the oldest
                t["spans"].append(span)

    def get(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            t = self._traces.get(trace_id)
            if t is None:
                return None
            spans = sorted(t["spans"], key=lambda s: s["start"])
            return {"trace_id": trace_id, "spans": spans,
                    "dropped_spans": t["dropped"],
                    "first_ts": t["first_ts"], "last_ts": t["last_ts"]}

    def list(self, limit: int = 100) -> List[dict]:
        """Trace summaries, most recently updated last (the CLI shows the
        tail)."""
        with self._lock:
            items = list(self._traces.items())[-limit:]
            out = []
            for tid, t in items:
                roots = [s for s in t["spans"] if not s.get("parent_span_id")]
                root_name = roots[0]["name"] if roots else (
                    t["spans"][0]["name"] if t["spans"] else "")
                out.append({
                    "trace_id": tid, "name": root_name,
                    "num_spans": len(t["spans"]) + t["dropped"],
                    "start": t["first_ts"],
                    "duration_s": round(t["last_ts"] - t["first_ts"], 6),
                })
            return out

    def summarize(self) -> dict:
        with self._lock:
            durs = sorted(t["last_ts"] - t["first_ts"]
                          for t in self._traces.values())
            n = len(durs)
            if not n:
                return {"num_traces": 0}
            return {
                "num_traces": n,
                "num_spans": sum(len(t["spans"]) + t["dropped"]
                                 for t in self._traces.values()),
                "duration_p50_s": round(durs[n // 2], 6),
                "duration_p99_s": round(
                    durs[min(n - 1, int(n * 0.99))], 6),
                "duration_max_s": round(durs[-1], 6),
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)


class EventsPusher:
    """Background thread shipping this process's new events to the head
    (same control-connection path as ``metrics_report``) and rewriting the
    crash-dump file each cycle.  Send failures back off and retry; the
    loop only exits when stopped or the client is closed for good."""

    def __init__(self, send_fn, origin: str, interval_s: float = DEFAULT_FLUSH_S,
                 dump_path: Optional[str] = None, closed_fn=None):
        self._send = send_fn
        self._origin = origin
        self._interval = interval_s
        self._dump_path = dump_path
        self._closed = closed_fn
        self._stop = threading.Event()
        self._cursor = 0  # last seq shipped to the head
        self._dump_cursor = 0  # last seq appended to the crash dump
        # serializes flush() (exit path) against an in-flight loop cycle:
        # both read-modify-write the cursors, and an unsynchronized race
        # would ship/append the same batch twice
        self._flush_lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="events-pusher")

    def start(self) -> "EventsPusher":
        if ENABLED:
            self._thread.start()
        return self

    def _dump_new(self) -> None:
        """Append events since the dump cursor to the crash-dump file.
        Independent cursor from shipping: a head outage must not stop the
        on-disk trail (and a dump failure must not re-ship)."""
        if not self._dump_path:
            return
        rows = _BUFFER.since(self._dump_cursor)
        if rows and append_dump(self._dump_path, rows):
            self._dump_cursor = rows[-1]["seq"]

    def flush(self) -> bool:
        """Synchronous ship+dump of anything new (used at exit and by
        tests; safe to call concurrently with the loop — the flush lock
        keeps the cursors single-writer).  Returns send success."""
        with self._flush_lock:
            self._dump_new()
            return self._ship_locked()

    def _ship_locked(self) -> bool:
        rows = _BUFFER.since(self._cursor)
        if not rows:
            return True
        try:
            self._send({"type": "events_report", "origin": self._origin,
                        "events": rows})
            self._cursor = max(self._cursor, rows[-1]["seq"])
            return True
        except Exception:
            return False  # cursor kept; retried next cycle

    def _loop(self) -> None:
        # the crash dump writes at EVERY interval regardless of head
        # health — only the send backs off.  A head outage is exactly
        # when the on-disk trail matters most.
        send_backoff = 0.0
        next_send = 0.0
        while not self._stop.wait(self._interval):
            if self._closed is not None and self._closed():
                return
            with self._flush_lock:
                self._dump_new()
                if time.monotonic() < next_send:
                    continue
                ok = self._ship_locked()
            if ok:
                send_backoff = 0.0
                next_send = 0.0
            else:
                # transient head hiccup: keep the cursor, retry with
                # bounded exponential backoff instead of dying silently
                send_backoff = min(
                    30.0, max(self._interval, send_backoff * 2))
                next_send = time.monotonic() + send_backoff

    def stop(self) -> None:
        self._stop.set()
        try:
            self.flush()
        except Exception:
            pass
