"""Packed codec for the hot control-plane frames.

The protobuf Envelope arm (wire.py) is the versioned IDL, but its pure-
Python message construction costs ~50-90us/task — measured at ~19% of
no-op task throughput on a 1-core head (VERDICT Weak #3).  This module
is the same schema (field-for-field from ``ray_tpu/protocol/
ray_tpu.proto``; test_wire pins the tables against the generated
descriptors so codec and IDL cannot drift) hand-lowered to struct-packed
fixed headers + length-prefixed blobs: no per-field reflection, no
message-object allocation — just ``struct.pack_into``-grade appends and
one ``b"".join``.  That takes the typed arm's overhead to low single
digits, which is what lets ``RAY_TPU_WIRE=proto`` be the DEFAULT.

Only the frame types that dominate a task wave are packed —
submit_batch, execute, task_done, seal, add_ref, remove_ref,
metrics_report, plus the get/wait request/reply RTT path (one location
per ref: per-field protobuf construction there was the single largest
typed-arm cost of a wave).  Everything else keeps the Envelope arm
(typed, slower, rare) or the raw-pickle long tail.  Wire interop is by first-byte
sniffing, same as the other two encodings: raw pickle starts ``0x80``,
an Envelope starts with the version tag ``0x08``, a packed frame starts
with the magic ``0xB1`` — receivers accept all three at any time, so
mixed clusters and rolling flag flips just work.

Frame layout::

    0xB1 | version u8 | frame-id u8 | frame-specific payload

Size gate: any frame that would reach the 2 GiB interop cap returns
``None`` (encode() in wire.py then falls through to the Envelope arm and
its own gates, landing on raw pickle which has no cap).  u32 length
prefixes additionally hard-fail past 4 GiB via struct.error, which the
same ``None`` path absorbs — an oversize payload can never produce a
frame a peer cannot parse.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Dict, Optional

from ray_tpu._private.object_store import ObjectLocation

MAGIC = 0xB1
MAGIC_BYTE = b"\xb1"
PACKED_VERSION = 1

_PICKLE_PROTO = pickle.HIGHEST_PROTOCOL
# same interop cap as wire._PB_MAX_FRAME (tests monkeypatch this one)
_MAX_FRAME = (1 << 31) - (1 << 20)

_U8 = struct.Struct("<B")
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")

_pu8 = _U8.pack
_pu16 = _U16.pack
_pu32 = _U32.pack
_pi64 = _I64.pack
_pf64 = _F64.pack


class _TooBig(ValueError):
    """A blob at/past the interop cap: take the fallback arm."""


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------

def _ab(parts, b) -> None:  # bytes, u32 length prefix
    if len(b) >= _MAX_FRAME:
        raise _TooBig
    parts.append(_pu32(len(b)))
    parts.append(bytes(b))


def _as(parts, s: str) -> None:  # str
    b = s.encode("utf-8")
    parts.append(_pu32(len(b)))
    parts.append(b)


def _albytes(parts, items) -> None:  # list of bytes
    parts.append(_pu32(len(items)))
    for b in items:
        parts.append(_pu32(len(b)))
        parts.append(bytes(b))


def _gb(data, off):  # -> (bytes, off)
    (n,) = _U32.unpack_from(data, off)
    off += 4
    return data[off:off + n], off + n


def _gs(data, off):  # -> (str, off)
    (n,) = _U32.unpack_from(data, off)
    off += 4
    return str(data[off:off + n], "utf-8"), off + n


def _glbytes(data, off):  # -> (list[bytes], off)
    (n,) = _U32.unpack_from(data, off)
    off += 4
    out = []
    u32 = _U32.unpack_from
    for _ in range(n):
        (m,) = u32(data, off)
        off += 4
        out.append(data[off:off + m])
        off += m
    return out, off


# ---------------------------------------------------------------------------
# ObjectLocation <-> packed (presence bitmask + values in bit order)
# ---------------------------------------------------------------------------

_L_INLINE, _L_SHM, _L_SPILL, _L_SIZE, _L_ERR, _L_NODE, _L_FETCH, \
    _L_APATH, _L_AOFF, _L_AKEY = (1 << i for i in range(10))


def _pack_loc(parts, loc: ObjectLocation) -> None:
    # None is NOT accepted: the TypeError falls back to the pickle arm,
    # which preserves None exactly (a dep can unseal between scheduling
    # and dispatch) — the same contract as the Envelope arm's _loc_to_pb.
    # Straight-line, helpers inlined: locations ride in every seal /
    # execute / task_done frame.
    ap = parts.append
    pu32 = _pu32
    flag_slot = len(parts)
    ap(b"")
    flags = 0
    v = loc.inline
    if v is not None:
        if len(v) >= _MAX_FRAME:
            raise _TooBig
        flags = _L_INLINE
        ap(pu32(len(v)))
        ap(bytes(v))
    v = loc.shm_name
    if v is not None:
        flags |= _L_SHM
        b = v.encode("utf-8")
        ap(pu32(len(b)))
        ap(b)
    v = loc.spilled_path
    if v is not None:
        flags |= _L_SPILL
        b = v.encode("utf-8")
        ap(pu32(len(b)))
        ap(b)
    if loc.size:
        flags |= _L_SIZE
        ap(_pi64(loc.size))
    if loc.is_error:
        flags |= _L_ERR
    v = loc.node_id
    if v:
        flags |= _L_NODE
        b = v.encode("utf-8")
        ap(pu32(len(b)))
        ap(b)
    v = loc.fetch_addr
    if v is not None:
        flags |= _L_FETCH
        b = str(v[0]).encode("utf-8")
        ap(pu32(len(b)))
        ap(b)
        ap(pu32(int(v[1])))
    v = loc.arena_path
    if v is not None:
        flags |= _L_APATH
        b = v.encode("utf-8")
        ap(pu32(len(b)))
        ap(b)
    if loc.arena_off:
        flags |= _L_AOFF
        ap(_pi64(loc.arena_off))
    v = loc.arena_key
    if v is not None:
        flags |= _L_AKEY
        ap(pu32(len(v)))
        ap(v)
    parts[flag_slot] = _pu16(flags)


def _unpack_loc(data, off):
    (flags,) = _U16.unpack_from(data, off)
    off += 2
    u32 = _U32.unpack_from
    inline = shm = spill = apath = akey = fetch = None
    size = aoff = 0
    node = ""
    if flags & _L_INLINE:
        (n,) = u32(data, off)
        off += 4
        inline = data[off:off + n]
        off += n
    if flags & _L_SHM:
        (n,) = u32(data, off)
        off += 4
        shm = str(data[off:off + n], "utf-8")
        off += n
    if flags & _L_SPILL:
        (n,) = u32(data, off)
        off += 4
        spill = str(data[off:off + n], "utf-8")
        off += n
    if flags & _L_SIZE:
        (size,) = _I64.unpack_from(data, off)
        off += 8
    if flags & _L_NODE:
        (n,) = u32(data, off)
        off += 4
        node = str(data[off:off + n], "utf-8")
        off += n
    if flags & _L_FETCH:
        (n,) = u32(data, off)
        off += 4
        host = str(data[off:off + n], "utf-8")
        off += n
        (port,) = u32(data, off)
        off += 4
        fetch = (host, port)
    if flags & _L_APATH:
        (n,) = u32(data, off)
        off += 4
        apath = str(data[off:off + n], "utf-8")
        off += n
    if flags & _L_AOFF:
        (aoff,) = _I64.unpack_from(data, off)
        off += 8
    if flags & _L_AKEY:
        (n,) = u32(data, off)
        off += 4
        akey = data[off:off + n]
        off += n
    return ObjectLocation(
        inline=inline, shm_name=shm, spilled_path=spill, size=size,
        is_error=bool(flags & _L_ERR), node_id=node, fetch_addr=fetch,
        arena_path=apath, arena_off=aoff, arena_key=akey,
    ), off


# ---------------------------------------------------------------------------
# TaskSpec <-> packed (presence-mask u32; bit = .proto field number - 1)
# ---------------------------------------------------------------------------
# The codec is STRAIGHT-LINE on both sides — no per-field dispatch, no
# message objects; one u32 presence mask, then values in field order.
# kinds: b bytes, s str, i int(i64), f bool-flag, L bytes-list,
#        P pickled, R resources map
_SPEC_FIELDS = {
    "task_id": (1, "b"), "name": (2, "s"), "fn_id": (3, "b"),
    "args_blob": (4, "b"), "args_oid": (5, "b"), "dep_ids": (6, "L"),
    "pinned_refs": (7, "L"), "owned_oids": (8, "L"), "return_ids": (9, "L"),
    "num_returns": (10, "i"), "resources": (11, "R"),
    "scheduling_strategy": (12, "P"), "retries_left": (13, "i"),
    "actor_id": (14, "b"), "method_name": (15, "s"),
    "is_actor_creation": (16, "f"), "max_restarts": (17, "i"),
    "max_task_retries": (18, "i"), "actor_name": (19, "s"),
    "runtime_env": (20, "P"), "max_concurrency": (21, "i"),
    "release_cpu_after_start": (22, "f"), "parent_task_id": (23, "b"),
}
_EXTRA_FIELD = 24  # pickled dict of spec keys not covered above
_EXTRA_BIT = 1 << (_EXTRA_FIELD - 1)
_SPEC_KEYSET = frozenset(_SPEC_FIELDS)


def _pack_spec(parts, spec: Dict[str, Any]) -> None:
    # Mirrors wire._spec_to_pb's normalization: absent/None scalars and
    # proto3-zero values are dropped, pickled fields keep None exactly,
    # unknown keys ride one pickled "extra" blob.  Field access is
    # explicit (one dict.get per field): measured ~4x faster than
    # iterate-and-dispatch for a 17-field spec.
    ap = parts.append
    pu32 = _pu32
    mask_slot = len(parts)
    ap(b"")  # presence-mask placeholder, patched at the end
    mask = 0
    get = spec.get
    v = get("task_id")
    if v is not None:
        mask |= 1
        ap(pu32(len(v)))
        ap(v)
    v = get("name")
    if v is not None:
        mask |= 2
        b = v.encode("utf-8")
        ap(pu32(len(b)))
        ap(b)
    v = get("fn_id")
    if v is not None:
        mask |= 4
        ap(pu32(len(v)))
        ap(v)
    v = get("args_blob")
    if v is not None:
        if len(v) >= _MAX_FRAME:
            raise _TooBig
        mask |= 8
        ap(pu32(len(v)))
        ap(v)
    v = get("args_oid")
    if v is not None:
        mask |= 16
        ap(pu32(len(v)))
        ap(v)
    v = get("dep_ids")
    if v:
        mask |= 32
        ap(pu32(len(v)))
        for b in v:
            ap(pu32(len(b)))
            ap(b)
    v = get("pinned_refs")
    if v:
        mask |= 64
        ap(pu32(len(v)))
        for b in v:
            ap(pu32(len(b)))
            ap(b)
    v = get("owned_oids")
    if v:
        mask |= 128
        ap(pu32(len(v)))
        for b in v:
            ap(pu32(len(b)))
            ap(b)
    v = get("return_ids")
    if v:
        mask |= 256
        ap(pu32(len(v)))
        for b in v:
            ap(pu32(len(b)))
            ap(b)
    v = get("num_returns")
    if v:
        mask |= 512
        ap(_pi64(v))
    v = get("resources")
    if v:
        mask |= 1024
        ap(pu32(len(v)))
        for rk, rv in v.items():
            b = rk.encode("utf-8")
            ap(pu32(len(b)))
            ap(b)
            # validate_options doesn't type-check custom resource
            # amounts; coerce so e.g. {"accel": "1"} stays schedulable
            ap(_pf64(float(rv)))
    if "scheduling_strategy" in spec:
        mask |= 2048
        b = pickle.dumps(spec["scheduling_strategy"], _PICKLE_PROTO)
        ap(pu32(len(b)))
        ap(b)
    v = get("retries_left")
    if v:
        mask |= 4096
        ap(_pi64(v))
    v = get("actor_id")
    if v is not None:
        mask |= 8192
        ap(pu32(len(v)))
        ap(v)
    v = get("method_name")
    if v is not None:
        mask |= 16384
        b = v.encode("utf-8")
        ap(pu32(len(b)))
        ap(b)
    if get("is_actor_creation"):
        mask |= 32768
    v = get("max_restarts")
    if v:
        mask |= 65536
        ap(_pi64(v))
    v = get("max_task_retries")
    if v:
        mask |= 131072
        ap(_pi64(v))
    v = get("actor_name")
    if v is not None:
        mask |= 262144
        b = v.encode("utf-8")
        ap(pu32(len(b)))
        ap(b)
    if "runtime_env" in spec:
        mask |= 524288
        b = pickle.dumps(spec["runtime_env"], _PICKLE_PROTO)
        ap(pu32(len(b)))
        ap(b)
    v = get("max_concurrency")
    if v:
        mask |= 1048576
        ap(_pi64(v))
    if get("release_cpu_after_start"):
        mask |= 2097152
    v = get("parent_task_id")
    if v is not None:
        mask |= 4194304
        ap(pu32(len(v)))
        ap(v)
    # unknown long tail -> one pickled blob (forward compat: trace_ctx,
    # dynamic_returns, concurrency_group, ...)
    if not (spec.keys() <= _SPEC_KEYSET):
        extra = {k: spec[k] for k in spec if k not in _SPEC_KEYSET}
        mask |= _EXTRA_BIT
        b = pickle.dumps(extra, _PICKLE_PROTO)
        ap(pu32(len(b)))
        ap(b)
    parts[mask_slot] = pu32(mask)


def _unpack_spec(mv, off):
    (mask,) = _U32.unpack_from(mv, off)
    off += 4
    spec: Dict[str, Any] = {}
    u32 = _U32.unpack_from
    i64 = _I64.unpack_from
    if mask & 1:
        (n,) = u32(mv, off)
        off += 4
        spec["task_id"] = mv[off:off + n]
        off += n
    if mask & 2:
        (n,) = u32(mv, off)
        off += 4
        spec["name"] = str(mv[off:off + n], "utf-8")
        off += n
    if mask & 4:
        (n,) = u32(mv, off)
        off += 4
        spec["fn_id"] = mv[off:off + n]
        off += n
    if mask & 8:
        (n,) = u32(mv, off)
        off += 4
        spec["args_blob"] = mv[off:off + n]
        off += n
    if mask & 16:
        (n,) = u32(mv, off)
        off += 4
        spec["args_oid"] = mv[off:off + n]
        off += n
    for bit, key in ((32, "dep_ids"), (64, "pinned_refs"),
                     (128, "owned_oids"), (256, "return_ids")):
        if mask & bit:
            (cnt,) = u32(mv, off)
            off += 4
            items = []
            for _ in range(cnt):
                (n,) = u32(mv, off)
                off += 4
                items.append(mv[off:off + n])
                off += n
            spec[key] = items
    if mask & 512:
        (spec["num_returns"],) = i64(mv, off)
        off += 8
    if mask & 1024:
        (cnt,) = u32(mv, off)
        off += 4
        res = {}
        for _ in range(cnt):
            (n,) = u32(mv, off)
            off += 4
            rk = str(mv[off:off + n], "utf-8")
            off += n
            (res[rk],) = _F64.unpack_from(mv, off)
            off += 8
        spec["resources"] = res
    if mask & 2048:
        (n,) = u32(mv, off)
        off += 4
        spec["scheduling_strategy"] = pickle.loads(mv[off:off + n])
        off += n
    if mask & 4096:
        (spec["retries_left"],) = i64(mv, off)
        off += 8
    if mask & 8192:
        (n,) = u32(mv, off)
        off += 4
        spec["actor_id"] = mv[off:off + n]
        off += n
    if mask & 16384:
        (n,) = u32(mv, off)
        off += 4
        spec["method_name"] = str(mv[off:off + n], "utf-8")
        off += n
    if mask & 32768:
        spec["is_actor_creation"] = True
    if mask & 65536:
        (spec["max_restarts"],) = i64(mv, off)
        off += 8
    if mask & 131072:
        (spec["max_task_retries"],) = i64(mv, off)
        off += 8
    if mask & 262144:
        (n,) = u32(mv, off)
        off += 4
        spec["actor_name"] = str(mv[off:off + n], "utf-8")
        off += n
    if mask & 524288:
        (n,) = u32(mv, off)
        off += 4
        spec["runtime_env"] = pickle.loads(mv[off:off + n])
        off += n
    if mask & 1048576:
        (spec["max_concurrency"],) = i64(mv, off)
        off += 8
    if mask & 2097152:
        spec["release_cpu_after_start"] = True
    if mask & 4194304:
        (n,) = u32(mv, off)
        off += 4
        spec["parent_task_id"] = mv[off:off + n]
        off += n
    if mask & _EXTRA_BIT:
        (n,) = u32(mv, off)
        off += 4
        spec.update(pickle.loads(mv[off:off + n]))
        off += n
    # the four always-present keys (stripped-dict form invariant)
    spec.setdefault("task_id", b"")
    spec.setdefault("name", "")
    spec.setdefault("return_ids", [])
    spec.setdefault("num_returns", 0)
    return spec, off


def _pack_seal_entry(parts, oid, loc, contained) -> None:
    _ab(parts, oid)
    _pack_loc(parts, loc)
    _albytes(parts, list(contained or ()))


def _unpack_seal_entry(mv, off):
    oid, off = _gb(mv, off)
    loc, off = _unpack_loc(mv, off)
    contained, off = _glbytes(mv, off)
    return oid, loc, contained, off


# ---------------------------------------------------------------------------
# frame packers: msg dict -> parts (raise to fall back)
# ---------------------------------------------------------------------------

def _pack_submit_batch(parts, msg) -> None:
    batch = msg["batch"]
    if len(msg) != 2:
        raise ValueError("extra keys")
    parts.append(_pu32(len(batch)))
    for kind, spec in batch:
        _as(parts, kind)
        _pack_spec(parts, spec)


def _unpack_submit_batch(mv, off):
    (n,) = _U32.unpack_from(mv, off)
    off += 4
    batch = []
    for _ in range(n):
        kind, off = _gs(mv, off)
        spec, off = _unpack_spec(mv, off)
        batch.append((kind, spec))
    return {"type": "submit_batch", "batch": batch}


_EXECUTE_KEYS = frozenset(("type", "spec", "dep_locs", "tpu_ids"))


def _pack_execute(parts, msg) -> None:
    if not (msg.keys() <= _EXECUTE_KEYS):
        raise ValueError("extra keys")
    _pack_spec(parts, msg["spec"])
    dep_locs = msg.get("dep_locs") or {}
    parts.append(_pu32(len(dep_locs)))
    for oid, loc in dep_locs.items():
        _ab(parts, oid)
        _pack_loc(parts, loc)  # None dep -> TypeError -> pickle arm
    tpu_ids = msg.get("tpu_ids") or ()
    parts.append(_pu32(len(tpu_ids)))
    for t in tpu_ids:
        parts.append(_pi64(t))


def _unpack_execute(mv, off):
    spec, off = _unpack_spec(mv, off)
    out: Dict[str, Any] = {"type": "execute", "spec": spec}
    (n,) = _U32.unpack_from(mv, off)
    off += 4
    if n:
        dep_locs = {}
        for _ in range(n):
            oid, off = _gb(mv, off)
            dep_locs[oid], off = _unpack_loc(mv, off)
        out["dep_locs"] = dep_locs
    (n,) = _U32.unpack_from(mv, off)
    off += 4
    if n:
        tpus = []
        for _ in range(n):
            (t,) = _I64.unpack_from(mv, off)
            off += 8
            tpus.append(t)
        out["tpu_ids"] = tpus
    return out


_TD_CREATION, _TD_ACTOR, _TD_NAME, _TD_FAILED, _TD_ERRSTR, _TD_EXTRA = (
    1, 2, 4, 8, 16, 32)
_TASK_DONE_KEYS = frozenset((
    "type", "seals", "spec_ref", "failed", "error_str", "exec_start",
    "exec_end", "worker_pid",
))
_TASK_DONE_REF_KEYS = frozenset((
    "task_id", "return_ids", "is_actor_creation", "actor_id", "name",
))


def _pack_task_done(parts, msg) -> None:
    parts.append(b"")  # seal-count placeholder patched below
    slot = len(parts) - 1
    n = 0
    for oid, loc, contained in msg.get("seals", ()):
        _pack_seal_entry(parts, oid, loc, contained)
        n += 1
    parts[slot] = _pu32(n)
    ref = msg["spec_ref"]
    if not (ref.keys() <= _TASK_DONE_REF_KEYS):
        raise ValueError("extra spec_ref keys")  # -> pickle arm
    _ab(parts, ref["task_id"])
    _albytes(parts, ref.get("return_ids", ()))
    if msg.keys() <= _TASK_DONE_KEYS:  # the common shape: no long tail
        rest = None
    else:
        rest = {k: v for k, v in msg.items() if k not in _TASK_DONE_KEYS}
    flags = 0
    if ref.get("is_actor_creation"):
        flags |= _TD_CREATION
    if ref.get("actor_id") is not None:
        flags |= _TD_ACTOR
    if ref.get("name") is not None:
        flags |= _TD_NAME
    if msg.get("failed"):
        flags |= _TD_FAILED
    if msg.get("error_str") is not None:
        flags |= _TD_ERRSTR
    if rest:
        flags |= _TD_EXTRA
    parts.append(_pu8(flags))
    if flags & _TD_ACTOR:
        _ab(parts, ref["actor_id"])
    if flags & _TD_NAME:
        _as(parts, ref["name"])
    if flags & _TD_ERRSTR:
        _as(parts, msg["error_str"])
    parts.append(_pf64(msg.get("exec_start", 0.0)))
    parts.append(_pf64(msg.get("exec_end", 0.0)))
    parts.append(_pi64(msg.get("worker_pid", 0)))
    if flags & _TD_EXTRA:
        _ab(parts, pickle.dumps(rest, _PICKLE_PROTO))


def _unpack_task_done(mv, off):
    (n,) = _U32.unpack_from(mv, off)
    off += 4
    seals = []
    for _ in range(n):
        oid, loc, contained, off = _unpack_seal_entry(mv, off)
        seals.append((oid, loc, contained))
    task_id, off = _gb(mv, off)
    return_ids, off = _glbytes(mv, off)
    (flags,) = _U8.unpack_from(mv, off)
    off += 1
    actor_id = name = error_str = None
    if flags & _TD_ACTOR:
        actor_id, off = _gb(mv, off)
    if flags & _TD_NAME:
        name, off = _gs(mv, off)
    if flags & _TD_ERRSTR:
        error_str, off = _gs(mv, off)
    (exec_start,) = _F64.unpack_from(mv, off)
    off += 8
    (exec_end,) = _F64.unpack_from(mv, off)
    off += 8
    (worker_pid,) = _I64.unpack_from(mv, off)
    off += 8
    out = {
        "type": "task_done",
        "seals": seals,
        "spec_ref": {
            "task_id": task_id,
            "return_ids": return_ids,
            "is_actor_creation": bool(flags & _TD_CREATION) or None,
            "actor_id": actor_id,
            "name": name,
        },
        "failed": bool(flags & _TD_FAILED),
        "error_str": error_str,
        "exec_start": exec_start,
        "exec_end": exec_end,
        "worker_pid": worker_pid,
    }
    if flags & _TD_EXTRA:
        blob, off = _gb(mv, off)
        out.update(pickle.loads(blob))
    return out


_SEAL_KEYS = frozenset(("type", "oid", "loc", "contained"))


def _pack_seal(parts, msg) -> None:
    if not (msg.keys() <= _SEAL_KEYS):
        raise ValueError("extra keys")
    _pack_seal_entry(parts, msg["oid"], msg["loc"], msg.get("contained", ()))


def _unpack_seal(mv, off):
    oid, loc, contained, off = _unpack_seal_entry(mv, off)
    return {"type": "seal", "oid": oid, "loc": loc, "contained": contained}


_REF_KEYS = frozenset(("type", "oids", "reason"))


def _pack_ref(parts, msg) -> None:
    # carries the pin reason (the Envelope RefUpdate arm predates it and
    # falls back to pickle for non-handle reasons)
    if not (msg.keys() <= _REF_KEYS):
        raise ValueError("extra keys")
    ap = parts.append
    pu32 = _pu32
    b = msg.get("reason", "handle").encode("utf-8")
    ap(pu32(len(b)))
    ap(b)
    oids = msg["oids"]
    ap(pu32(len(oids)))
    for o in oids:
        ap(pu32(len(o)))
        ap(o)


def _unpack_add_ref(mv, off):
    reason, off = _gs(mv, off)
    oids, off = _glbytes(mv, off)
    return {"type": "add_ref", "oids": oids, "reason": reason}


def _unpack_remove_ref(mv, off):
    reason, off = _gs(mv, off)
    oids, off = _glbytes(mv, off)
    return {"type": "remove_ref", "oids": oids, "reason": reason}


_GETLOC_KEYS = frozenset(("type", "oids", "timeout", "req_id"))
_WAIT_KEYS = frozenset(("type", "oids", "num_returns", "timeout", "req_id"))


def _pack_get_locations(parts, msg) -> None:
    if not (msg.keys() <= _GETLOC_KEYS):
        raise ValueError("extra keys")
    ap = parts.append
    pu32 = _pu32
    oids = msg["oids"]
    ap(pu32(len(oids)))
    for o in oids:
        ap(pu32(len(o)))
        ap(o)
    t = msg.get("timeout")
    if t is None:
        ap(b"\x00")
    else:
        ap(b"\x01")
        ap(_pf64(t))
    ap(_pi64(msg["req_id"]))


def _unpack_get_locations(data, off):
    oids, off = _glbytes(data, off)
    has_t = data[off]
    off += 1
    timeout = None
    if has_t:
        (timeout,) = _F64.unpack_from(data, off)
        off += 8
    (req_id,) = _I64.unpack_from(data, off)
    return {"type": "get_locations", "oids": oids, "timeout": timeout,
            "req_id": req_id}


def _pack_wait(parts, msg) -> None:
    if not (msg.keys() <= _WAIT_KEYS):
        raise ValueError("extra keys")
    ap = parts.append
    pu32 = _pu32
    oids = msg["oids"]
    ap(pu32(len(oids)))
    for o in oids:
        ap(pu32(len(o)))
        ap(o)
    ap(_pi64(msg["num_returns"]))
    t = msg.get("timeout")
    if t is None:
        ap(b"\x00")
    else:
        ap(b"\x01")
        ap(_pf64(t))
    ap(_pi64(msg["req_id"]))


def _unpack_wait(data, off):
    oids, off = _glbytes(data, off)
    (num_returns,) = _I64.unpack_from(data, off)
    off += 8
    has_t = data[off]
    off += 1
    timeout = None
    if has_t:
        (timeout,) = _F64.unpack_from(data, off)
        off += 8
    (req_id,) = _I64.unpack_from(data, off)
    return {"type": "wait", "oids": oids, "num_returns": num_returns,
            "timeout": timeout, "req_id": req_id}


# reply shapes (the ray.get/ray.wait RTT path — one location per ref, so
# per-field protobuf construction here was the dominant typed-arm cost
# of a task wave); only the three get/wait shapes are typed, like the
# Envelope arm — anything else falls back to pickle
_REPLY_GET = frozenset(("type", "req_id", "locations"))
_REPLY_TIMEOUT = frozenset(("type", "req_id", "timeout"))
_REPLY_WAIT = frozenset(("type", "req_id", "ready", "locations"))
_RP_TIMEOUT, _RP_WAIT = 1, 2


def _pack_reply(parts, msg) -> None:
    keys = msg.keys()
    ap = parts.append
    if keys == _REPLY_TIMEOUT and msg["timeout"] is True:
        ap(_pu8(_RP_TIMEOUT))
        ap(_pi64(msg["req_id"]))
        return
    if keys == _REPLY_GET:
        ap(_pu8(0))
    elif keys == _REPLY_WAIT:
        ap(_pu8(_RP_WAIT))
    else:
        raise ValueError("untyped reply shape")  # -> pickle arm
    ap(_pi64(msg["req_id"]))
    locs = msg["locations"]
    ap(_pu32(len(locs)))
    pu32 = _pu32
    for oid, loc in locs.items():
        ap(pu32(len(oid)))
        ap(oid)
        _pack_loc(parts, loc)  # None -> TypeError -> pickle (exactness)
    if keys == _REPLY_WAIT:
        ready = msg["ready"]
        ap(pu32(len(ready)))
        for o in ready:
            ap(pu32(len(o)))
            ap(o)


def _unpack_reply(data, off):
    flags = data[off]
    off += 1
    (req_id,) = _I64.unpack_from(data, off)
    off += 8
    if flags & _RP_TIMEOUT:
        return {"type": "reply", "req_id": req_id, "timeout": True}
    (n,) = _U32.unpack_from(data, off)
    off += 4
    locations = {}
    u32 = _U32.unpack_from
    for _ in range(n):
        (m,) = u32(data, off)
        off += 4
        oid = data[off:off + m]
        off += m
        locations[oid], off = _unpack_loc(data, off)
    out = {"type": "reply", "req_id": req_id, "locations": locations}
    if flags & _RP_WAIT:
        out["ready"], off = _glbytes(data, off)
    return out


def _pack_metrics_report(parts, msg) -> None:
    # header typed, metrics payload opaque (a deeply dynamic snapshot
    # dict — same role as the IDL's bytes fields for language-serialized
    # payloads); the win over the Envelope arm is skipping the message
    # build entirely on the every-2s per-process push path
    if msg.keys() != {"type", "origin", "metrics"}:
        raise ValueError("extra keys")
    _as(parts, msg["origin"])
    _ab(parts, pickle.dumps(msg["metrics"], _PICKLE_PROTO))


def _unpack_metrics_report(mv, off):
    origin, off = _gs(mv, off)
    blob, off = _gb(mv, off)
    return {"type": "metrics_report", "origin": origin,
            "metrics": pickle.loads(blob)}


# ---------------------------------------------------------------------------
# dispatch tables — raylint R1 checks these three stay in lockstep
# ---------------------------------------------------------------------------

_FRAME_IDS = {
    "submit_batch": 1,
    "execute": 2,
    "task_done": 3,
    "seal": 4,
    "add_ref": 5,
    "remove_ref": 6,
    "metrics_report": 7,
    "get_locations": 8,
    "wait": 9,
    "reply": 10,
}

_PACK = {
    "submit_batch": _pack_submit_batch,
    "execute": _pack_execute,
    "task_done": _pack_task_done,
    "seal": _pack_seal,
    "add_ref": _pack_ref,
    "remove_ref": _pack_ref,
    "metrics_report": _pack_metrics_report,
    "get_locations": _pack_get_locations,
    "wait": _pack_wait,
    "reply": _pack_reply,
}

_UNPACK = {
    "submit_batch": _unpack_submit_batch,
    "execute": _unpack_execute,
    "task_done": _unpack_task_done,
    "seal": _unpack_seal,
    "add_ref": _unpack_add_ref,
    "remove_ref": _unpack_remove_ref,
    "metrics_report": _unpack_metrics_report,
    "get_locations": _unpack_get_locations,
    "wait": _unpack_wait,
    "reply": _unpack_reply,
}

_BY_ID = {fid: _UNPACK[name] for name, fid in _FRAME_IDS.items()}


def encode(msg: Dict[str, Any]) -> Optional[bytes]:
    """Packed frame for a hot message, or None (caller falls back to the
    Envelope arm).  Never raises: any unexpected shape, oversize blob, or
    u32 overflow lands on None — the fallback arms are always valid."""
    packer = _PACK.get(msg.get("type"))
    if packer is None:
        return None
    parts = [MAGIC_BYTE, _pu8(PACKED_VERSION), _pu8(_FRAME_IDS[msg["type"]])]
    try:
        packer(parts, msg)
        out = b"".join(parts)
    except (KeyError, TypeError, ValueError, struct.error, OverflowError,
            AttributeError):
        return None
    if len(out) >= _MAX_FRAME:
        # the whole-frame gate (many small blobs can add up past the cap
        # even when no single one trips _ab's per-blob gate)
        return None
    return out


def decode(data: bytes) -> Dict[str, Any]:
    """Decode a packed frame (caller checked the magic byte)."""
    version = data[1]
    if version != PACKED_VERSION:
        raise ValueError(f"packed wire version {version} != {PACKED_VERSION}")
    unpacker = _BY_ID.get(data[2])
    if unpacker is None:
        raise ValueError(f"unknown packed frame id {data[2]}")
    return unpacker(data, 3)
