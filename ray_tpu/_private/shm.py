"""mmap'd /dev/shm segments — the plasma arena analog.

The reference's plasma store serves objects out of a dlmalloc arena built on
mmap'd /dev/shm (``src/ray/object_manager/plasma/plasma_allocator.h:41``,
fd-passing in ``fling.cc``).  On Linux a named file in /dev/shm *is* POSIX
shared memory, so we get the same zero-copy cross-process mapping with plain
``open`` + ``mmap`` and none of multiprocessing.SharedMemory's
resource-tracker lifetime hazards.  One segment per object (the reference
allocates objects inside one arena; per-object segments are simpler and the
kernel dedups the page-cache either way).
"""

from __future__ import annotations

import mmap
import os

SHM_DIR = "/dev/shm"
# Per-node override: a cluster node agent points its workers at a private
# tmpfs subdirectory so that two nodes sharing one test host have honestly
# disjoint object namespaces (a remote segment is only reachable through
# the object-transfer plane, never by accidental same-host attach).
_SHM_DIR_ENV = "RAY_TPU_SHM_DIR"


def shm_dir() -> str:
    return os.environ.get(_SHM_DIR_ENV, SHM_DIR)


class ShmSegment:
    """A named shared-memory segment holding one sealed object."""

    def __init__(self, name: str, size: int, create: bool):
        self.name = name
        self.size = size
        path = os.path.join(shm_dir(), name)
        if create:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
            try:
                os.ftruncate(fd, size)
                self._mm = mmap.mmap(fd, size)
            finally:
                os.close(fd)
        else:
            fd = os.open(path, os.O_RDWR)
            try:
                if size <= 0:
                    size = os.fstat(fd).st_size
                    self.size = size
                self._mm = mmap.mmap(fd, size)
            finally:
                os.close(fd)

    @staticmethod
    def path_for(name: str) -> str:
        return os.path.join(shm_dir(), name)

    @classmethod
    def create(cls, name: str, size: int) -> "ShmSegment":
        return cls(name, size, create=True)

    @classmethod
    def attach(cls, name: str, size: int = -1) -> "ShmSegment":
        return cls(name, size, create=False)

    @property
    def buf(self) -> memoryview:
        return memoryview(self._mm)

    def close(self) -> None:
        try:
            self._mm.close()
        except (BufferError, ValueError):
            # Exported zero-copy views still alive; mapping will be dropped
            # at process exit (matches plasma clients holding mmaps open).
            pass

    @staticmethod
    def unlink(name: str) -> None:
        try:
            os.unlink(os.path.join(shm_dir(), name))
        except FileNotFoundError:
            pass

    @staticmethod
    def exists(name: str) -> bool:
        return os.path.exists(os.path.join(shm_dir(), name))


# ---------------------------------------------------------------------------
# Session-scoped naming + orphan sweeping
# ---------------------------------------------------------------------------
# Segment names are "{prefix}-{session}-{oid}".  Every session also writes a
# liveness marker "{prefix}-{session}-alive" containing the head PID, so the
# next init() can reclaim segments a SIGKILL'd head left behind without
# touching a concurrently-running session's objects.

_SESSION_ENV = "RAY_TPU_SESSION"


def current_session_id() -> str:
    return os.environ.get(_SESSION_ENV, "nosession")


def session_shm_name(oid_hex: str) -> str:
    from ray_tpu._private.config import get_config

    return f"{get_config().shm_prefix}-{current_session_id()}-{oid_hex}"


def write_session_marker(session_id: str, pid: int) -> None:
    from ray_tpu._private.config import get_config

    path = os.path.join(shm_dir(), f"{get_config().shm_prefix}-{session_id}-alive")
    with open(path, "w") as f:
        f.write(str(pid))


def remove_session_marker(session_id: str) -> None:
    from ray_tpu._private.config import get_config

    try:
        os.unlink(os.path.join(shm_dir(), f"{get_config().shm_prefix}-{session_id}-alive"))
    except OSError:
        pass


def sweep_orphaned_segments() -> int:
    """Unlink segments belonging to sessions whose head process is dead
    (no marker, or marker PID not alive).  Returns how many were removed.
    Called at head start — the plasma-store restart cleanup the reference
    gets from deleting its whole arena file."""
    from ray_tpu._private.config import get_config

    prefix = get_config().shm_prefix
    try:
        names = os.listdir(shm_dir())
    except OSError:
        return 0
    sessions: dict = {}
    for n in names:
        if not n.startswith(prefix + "-"):
            continue
        rest = n[len(prefix) + 1:]
        sid = rest.split("-", 1)[0]
        sessions.setdefault(sid, []).append(n)
    removed = 0
    for sid, segs in sessions.items():
        marker = f"{prefix}-{sid}-alive"
        alive = False
        try:
            with open(os.path.join(shm_dir(), marker)) as f:
                pid = int(f.read().strip() or "0")
            os.kill(pid, 0)  # raises if dead
            # a ZOMBIE still answers kill(pid, 0) but owns nothing — in
            # containers whose pid 1 never reaps orphans, a SIGKILL'd
            # head would otherwise pin its segments forever
            with open(f"/proc/{pid}/stat") as f:
                alive = f.read().rsplit(")", 1)[-1].split()[0] != "Z"
        except (OSError, ValueError, IndexError):
            alive = False
        if alive:
            continue
        for n in segs:
            try:
                os.unlink(os.path.join(shm_dir(), n))
                removed += 1
            except OSError:
                pass
    return removed
