"""mmap'd /dev/shm segments — the plasma arena analog.

The reference's plasma store serves objects out of a dlmalloc arena built on
mmap'd /dev/shm (``src/ray/object_manager/plasma/plasma_allocator.h:41``,
fd-passing in ``fling.cc``).  On Linux a named file in /dev/shm *is* POSIX
shared memory, so we get the same zero-copy cross-process mapping with plain
``open`` + ``mmap`` and none of multiprocessing.SharedMemory's
resource-tracker lifetime hazards.  One segment per object (the reference
allocates objects inside one arena; per-object segments are simpler and the
kernel dedups the page-cache either way).
"""

from __future__ import annotations

import mmap
import os

SHM_DIR = "/dev/shm"


class ShmSegment:
    """A named shared-memory segment holding one sealed object."""

    def __init__(self, name: str, size: int, create: bool):
        self.name = name
        self.size = size
        path = os.path.join(SHM_DIR, name)
        if create:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
            try:
                os.ftruncate(fd, size)
                self._mm = mmap.mmap(fd, size)
            finally:
                os.close(fd)
        else:
            fd = os.open(path, os.O_RDWR)
            try:
                if size <= 0:
                    size = os.fstat(fd).st_size
                    self.size = size
                self._mm = mmap.mmap(fd, size)
            finally:
                os.close(fd)

    @classmethod
    def create(cls, name: str, size: int) -> "ShmSegment":
        return cls(name, size, create=True)

    @classmethod
    def attach(cls, name: str, size: int = -1) -> "ShmSegment":
        return cls(name, size, create=False)

    @property
    def buf(self) -> memoryview:
        return memoryview(self._mm)

    def close(self) -> None:
        try:
            self._mm.close()
        except (BufferError, ValueError):
            # Exported zero-copy views still alive; mapping will be dropped
            # at process exit (matches plasma clients holding mmaps open).
            pass

    @staticmethod
    def unlink(name: str) -> None:
        try:
            os.unlink(os.path.join(SHM_DIR, name))
        except FileNotFoundError:
            pass

    @staticmethod
    def exists(name: str) -> bool:
        return os.path.exists(os.path.join(SHM_DIR, name))
