"""Named locks with an opt-in acquisition-order witness.

``make_lock("node.registry")`` is a plain ``threading.Lock`` (or RLock)
in production.  Under ``RAY_TPU_LOCKWITNESS=1`` it returns a
:class:`~ray_tpu.devtools.raylint.lockwitness.WitnessLock` proxy that
feeds the global lock-order graph, so a tier-1 test can drive a live
cluster and assert the whole run was deadlock-order-clean.  The env
check happens once at lock creation — the hot path never pays for the
feature it isn't using.
"""

from __future__ import annotations

import os
import threading

def make_lock(name: str, *, rlock: bool = False):
    """A named Lock/RLock, witness-wrapped when RAY_TPU_LOCKWITNESS=1.

    The env var is read per call so tests can enable the witness after
    import; lock CREATION is rare (never on a hot path), only the
    acquire/release fast path matters and that stays native when off.
    """
    lock = threading.RLock() if rlock else threading.Lock()
    if os.environ.get("RAY_TPU_LOCKWITNESS"):
        from ray_tpu.devtools.raylint.lockwitness import wrap_lock

        return wrap_lock(name, lock)
    return lock
