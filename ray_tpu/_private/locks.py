"""Named locks with wait/hold attribution and an opt-in order witness.

``make_lock("node.registry")`` returns a :class:`_TimedLock` — a thin
proxy whose common path is a bare delegation to the C lock (one slot
load + one branch).  Timing runs on a DUTY CYCLE: a module metronome
arms every proxy for ``_ARM_BURST_S`` out of every ``_ARM_INTERVAL_S``
(~2.4% duty), and only armed acquires pay the non-blocking probe +
``perf_counter`` pair that measures a contended wait and the hold that
caused it.  :func:`lock_stats` scales the armed-window raw aggregates
by the measured wall/armed ratio, so the rows are unbiased estimates of
the process-wide totals — the metronome's phase is uncorrelated with
lock traffic, which is what makes sampled-window totals extrapolate.

The head's dispatch path acquires these locks ~14x per task, so the
DISARMED path cost is what the 1%-of-throughput budget for the whole
profiling plane is spent on; that is why ``__enter__``/``__exit__`` are
hand-leaned (zero-arg C acquire, no ``*exc`` tuple, no nested Python
call) rather than aliases of ``acquire``/``release``.

Aggregates live in a module registry (:func:`lock_stats`) and are
published as per-lock gauges by the continuous profiler's ship tick, so
a hot lock's wait/hold ratio is a TSDB trend the doctor's
``lock_contention`` rule can read — measured wait time, not a guess,
behind "transport" and "core-bound" labels.

Modes (env read per ``make_lock`` call — lock CREATION is rare, never on
a hot path):

- default: duty-cycle contended-wait timing as above
  (``RAY_TPU_LOCKTIME=0`` turns the proxy off entirely and returns raw
  ``threading.Lock`` objects; ``RAY_TPU_LOCKTIME_BURST_S`` /
  ``RAY_TPU_LOCKTIME_INTERVAL_S`` tune the duty cycle);
- ``RAY_TPU_LOCKPROF=1``: full capture — EVERY acquire timed exactly
  (blocking ones via a perf_counter pair, no duty cycle, no scaling),
  hold timed on every release;
- ``RAY_TPU_LOCKWITNESS=1``: the raylint
  :class:`~ray_tpu.devtools.raylint.lockwitness.WitnessLock` proxy that
  feeds the global lock-order graph (tier-1 deadlock-order gate);
  witness mode replaces timing — stacking proxies would double the
  per-acquire cost in the mode tests drive hardest.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Dict, Optional

# Locks acquired many times per TASK (not per control message) sit under
# the 1%-of-throughput overhead budget for the whole profiling plane;
# the metrics-registry lock guards nanosecond-scale dict writes under
# every Counter.inc/Gauge.set and can never reveal a dispatch stall —
# timing it costs more than its signal is worth.
_UNTIMED = frozenset(("metrics.registry",))

# Duty cycle of the timing window.  50ms every 2s keeps the armed
# fraction at ~2.4% — the probe+perf_counter cost only ever applies to
# that slice, and the wall/armed scale in lock_stats() undoes the
# sampling.
_ARM_BURST_S = float(os.environ.get("RAY_TPU_LOCKTIME_BURST_S", "0.05"))
_ARM_INTERVAL_S = float(os.environ.get("RAY_TPU_LOCKTIME_INTERVAL_S", "2.0"))

# name -> aggregate timing row.  Plain dict guarded by a RAW lock (the
# stats lock itself must never be a timed lock).
_stats_lock = threading.Lock()
_stats: Dict[str, dict] = {}

# Every live default-mode proxy, so the metronome can flip their _armed
# flag without the proxies polling a clock on the hot path.
_instances: "weakref.WeakSet[_TimedLock]" = weakref.WeakSet()

_armed_total_s = 0.0           # completed armed time this epoch
_armed_since: Optional[float] = None  # perf_counter of the open armed window
_timing_t0: Optional[float] = None    # epoch start (first make_lock / reset)
_manual_armed: Optional[bool] = None  # arm_timing() pin; None = metronome
_metronome: Optional[threading.Thread] = None
_metronome_pid: Optional[int] = None


def _stat_row(name: str) -> dict:
    with _stats_lock:
        row = _stats.get(name)
        if row is None:
            row = _stats[name] = {
                "acquires": 0, "contended": 0,
                "wait_s": 0.0, "hold_s": 0.0,
                "max_wait_s": 0.0, "max_hold_s": 0.0,
            }
        return row


def _arm(on: bool) -> None:
    global _armed_since, _armed_total_s
    now = time.perf_counter()
    with _stats_lock:
        if on and _armed_since is None:
            _armed_since = now
        elif not on and _armed_since is not None:
            _armed_total_s += now - _armed_since
            _armed_since = None
        proxies = list(_instances)
    for lk in proxies:
        lk._armed = on


def arm_timing(on: Optional[bool]) -> None:
    """Pin the timing window open (``True``) or shut (``False``) — the
    metronome leaves a pinned state alone, so a test can hold timing on
    while it hammers a lock.  ``None`` disarms and hands control back to
    the metronome."""
    global _manual_armed
    _manual_armed = None if on is None else bool(on)
    _arm(bool(on) if on is not None else False)


def timing_scale() -> float:
    """wall-time / armed-time since the epoch began — the factor that
    turns armed-window raw aggregates into process-wide estimates."""
    with _stats_lock:
        armed = _armed_total_s
        if _armed_since is not None:
            armed += time.perf_counter() - _armed_since
        t0 = _timing_t0
    if t0 is None or armed <= 0.0:
        return 1.0
    return max(1.0, (time.perf_counter() - t0) / armed)


def _metronome_loop(pid: int) -> None:
    while pid == os.getpid():
        time.sleep(_ARM_INTERVAL_S)
        if _manual_armed is None:
            _arm(True)
        time.sleep(_ARM_BURST_S)
        if _manual_armed is None:
            _arm(False)


def _ensure_metronome() -> None:
    """Start (or restart after fork — forked children inherit the module
    state but not the thread) the arming metronome.  Called from
    ``make_lock``; lock creation is rare, never on a hot path."""
    global _metronome, _metronome_pid, _timing_t0
    global _armed_total_s, _armed_since
    pid = os.getpid()
    with _stats_lock:
        if (_metronome is not None and _metronome_pid == pid
                and _metronome.is_alive()):
            return
        if _timing_t0 is None or _metronome_pid != pid:
            # fresh epoch: a forked child must not inherit the parent's
            # armed-time accounting, it never observed those windows
            _timing_t0 = time.perf_counter()
            _armed_total_s = 0.0
            _armed_since = None
        _metronome_pid = pid
        _metronome = threading.Thread(
            target=_metronome_loop, args=(pid,), daemon=True,
            name="ray_tpu-lock-metronome")
        _metronome.start()


def lock_stats() -> Dict[str, dict]:
    """Aggregate wait/hold rows per named lock since process start.

    Default-mode rows are duty-cycle ESTIMATES: raw armed-window
    aggregates scaled by the measured wall/armed ratio (``max_*`` stay
    raw — an observed extreme is a fact, not a rate).  Under
    ``RAY_TPU_LOCKPROF=1`` every acquire was timed, so rows are exact
    and no scale applies."""
    scale = 1.0 if os.environ.get("RAY_TPU_LOCKPROF") else timing_scale()
    with _stats_lock:
        rows = {name: dict(row) for name, row in _stats.items()}
    if scale != 1.0:
        for row in rows.values():
            row["acquires"] = int(row["acquires"] * scale)
            row["contended"] = int(row["contended"] * scale)
            row["wait_s"] *= scale
            row["hold_s"] *= scale
    return rows


def reset_lock_stats() -> None:
    """Clear the rows AND restart the scaling epoch, so post-reset rows
    estimate post-reset traffic only (proxies created before the reset
    keep their orphaned rows — create locks after resetting)."""
    global _armed_total_s, _armed_since, _timing_t0
    with _stats_lock:
        _stats.clear()
        now = time.perf_counter()
        _timing_t0 = now
        _armed_total_s = 0.0
        if _armed_since is not None:
            _armed_since = now


def publish_lock_metrics() -> None:
    """Fold the aggregates into per-lock gauges (rides the continuous
    profiler's publish tick; workers' copies reach the head — and the
    TSDB — over the ordinary metrics_report path)."""
    rows = lock_stats()
    if not rows:
        return
    from ray_tpu.util.metrics import Gauge

    wait = Gauge("ray_tpu_lock_wait_s",
                 "cumulative measured wait on a named lock")
    hold = Gauge("ray_tpu_lock_hold_s",
                 "cumulative measured hold behind contended acquires")
    contended = Gauge("ray_tpu_lock_contended_total",
                      "contended acquires of a named lock")
    for name, row in rows.items():
        tags = {"lock": name}
        wait.set(round(row["wait_s"], 6), tags=tags)
        hold.set(round(row["hold_s"], 6), tags=tags)
        contended.set(row["contended"], tags=tags)


class _TimedLock:
    """Duty-cycled contended-wait timing proxy.  Disarmed (the ~97.6%
    common case): ``__enter__`` is one slot load, one branch, and a
    ZERO-arg call into the C acquire; ``__exit__`` takes the exc triple
    positionally (no tuple packing) and calls the bound C release
    directly.  Armed: a non-blocking probe first, and — only when the
    lock turns out contended, which is exactly when the time is worth
    measuring — a ``perf_counter`` pair around the blocking acquire."""

    __slots__ = ("_inner", "_inner_acquire", "_inner_release", "_row",
                 "_t0", "_armed", "__weakref__")

    def __init__(self, lock, name: str):
        self._inner = lock
        self._inner_acquire = lock.acquire
        self._inner_release = lock.release
        self._row = _stat_row(name)
        self._t0 = None  # hold-start of the acquire being timed
        self._armed = False
        with _stats_lock:
            _instances.add(self)

    def __enter__(self):
        if self._armed:
            return self._timed_acquire(True, -1)
        return self._inner_acquire()

    def __exit__(self, t, v, tb):
        if self._t0 is not None:
            self._finish_hold()
        self._inner_release()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._armed:
            return self._timed_acquire(blocking, timeout)
        return self._inner_acquire(blocking, timeout)

    def release(self) -> None:
        if self._t0 is not None:
            self._finish_hold()
        self._inner_release()

    def _timed_acquire(self, blocking: bool, timeout: float) -> bool:
        row = self._row
        row["acquires"] += 1
        if self._inner_acquire(False):
            return True
        if not blocking:
            return False
        t0 = time.perf_counter()
        got = self._inner_acquire(True, timeout)
        t1 = time.perf_counter()
        if not got:
            return False
        wait = t1 - t0
        row["contended"] += 1
        row["wait_s"] += wait
        if wait > row["max_wait_s"]:
            row["max_wait_s"] = wait
        if self._t0 is None:  # outermost timed acquire (RLock reentry)
            self._t0 = t1
        return True

    def _finish_hold(self) -> None:
        t0 = self._t0
        if t0 is None:
            return
        self._t0 = None
        held = time.perf_counter() - t0
        row = self._row
        row["hold_s"] += held
        if held > row["max_hold_s"]:
            row["max_hold_s"] = held

    def locked(self) -> bool:
        # parity with threading.Lock.locked (RLocks lack it; mirror that)
        if self._inner_acquire(False):
            self._inner_release()
            return False
        return True

    # --- threading.Condition protocol -----------------------------------
    # Condition(make_lock(..., rlock=True)) must see the C RLock's owner
    # tracking; its nonblocking-probe fallback reads a held REENTRANT
    # lock as "not owned" and cond.wait() then refuses to wait.  The
    # cond-wait release/reacquire pair is deliberately untimed: the gap
    # is dominated by waiting for the notify, not by lock contention.

    def _is_owned(self):
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def _release_save(self):
        if self._t0 is not None:
            self._finish_hold()
        inner = self._inner
        if hasattr(inner, "_release_save"):
            return inner._release_save()
        inner.release()
        return None

    def _acquire_restore(self, state):
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()


class _FullTimedLock(_TimedLock):
    """``RAY_TPU_LOCKPROF=1``: every acquire timed exactly — blocking
    ones via a perf_counter pair, no duty cycle, no scaling.  Costs a
    timing pair per acquire; that is the point of opting in."""

    __slots__ = ()

    def __enter__(self):
        return self._full_acquire(True, -1)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        return self._full_acquire(blocking, timeout)

    def _full_acquire(self, blocking: bool, timeout: float) -> bool:
        t0 = time.perf_counter()
        got = self._inner_acquire(blocking, timeout)
        t1 = time.perf_counter()
        row = self._row
        row["acquires"] += 1
        if not got:
            return False
        wait = t1 - t0
        row["contended"] += 1
        row["wait_s"] += wait
        if wait > row["max_wait_s"]:
            row["max_wait_s"] = wait
        if self._t0 is None:
            self._t0 = t1
        return True


def make_lock(name: str, *, rlock: bool = False):
    """A named Lock/RLock with the timing proxy of the active mode (see
    module docstring).  ``RAY_TPU_LOCKTIME=0`` restores raw native locks;
    the env checks happen per call so tests can flip modes after import.
    """
    lock = threading.RLock() if rlock else threading.Lock()
    if os.environ.get("RAY_TPU_LOCKWITNESS"):
        from ray_tpu.devtools.raylint.lockwitness import wrap_lock

        return wrap_lock(name, lock)
    if os.environ.get("RAY_TPU_LOCKTIME", "1") in ("0", "false", "no"):
        return lock
    if os.environ.get("RAY_TPU_LOCKPROF"):
        return _FullTimedLock(lock, name)
    if name in _UNTIMED:
        return lock
    _ensure_metronome()
    return _TimedLock(lock, name)
