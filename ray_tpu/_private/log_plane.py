"""Cluster log plane: capture + ship (the consume side lives in
``util/log_store.py`` and the head).

Reference analog: ``python/ray/_private/log_monitor.py`` — a per-node
loop tails worker log files and publishes batched records so the driver
and ``ray logs`` see every process's output.  This module provides both
halves a *producing* process needs:

- **capture** (:func:`redirect_process_output`): dup2 fds 1/2 into a
  size-capped rotating per-process file under ``<session>/logs/`` and
  install :class:`ContextStampingStream` wrappers so every *line* written
  through Python (``print()`` included, not just the ``ray_tpu`` logger)
  is prefixed with the writer's live context — job, task id, actor id,
  trace id — read from ``global_worker`` / ``tracing`` contextvars at
  write time.  C-level writes still land in the file (dup2), just
  unstamped.

- **ship** (:class:`LogMonitor`): tails registered files with
  rotation-safe offsets (inode change = rotated, size shrink = truncated;
  neither loses lines or re-ships old offsets), parses the stamps back
  into records, rate-limits each source to a counted ``(suppressed N
  lines)`` marker, and batch-ships over the existing control connection
  (``{"type": "log_report"}``, the ``metrics_report`` path) — or straight
  into the head's store via ``ingest_fn`` when it runs in-process.

Line-prefix protocol: ``\\x1frt1|<src>|<job>|<task>|<actor>|<trace>\\x1f``
before the text.  ``\\x1f`` (unit separator) never appears in normal
output; a line without the prefix is shipped as-is with empty context.
``src`` is one char: ``o`` stdout, ``e`` stderr, a level letter
(``D/I/W/E/C``) for logger records, ``m`` for suppression markers.

Knobs: ``RAY_TPU_LOG_ROTATE_BYTES`` (per-file cap, default 16 MiB, one
``.1`` backup), ``RAY_TPU_LOG_SHIP_S`` (tail/ship cadence, default 1s),
``RAY_TPU_LOG_RATE_LPS`` (per-source lines/s before suppression,
default 2000), ``RAY_TPU_LOG_TO_DRIVER=0`` (driver-side, stop
re-emitting job records).
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple


def enabled() -> bool:
    """RAY_TPU_LOG_PLANE=0 turns the whole plane off: capture falls back
    to plain (unstamped) redirection and no monitor threads run."""
    return os.environ.get("RAY_TPU_LOG_PLANE", "1") != "0"

STAMP = "\x1f"
_VER = "rt1"
_PREFIX = STAMP + _VER + "|"

# record tuple layout (wire + store):
# (ts, stream, src, job, task, actor, trace, line)
REC_TS, REC_STREAM, REC_SRC, REC_JOB, REC_TASK, REC_ACTOR, REC_TRACE, \
    REC_LINE = range(8)

_MAX_LINE = 4096  # clamp pathological lines; keeps rings and wire bounded


def _int_env(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _float_env(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


# Context-epoch cache: the full lookup below costs ~750ns, which at
# thousands of print()s per second is the plane's single biggest cost.
# The values only change when the worker enters/leaves a task or a trace
# context opens/closes — those sites call bump_context_epoch(), and each
# thread reuses its cached (fields, stamp body) until the epoch moves.
# The epoch is read BEFORE computing, so a concurrent bump can only mark
# fresh fields as stale (a spurious recompute), never serve stale ones.
_epoch = 0
_tls = threading.local()


def bump_context_epoch() -> None:
    """Called by worker/tracing wherever execution context changes."""
    global _epoch
    _epoch += 1


def context_fields() -> Tuple[str, str, str, str]:
    """(job, task, actor, trace) of the *calling thread*, as hex strings
    ("" when absent)."""
    cached = getattr(_tls, "ctx", None)
    if cached is not None and cached[0] == _epoch:
        return cached[1]
    e = _epoch
    fields = _context_fields_uncached()
    # [3] caches the fully formatted stamp per src for this context
    _tls.ctx = (e, fields, "|".join(fields), {})
    return fields


def _context_fields_uncached() -> Tuple[str, str, str, str]:
    """Lazy sys.modules lookups: this runs inside ``print()`` and must
    not import anything (import locks inside a write() re-entering an
    importing thread deadlocks)."""
    job = task = actor = trace = ""
    w = sys.modules.get("ray_tpu._private.worker")
    if w is not None:
        gw = w.global_worker
        j = gw.current_job_id or gw.job_id
        if j:
            job = str(j)
        t = gw.current_task_id
        if t:
            task = t.hex() if isinstance(t, bytes) else str(t)
        a = gw.current_actor_id
        if a:
            actor = a.hex() if isinstance(a, bytes) else str(a)
    tr = sys.modules.get("ray_tpu.util.tracing")
    if tr is not None:
        try:
            ctx = tr.current_context()
        except Exception:
            ctx = None
        if ctx:
            trace = str(ctx.get("trace_id") or "")
    return job, task, actor, trace


def format_stamp(src: str) -> str:
    """The line prefix for a record written NOW by this thread."""
    cached = getattr(_tls, "ctx", None)
    if cached is None or cached[0] != _epoch:
        context_fields()
        cached = _tls.ctx
    stamp = cached[3].get(src)
    if stamp is None:
        stamp = cached[3][src] = _PREFIX + src + "|" + cached[2] + STAMP
    return stamp


def parse_line(raw: str, default_src: str = "o"):
    """``(src, job, task, actor, trace, text)`` from one tailed line.
    Unstamped lines (C-level writes, pre-redirect output) come back with
    empty context and ``default_src``."""
    if raw.startswith(_PREFIX):
        end = raw.find(STAMP, len(_PREFIX))
        if end != -1:
            head = raw[len(_PREFIX):end]
            parts = head.split("|")
            if len(parts) == 5:
                src, job, task, actor, trace = parts
                return src or default_src, job, task, actor, trace, raw[end + 1:]
    return default_src, "", "", "", "", raw


class _RotatingFile:
    """Owns the capture file shared by fds 1 and 2: tracks size, and past
    the cap renames ``path`` -> ``path.1`` and re-dup2s a fresh file onto
    both fds.  One backup: a log-spamming process costs at most
    2x rotate_bytes of disk, matching the reference's capped worker
    logs."""

    def __init__(self, path: str, max_bytes: int, fds=(1, 2)):
        self.path = path
        self.max_bytes = max_bytes
        self.fds = tuple(fds)
        self.lock = threading.Lock()
        try:
            self.size = os.path.getsize(path)
        except OSError:
            self.size = 0

    def wrote(self, n: int) -> None:
        # unlocked add: += under the GIL can drop a race's worth of
        # bytes, which only delays an (approximate by design) rotation —
        # not worth a lock acquire inside every print()
        self.size += n
        if self.size < self.max_bytes:
            return
        with self.lock:
            if self.size < self.max_bytes:
                return  # another thread just rotated
            try:
                os.replace(self.path, self.path + ".1")
                fd = os.open(self.path,
                             os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
                for target in self.fds:
                    os.dup2(fd, target)
                os.close(fd)
                self.size = 0
            except OSError:
                # rotation is best-effort; keep writing to the old inode
                self.size = 0


class ContextStampingStream:
    """Line-buffered text stream over a redirected fd that prefixes every
    line with the live context stamp.  Installed as ``sys.stdout``/
    ``sys.stderr`` after dup2 so plain ``print()`` is correlated.

    Does its own line buffering with direct ``os.write`` at newline
    boundaries — one syscall per complete line, no TextIOWrapper newline
    scan — because this sits inside every ``print()`` the process makes
    and its cost over the disabled path is what the
    ``log_plane_overhead`` bench gates.  Never raises from ``write`` —
    logging must never kill the process it observes."""

    _rt_log_plane = True  # logging_utils checks this to pre-stamp records

    encoding = "utf-8"
    errors = "replace"
    newlines = None

    def __init__(self, fd: int, src: str, rot: Optional[_RotatingFile] = None):
        self._fd = fd
        self._src = src
        self._rot = rot
        self._lock = threading.Lock()
        self._at_start = True
        self._buf: List[str] = []  # pending partial line (already stamped)

    def _emit(self, data: str) -> None:
        """os.write the whole encoded chunk (lock held by caller)."""
        raw = data.encode("utf-8", "replace")
        n = os.write(self._fd, raw)
        while n < len(raw):  # short writes only on pipes/signals
            n += os.write(self._fd, raw[n:])
        if self._rot is not None:
            self._rot.wrote(n)

    def write(self, s) -> int:
        if not s:
            return 0
        if not isinstance(s, str):
            s = str(s)
        try:
            with self._lock:
                # fast path: at most one newline, at the end — the two
                # shapes print() emits (the joined text, then its
                # end="\n")
                nl = s.find("\n")
                if nl == -1 or nl == len(s) - 1:
                    if self._at_start and not s.startswith(STAMP):
                        s2 = format_stamp(self._src) + s
                    else:
                        s2 = s
                    if nl == -1:
                        self._buf.append(s2)
                        self._at_start = False
                    else:
                        if self._buf:
                            self._buf.append(s2)
                            s2 = "".join(self._buf)
                            self._buf.clear()
                        self._emit(s2)
                        self._at_start = True
                    return len(s)
                # slow path: several lines in one call
                parts = s.split("\n")
                tail = parts.pop()  # partial line ("" when s ends in \n)
                out = self._buf[:]
                self._buf.clear()
                for seg in parts:
                    if self._at_start and not seg.startswith(STAMP):
                        out.append(format_stamp(self._src))
                    out.append(seg)
                    out.append("\n")
                    self._at_start = True
                self._emit("".join(out))
                if tail:
                    if not tail.startswith(STAMP):
                        self._buf.append(format_stamp(self._src))
                    self._buf.append(tail)
                    self._at_start = False
        except (OSError, ValueError):
            pass
        return len(s)

    def writelines(self, lines) -> None:
        for ln in lines:
            self.write(ln)

    def write_record(self, src: str, text: str) -> None:
        """One pre-formatted record line with an explicit src (logger
        levels): stamps with ``src`` regardless of this stream's own.
        A pending partial print() line is terminated first — a logger
        record never glues onto someone else's line."""
        if not text.endswith("\n"):
            text += "\n"
        try:
            with self._lock:
                out = format_stamp(src) + text
                if self._buf:
                    self._buf.append("\n")
                    self._buf.append(out)
                    out = "".join(self._buf)
                    self._buf.clear()
                self._emit(out)
                self._at_start = True
        except (OSError, ValueError):
            pass

    def flush(self) -> None:
        try:
            with self._lock:
                if self._buf:
                    self._emit("".join(self._buf))
                    self._buf.clear()
                    # the partial line is on disk but still open; the
                    # next write continues it unstamped
        except (OSError, ValueError):
            pass

    def fileno(self) -> int:
        return self._fd

    def isatty(self) -> bool:
        return False

    def writable(self) -> bool:
        return True

    def readable(self) -> bool:
        return False

    def seekable(self) -> bool:
        return False

    def close(self) -> None:  # never close a process-level fd from here
        self.flush()


def redirect_process_output(path: str, fds=(1, 2)) -> bool:
    """dup2 this process's stdout/stderr into a rotating capture file at
    ``path`` and install stamping wrappers.  The worker-boot invariant
    holds: any failure leaves the process on its inherited fds.  With the
    plane disabled (``RAY_TPU_LOG_PLANE=0``) the redirect still happens
    (the file is the crash trail) but lines go through plain unstamped
    streams — the bench's disabled-path baseline."""
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        for target in fds:
            os.dup2(fd, target)
        os.close(fd)
        if not enabled():
            if 1 in fds:
                sys.stdout = os.fdopen(1, "w", buffering=1, closefd=False)
            if 2 in fds:
                sys.stderr = os.fdopen(2, "w", buffering=1, closefd=False)
            return True
        rot = _RotatingFile(
            path, _int_env("RAY_TPU_LOG_ROTATE_BYTES", 16 << 20), fds)
        if 1 in fds:
            sys.stdout = ContextStampingStream(1, "o", rot)
        if 2 in fds:
            sys.stderr = ContextStampingStream(2, "e", rot)
        return True
    except OSError:
        return False


class StampedFileHandler(logging.Handler):
    """Mirror a process's ``ray_tpu.*`` logger records into a stamped,
    size-capped capture file.  For processes that must NOT dup2 their
    fds away (the head shares the driver's tty): the user keeps their
    terminal output, the log plane still gets a tailable per-process
    file."""

    def __init__(self, path: str, max_bytes: Optional[int] = None):
        super().__init__()
        self.path = path
        self.max_bytes = (max_bytes if max_bytes is not None
                          else _int_env("RAY_TPU_LOG_ROTATE_BYTES", 16 << 20))
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._f = open(path, "a", errors="replace")
        try:
            self._size = os.path.getsize(path)
        except OSError:
            self._size = 0

    _LEVEL_SRC = {"DEBUG": "D", "INFO": "I", "WARNING": "W",
                  "ERROR": "E", "CRITICAL": "C"}

    def emit(self, record: logging.LogRecord) -> None:
        try:
            src = self._LEVEL_SRC.get(record.levelname, "I")
            line = format_stamp(src) + self.format(record) + "\n"
            # no inner locking: logging.Handler.handle() already holds
            # self.lock around emit(), so writes and the rotation swap
            # are serialized by the framework
            self._f.write(line)
            self._f.flush()
            self._size += len(line)
            if self._size >= self.max_bytes:
                self._f.close()
                os.replace(self.path, self.path + ".1")
                self._f = open(self.path, "a", errors="replace")
                self._size = 0
        except Exception:
            self.handleError(record)

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:
            pass
        super().close()


def attach_logger_capture(path: str) -> Optional[StampedFileHandler]:
    """Attach a StampedFileHandler for every ``ray_tpu.*`` logger record
    in this process (head self-capture).  Returns the handler so the
    caller can detach it at shutdown."""
    try:
        h = StampedFileHandler(path)
    except OSError:
        return None
    h.setFormatter(logging.Formatter(
        "[ray_tpu %(levelname)s %(name)s] %(message)s"))
    logging.getLogger("ray_tpu").addHandler(h)
    return h


def make_driver_log_callback(out_fn: Optional[Callable[[str], None]] = None):
    """Pubsub callback re-emitting a job's shipped log records on the
    driver, prefixed ``(name pid=… node=…)`` like the reference's
    print_to_stdstream.  Error-ish records go to the driver's stderr,
    the rest to stdout."""

    def _cb(data) -> None:
        for r in (data or {}).get("records") or []:
            try:
                name = r.get("name") or r.get("stream") or "?"
                prefix = f"({name} pid={r.get('pid')}, node={r.get('node')})"
                text = f"{prefix} {r.get('line', '')}"
                if out_fn is not None:
                    out_fn(text)
                    continue
                src = r.get("src", "o")
                stream = (sys.stderr if src in ("e", "E", "C", "W")
                          else sys.stdout)
                print(text, file=stream)
            except Exception:
                return  # a broken sink must not kill the pubsub thread

    return _cb


class _Tail:
    __slots__ = ("stream", "path", "meta", "fd", "carry", "tokens",
                 "tok_t", "suppressed", "default_src")

    def __init__(self, stream: str, path: str, meta: dict, now: float):
        self.stream = stream
        self.path = path
        self.meta = meta
        self.fd: Optional[int] = None
        self.carry = b""
        self.tokens: float = 0.0
        self.tok_t = now
        self.suppressed = 0
        self.default_src = "o"


class LogMonitor:
    """Rotation-safe multi-file tailer (reference ``LogMonitor``).

    Files are *registered* (not dir-scanned) so ownership is explicit: on
    an emulated multi-node host the head and an agent may share one
    session dir, and each must ship only its own workers' files or every
    line arrives twice.  ``send_fn`` ships ``log_report`` frames over a
    control connection (node agent); ``ingest_fn`` feeds the head's store
    directly when the monitor runs inside the head process.

    Offsets live in the open fd. Per poll: drain the fd to EOF, then
    compare ``stat(path)`` to ``fstat(fd)`` — a different inode means the
    file rotated under us (the drained fd already holds every old line;
    reopen at 0), a shrunken same-inode file means truncation (seek 0).
    Old offsets are never re-shipped because the old inode's fd is the
    only cursor that ever read it."""

    def __init__(self, origin: str,
                 send_fn: Optional[Callable[[dict], None]] = None,
                 ingest_fn: Optional[Callable] = None,
                 interval_s: Optional[float] = None,
                 rate_lps: Optional[float] = None,
                 max_batch_lines: int = 2000,
                 max_read_bytes: int = 1 << 20,
                 closed_fn: Callable[[], bool] = lambda: False):
        self.origin = origin
        # named `send`, not `_send_fn`: this IS the monitor's wire-send
        # call, and raylint R1 pairs its log_report frames with the
        # head's dispatch arm through that name
        self.send = send_fn
        self._ingest_fn = ingest_fn
        self.interval_s = (interval_s if interval_s is not None
                           else _float_env("RAY_TPU_LOG_SHIP_S", 1.0))
        self.rate_lps = (rate_lps if rate_lps is not None
                         else _float_env("RAY_TPU_LOG_RATE_LPS", 2000.0))
        self.max_batch_lines = max_batch_lines
        self.max_read_bytes = max_read_bytes
        self._closed_fn = closed_fn
        self._tails: Dict[str, _Tail] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- registration ---------------------------------------------------
    def register(self, stream: str, path: str, **meta) -> None:
        now = time.time()
        with self._lock:
            if stream in self._tails:
                return
            t = _Tail(stream, path, meta, now)
            t.tokens = self.rate_lps  # full bucket at birth
            if meta.get("src"):
                t.default_src = meta["src"]
            self._tails[stream] = t

    def unregister(self, stream: str, final_drain: bool = True) -> None:
        """Drop a stream, shipping whatever the file gained since the
        last poll first — this is how a SIGKILL'd worker's final stderr
        reaches the head after death."""
        with self._lock:
            t = self._tails.pop(stream, None)
        if t is None:
            return
        if final_drain:
            recs = self._drain(t, time.time(), final=True)
            if recs:
                self._ship(recs, {t.stream: t.meta})
        if t.fd is not None:
            try:
                os.close(t.fd)
            except OSError:
                pass

    def streams(self) -> List[str]:
        with self._lock:
            return list(self._tails)

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "LogMonitor":
        t = threading.Thread(target=self._loop, name="log-monitor",
                             daemon=True)
        t.start()
        self._thread = t
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        self.poll_once()  # final ship while the connection is still live

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            if self._closed_fn():
                return
            try:
                self.poll_once()
            except Exception:
                # the tail loop must outlive any single bad file
                pass

    # -- tailing --------------------------------------------------------
    def poll_once(self, now: Optional[float] = None) -> int:
        """Tail every registered file once; ship complete lines.  Returns
        the number of records shipped (tests drive this directly)."""
        if now is None:
            now = time.time()
        with self._lock:
            tails = list(self._tails.values())
        records: List[tuple] = []
        metas: Dict[str, dict] = {}
        for t in tails:
            recs = self._drain(t, now)
            if recs:
                records.extend(recs)
                metas[t.stream] = t.meta
        if records:
            self._ship(records, metas)
        return len(records)

    def _drain(self, t: _Tail, now: float, final: bool = False) -> List[tuple]:
        if t.fd is None:
            try:
                t.fd = os.open(t.path, os.O_RDONLY)
            except OSError:
                return []
        chunks = []
        budget = self.max_read_bytes
        eof = False
        try:
            while budget > 0:
                chunk = os.read(t.fd, min(65536, budget))
                if not chunk:
                    eof = True
                    break
                chunks.append(chunk)
                budget -= len(chunk)
            # rotation/truncation checks only once the old fd is fully
            # drained: closing it with bytes still unread would lose them
            if eof:
                try:
                    st = os.stat(t.path)
                except OSError:
                    st = None  # mid-rotation rename; next poll reopens
                fst = os.fstat(t.fd)
                if st is None or st.st_ino != fst.st_ino:
                    # rotated: the drained fd held the complete old file —
                    # terminate any carried partial as its final line, then
                    # follow the new inode from offset 0
                    last_data = chunks[-1] if chunks else t.carry
                    if last_data and not last_data.endswith(b"\n"):
                        chunks.append(b"\n")
                    os.close(t.fd)
                    t.fd = None
                    if st is not None:
                        try:
                            t.fd = os.open(t.path, os.O_RDONLY)
                        except OSError:
                            t.fd = None
                elif st.st_size < os.lseek(t.fd, 0, os.SEEK_CUR):
                    # truncated in place: restart from the top
                    os.lseek(t.fd, 0, os.SEEK_SET)
                    t.carry = b""
        except OSError:
            return []
        data = t.carry + b"".join(chunks)
        if not data:
            return []
        lines = data.split(b"\n")
        t.carry = lines.pop()  # trailing partial (b"" when data ends in \n)
        if final and t.carry:
            lines.append(t.carry)
            t.carry = b""
        # refill the token bucket, then spend it; overflow becomes one
        # counted marker instead of a head-melting flood
        t.tokens = min(self.rate_lps * 2,
                       t.tokens + (now - t.tok_t) * self.rate_lps)
        t.tok_t = now
        out: List[tuple] = []
        stream, dsrc, plen = t.stream, t.default_src, len(_PREFIX)
        for idx, raw in enumerate(lines):
            if t.tokens < 1.0:
                # everything past here is over budget: count, don't parse
                t.suppressed += len(lines) - idx
                break
            t.tokens -= 1.0
            if t.suppressed:
                out.append((now, stream, "m", "", "", "", "",
                            f"(suppressed {t.suppressed} lines)"))
                t.suppressed = 0
            line = raw[:_MAX_LINE].decode("utf-8", "replace")
            # parse_line, inlined: this loop is the head/agent-side cost
            # of a log flood (the bench's tail_ship number)
            if line.startswith(_PREFIX):
                end = line.find(STAMP, plen)
                if end != -1:
                    parts = line[plen:end].split("|")
                    if len(parts) == 5:
                        out.append((now, stream, parts[0] or dsrc, parts[1],
                                    parts[2], parts[3], parts[4],
                                    line[end + 1:]))
                        continue
            out.append((now, stream, dsrc, "", "", "", "", line))
        if final and t.suppressed:
            out.append((now, t.stream, "m", "", "", "", "",
                        f"(suppressed {t.suppressed} lines)"))
            t.suppressed = 0
        return out

    def _ship(self, records: List[tuple], metas: Dict[str, dict]) -> None:
        for i in range(0, len(records), self.max_batch_lines):
            batch = records[i:i + self.max_batch_lines]
            if self._ingest_fn is not None:
                try:
                    self._ingest_fn(self.origin, batch, metas)
                except Exception:
                    pass
            if self.send is not None:
                try:
                    self.send({"type": "log_report",
                               "origin": self.origin,
                               "records": batch, "streams": metas})
                except (OSError, ValueError):
                    return  # connection gone; the closed_fn ends the loop
