"""Worker/driver runtime: the process-local half of the core API.

Combines the roles of the reference's Python worker
(``python/ray/_private/worker.py`` — global ``Worker`` singleton, ``init``,
``get/put/wait``) and the Cython task-execution callback
(``python/ray/_raylet.pyx:680`` ``execute_task``): argument resolution,
function-table fetch on miss (``FunctionActorManager``,
``python/ray/_private/function_manager.py:56``), running the user function,
and storing returns.  Also builds task specs (TaskSpecBuilder analog,
``src/ray/common/task/task_spec.h``).
"""

from __future__ import annotations

import asyncio
import hashlib
import inspect
import os
import queue
import sys
import threading
import time
import traceback
import weakref
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu._private import events as _events
from ray_tpu._private import log_plane
from ray_tpu._private import serialization
from ray_tpu._private.client import CoreClient
from ray_tpu._private.config import get_config
from ray_tpu._private.object_ref import ObjectRef, new_id
from ray_tpu._private.object_store import ObjectLocation, read_value, store_value

FN_NAMESPACE = "fn"

# per-execution tenant identity (see Worker.current_job_id): contextvars
# so the value follows the executing thread OR asyncio task, never leaks
# between a threaded actor's concurrent methods or interleaved coroutines
import contextvars  # noqa: E402

_job_ctx: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "ray_tpu_current_job", default=None)
_ns_ctx: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "ray_tpu_current_namespace", default=None)


class _ArgPlaceholder:
    """Marks a top-level ObjectRef argument resolved by the head before dispatch."""

    __slots__ = ("oid",)

    def __init__(self, oid: bytes):
        self.oid = oid

    def __reduce__(self):
        return (_ArgPlaceholder, (self.oid,))


class Worker:
    """Process-global runtime state (driver or worker mode)."""

    def __init__(self):
        self.mode: Optional[str] = None  # "driver" | "worker"
        self.client: Optional[CoreClient] = None
        self.node: Optional["Node"] = None  # driver only: in-process head
        self.node_id: str = ""
        # thin-client mode (ray_tpu.init("client://...") — Ray Client
        # analog): this process shares no shm with the cluster, so object
        # payloads ride the control socket both ways
        self.thin_client: bool = False
        self.worker_id: bytes = b""
        self.function_cache: Dict[bytes, Any] = {}
        self.registered_fn_ids: set = set()
        # runtime_env package uploads are once per unique env per driver
        # (content addressing dedups across drivers at the KV)
        self._prepared_envs: Dict[str, dict] = {}
        self._current_task_id: Optional[bytes] = None
        self._current_actor_id: Optional[bytes] = None
        self.actor_instance: Any = None
        # tenant identity: for drivers, assigned at register_client; for
        # workers, inherited per-task from the executing spec (actor
        # workers pin theirs at creation).  get_runtime_context() and
        # namespace-scoped get_actor read these.  The per-task half
        # lives in CONTEXTVARS (module-level _job_ctx/_ns_ctx): threaded
        # actors run methods from different submitters concurrently, and
        # async methods hop to the event-loop thread — contextvars track
        # the executing thread AND the asyncio task, so one method never
        # reads another's tenant.
        self.job_id: Optional[str] = None
        self.namespace: Optional[str] = None
        # per-thread: threaded actors run several methods at once, and each
        # thread's nested-get blocked/unblocked notifications must pair up
        self._depth_local = threading.local()
        # local handle counts per oid; the head is told when this process's
        # first handle appears (borrow) and when its last one dies
        self._ref_counts: Dict[bytes, int] = {}
        self._ref_lock = threading.Lock()
        # Finalizers only ever append here — a deque append is atomic,
        # allocates without taking our lock, and is reentrancy-safe, so a
        # GC pass firing a finalizer mid-track_ref can't self-deadlock
        # (the reference's ReferenceCounter defers finalizer work the same
        # way).  Drained by flush_removals on client calls + a 1s timer.
        self._dead_handles: "deque[bytes]" = deque()
        self._flusher_started = False

    # task/actor identity are properties so EVERY set site invalidates
    # the log plane's per-thread stamp cache (print()-path lines carry
    # the live context without re-deriving it per line)
    @property
    def current_task_id(self) -> Optional[bytes]:
        return self._current_task_id

    @current_task_id.setter
    def current_task_id(self, value: Optional[bytes]) -> None:
        self._current_task_id = value
        log_plane.bump_context_epoch()

    @property
    def current_actor_id(self) -> Optional[bytes]:
        return self._current_actor_id

    @current_actor_id.setter
    def current_actor_id(self, value: Optional[bytes]) -> None:
        self._current_actor_id = value
        log_plane.bump_context_epoch()

    @property
    def current_job_id(self) -> Optional[str]:
        return _job_ctx.get()

    @current_job_id.setter
    def current_job_id(self, value: Optional[str]) -> None:
        _job_ctx.set(value)
        log_plane.bump_context_epoch()

    @property
    def current_namespace(self) -> Optional[str]:
        return _ns_ctx.get()

    @current_namespace.setter
    def current_namespace(self, value: Optional[str]) -> None:
        _ns_ctx.set(value)

    @property
    def task_depth(self) -> int:
        return getattr(self._depth_local, "depth", 0)

    @task_depth.setter
    def task_depth(self, value: int) -> None:
        self._depth_local.depth = value

    # ------------------------------------------------------------------
    # reference tracking (client half of ReferenceCounter)
    # ------------------------------------------------------------------
    def track_ref(self, ref: ObjectRef, *, owned: bool) -> ObjectRef:
        """Register a live handle.  ``owned=True`` for refs whose head-side
        entry was created on this process's behalf with an initial count
        (put / task returns); ``owned=False`` for deserialized borrows,
        which add_ref immediately (the enclosing container's pin is still
        held, so the increment can't race the object's deletion)."""
        oid = ref.binary()
        announce = False
        with self._ref_lock:
            n = self._ref_counts.get(oid, 0)
            self._ref_counts[oid] = n + 1
            if n == 0 and not owned:
                announce = True
        if announce and self.client is not None and not self.client.closed:
            try:
                self.client.add_refs([oid])
            except Exception:
                pass
        weakref.finalize(ref, self._dead_handles.append, oid)
        self._ensure_flusher()
        return ref

    def _ensure_flusher(self) -> None:
        if self._flusher_started:
            return
        self._flusher_started = True

        def loop():
            while True:
                time.sleep(1.0)
                if self.client is None or self.client.closed:
                    continue
                try:
                    self.flush_removals()
                except Exception:
                    pass

        threading.Thread(target=loop, daemon=True, name="ref-flusher").start()

    def flush_removals(self) -> None:
        """Drain finalizer notifications: decrement local counts, tell the
        head about handles whose last local copy died."""
        removals: List[bytes] = []
        with self._ref_lock:
            while True:
                try:
                    oid = self._dead_handles.popleft()
                except IndexError:
                    break
                n = self._ref_counts.get(oid, 0) - 1
                if n > 0:
                    self._ref_counts[oid] = n
                else:
                    self._ref_counts.pop(oid, None)
                    removals.append(oid)
        if removals and self.client is not None and not self.client.closed:
            try:
                self.client.remove_refs(removals)
            except Exception:
                pass

    @property
    def connected(self) -> bool:
        return self.client is not None

    # ------------------------------------------------------------------
    # objects
    # ------------------------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        self.flush_removals()
        ref = ObjectRef.random()
        if self.thin_client:
            self._put_blob(ref, value)
        else:
            loc, contained = store_value(ref, value)
            self.client.seal(ref.binary(), loc, [r.binary() for r in contained])
        return self.track_ref(ref, owned=True)

    def _put_blob(self, ref: ObjectRef, value: Any,
                  track_contained: bool = True) -> None:
        """Thin-client put: ship serialized bytes; the head stores them."""
        meta, buffers, contained = serialization.serialize(value)
        reply = self.client.request({
            "type": "put_blob",
            "oid": ref.binary(),
            "blob": serialization.to_bytes(meta, buffers),
            # big-args specs track their refs via pinned_refs instead
            "contained": [r.binary() for r in contained] if track_contained else [],
        }, timeout=300)["value"]
        if isinstance(reply, dict) and reply.get("error"):
            raise RuntimeError(reply["error"])

    def _get_blobs(self, oids: List[bytes], timeout: Optional[float]) -> List[Any]:
        """Thin-client get: the head ships each payload over the socket.
        One shared deadline across the batch (fat-client get semantics);
        fetches run concurrently over the req_id-multiplexed connection."""
        from concurrent.futures import ThreadPoolExecutor

        from ray_tpu.exceptions import GetTimeoutError

        deadline = None if timeout is None else time.monotonic() + timeout

        def fetch(oid: bytes):
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise GetTimeoutError(f"Get timed out after {timeout}s")
            reply = self.client.request(
                {"type": "get_blob", "oid": oid, "timeout": remaining},
                timeout=None if remaining is None else remaining + 30,
            )["value"]
            if reply.get("timeout"):
                raise GetTimeoutError(f"Get timed out after {timeout}s")
            if reply.get("error"):
                raise RuntimeError(reply["error"])
            value = serialization.deserialize(memoryview(reply["blob"]))
            return value, bool(reply.get("is_error"))

        unique = list(dict.fromkeys(oids))
        if len(unique) == 1:
            results = [fetch(unique[0])]
        else:
            with ThreadPoolExecutor(min(8, len(unique))) as ex:
                results = list(ex.map(fetch, unique))
        values: Dict[bytes, Any] = {}
        for oid, (value, is_error) in zip(unique, results):
            if is_error:
                raise value
            values[oid] = value
        return [values[oid] for oid in oids]

    def get(self, refs: List[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        # traced callers get a get_wait span (object availability + transfer
        # is a first-class phase of a request's critical path); untraced or
        # events-off callers pay one flag check
        trace_ctx = None
        if _events.ENABLED:
            from ray_tpu.util import tracing

            trace_ctx = tracing.current_context()
        if trace_ctx is None:
            return self._get(refs, timeout)
        t0 = time.perf_counter()
        try:
            return self._get(refs, timeout)
        finally:
            waited = time.perf_counter() - t0
            if waited >= 0.001:
                from ray_tpu.util import tracing

                tracing.emit_span(
                    f"get x{len(refs)}", waited,
                    tracing.child_context("get"), phase="get_wait",
                    num_objects=len(refs))

    def _get(self, refs: List[ObjectRef], timeout: Optional[float]) -> List[Any]:
        from ray_tpu.exceptions import GetTimeoutError

        self.flush_removals()
        oids = [r.binary() for r in refs]
        if self.thin_client:
            return self._get_blobs(oids, timeout)
        blocked = self.mode == "worker" and self.task_depth > 0
        if blocked:
            self.client.notify_blocked()
        try:
            locations = self.client.get_locations(list(set(oids)), timeout)
        finally:
            if blocked:
                self.client.notify_unblocked()
        if locations is None:
            raise GetTimeoutError(f"Get timed out after {timeout}s for {len(oids)} objects")
        try:
            return [read_value(locations[oid], oid) for oid in oids]
        except FileNotFoundError:
            # segment spilled/moved between location reply and attach —
            # one refetch gets the fresh location
            locations = self.client.get_locations(list(set(oids)), timeout)
            return [read_value(locations[oid], oid) for oid in oids]

    def wait(
        self, refs: List[ObjectRef], num_returns: int, timeout: Optional[float]
    ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        self.flush_removals()
        oids = [r.binary() for r in refs]
        blocked = self.mode == "worker" and self.task_depth > 0
        if blocked:
            self.client.notify_blocked()
        try:
            ready_ids, _ = self.client.wait(oids, num_returns, timeout)
        finally:
            if blocked:
                self.client.notify_unblocked()
        ready_set = set(ready_ids)
        ready, not_ready = [], []
        for r in refs:
            (ready if r.binary() in ready_set and len(ready) < num_returns else not_ready).append(r)
        return ready, not_ready

    # ------------------------------------------------------------------
    # task specs
    # ------------------------------------------------------------------
    def register_function(self, blob: bytes) -> bytes:
        fn_id = hashlib.sha1(blob).digest()
        if fn_id not in self.registered_fn_ids:
            self.client.kv_put(FN_NAMESPACE, fn_id, blob)
            self.registered_fn_ids.add(fn_id)
        return fn_id

    def fetch_function(self, fn_id: bytes) -> Any:
        fn = self.function_cache.get(fn_id)
        if fn is None:
            blob = self.client.kv_get(FN_NAMESPACE, fn_id)
            if blob is None:
                raise RuntimeError(f"function {fn_id.hex()} not found in GCS KV")
            fn = cloudpickle.loads(blob)
            self.function_cache[fn_id] = fn
        return fn

    def build_task_spec(
        self,
        *,
        name: str,
        fn_id: Optional[bytes],
        args: tuple,
        kwargs: dict,
        num_returns: int,
        resources: Dict[str, float],
        scheduling_strategy: Optional[dict] = None,
        max_retries: int = 0,
        actor_id: Optional[bytes] = None,
        method_name: Optional[str] = None,
        is_actor_creation: bool = False,
        max_restarts: int = 0,
        max_task_retries: int = 0,
        actor_name: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        max_concurrency: int = 1,
        release_cpu_after_start: bool = False,
        concurrency_group: Optional[str] = None,
        concurrency_groups: Optional[Dict[str, int]] = None,
        lifetime: Optional[str] = None,
        namespace: Optional[str] = None,
    ) -> Tuple[dict, List[ObjectRef]]:
        cfg = get_config()
        if runtime_env and (runtime_env.get("working_dir")
                            or runtime_env.get("py_modules")):
            import json as _json

            from ray_tpu._private.runtime_env_packaging import (
                prepare_runtime_env,
            )

            ck = _json.dumps(runtime_env, sort_keys=True)
            prepared = self._prepared_envs.get(ck)
            if prepared is None:
                prepared = prepare_runtime_env(runtime_env, self.client)
                self._prepared_envs[ck] = prepared
            runtime_env = prepared
        dep_ids: List[bytes] = []

        def _convert(v):
            if isinstance(v, ObjectRef):
                dep_ids.append(v.binary())
                return _ArgPlaceholder(v.binary())
            return v

        conv_args = tuple(_convert(a) for a in args)
        conv_kwargs = {k: _convert(v) for k, v in kwargs.items()}
        meta, buffers, contained = serialization.serialize((conv_args, conv_kwargs))
        # Pin every referenced object for the task's lifetime: top-level arg
        # refs (dep_ids) and refs nested inside serialized args.  Counted
        # HERE, while the caller's handles are provably alive (they sit in
        # ``args``), so a handle finalizer can't race the increment; the
        # head releases the pins when the task completes.
        pinned = list(dict.fromkeys(dep_ids + [r.binary() for r in contained]))
        if pinned:
            self.client.add_refs(pinned, reason="task_arg")
        owned_oids: List[bytes] = []
        total = serialization.total_size(meta, buffers)
        if total <= cfg.max_direct_call_object_size:
            args_blob = serialization.to_bytes(meta, buffers)
            args_oid = None
        else:
            # big args travel via the object store, not the control socket;
            # the spec owns this object's initial refcount
            big_ref = ObjectRef.random()
            if self.thin_client:
                self._put_blob(big_ref, (conv_args, conv_kwargs),
                               track_contained=False)
            else:
                loc, _ = store_value(big_ref, (conv_args, conv_kwargs))
                self.client.seal(big_ref.binary(), loc, [])
            args_blob = None
            args_oid = big_ref.binary()
            dep_ids.append(args_oid)
            owned_oids.append(args_oid)
        task_id = new_id()
        return_ids = [new_id() for _ in range(num_returns)]
        spec = {
            "task_id": task_id,
            "name": name,
            "fn_id": fn_id,
            "args_blob": args_blob,
            "args_oid": args_oid,
            "dep_ids": dep_ids,
            "pinned_refs": pinned,
            "owned_oids": owned_oids,
            "return_ids": return_ids,
            "num_returns": num_returns,
            "resources": dict(resources),
            "scheduling_strategy": scheduling_strategy,
            "retries_left": max_retries,
            "actor_id": actor_id,
            "method_name": method_name,
            "is_actor_creation": is_actor_creation,
            "max_restarts": max_restarts,
            "max_task_retries": max_task_retries,
            "actor_name": actor_name,
            "runtime_env": runtime_env,
            "max_concurrency": max_concurrency,
            "release_cpu_after_start": release_cpu_after_start,
            "concurrency_group": concurrency_group,
            "concurrency_groups": concurrency_groups,
            "lifetime": lifetime,
            # lineage edge for recursive cancellation (the reference embeds
            # the parent in the task id itself, src/ray/common/id.h)
            "parent_task_id": self.current_task_id,
            # tenant attribution: the submitting job, inherited by nested
            # submissions from inside tasks (current_*) or the driver's
            # own identity; actor creation may pin an explicit namespace
            "job_id": self.current_job_id or self.job_id,
            "namespace": (namespace if is_actor_creation and namespace
                          else self.current_namespace or self.namespace),
        }
        # strip default/absent fields off the wire — every consumer reads
        # optionals with .get(); a plain task's spec shrinks ~2x
        spec = {
            k: v for k, v in spec.items()
            if not (v is None or v == [] or v is False or v == 0)
            or k in ("task_id", "name", "return_ids", "num_returns")
        }
        from ray_tpu.util import tracing

        trace_ctx = tracing.child_context_for_task(name)
        if trace_ctx is not None:
            spec["trace_ctx"] = trace_ctx
        return spec, [
            self.track_ref(ObjectRef(oid), owned=True) for oid in return_ids
        ]


global_worker = Worker()

# -- cancellation state (worker mode) ---------------------------------------
# ids cancelled before they started: the exec loop skips them.  Async
# in-flight coroutines register here so a cancel can .cancel() them.
_cancelled_ids: set = set()
_async_futs: Dict[bytes, Any] = {}
_async_futs_lock = threading.Lock()
# main-thread execution state for interruption: "tid" is set only while
# user code for that task is running ON the main thread (the only thread
# interrupt_main can reach); "spec" outlives it until task_done is sent so
# the main loop can recover a report if a late KeyboardInterrupt lands
# between the user code finishing and the report going out.
_main_exec: Dict[str, Any] = {"tid": None, "spec": None}


def _on_cancel_message(msg: dict) -> None:
    """Runs on the client's recv thread (ray_tpu cancel -> CancelTask RPC
    analog).  Three cases: not started yet (skip via _cancelled_ids),
    running on the main thread (KeyboardInterrupt via interrupt_main — the
    reference raises the same into the worker), running as a coroutine
    (Future.cancel)."""
    tid = msg["task_id"]
    _cancelled_ids.add(tid)
    with _async_futs_lock:
        fut = _async_futs.get(tid)
    if fut is not None:
        fut.cancel()
        _cancelled_ids.discard(tid)  # consumed; nothing else will skip it
        return
    # interrupt only while the TARGET task's user code is on the main
    # thread — checking current_task_id alone could interrupt whatever ran
    # next (sealing, or an unrelated pipelined task)
    if _main_exec["tid"] == tid:
        import _thread

        _thread.interrupt_main()
    if len(_cancelled_ids) > 10_000:
        # unconsumed ids (cancels that raced completion) must not grow
        # forever; losing 10k-old skip markers is harmless
        _cancelled_ids.clear()


# ---------------------------------------------------------------------------
# Task execution (worker process)
# ---------------------------------------------------------------------------

_async_loop: Optional[asyncio.AbstractEventLoop] = None
_async_loop_lock = threading.Lock()
_async_sem: Optional[asyncio.Semaphore] = None
# per-concurrency-group coroutine bounds (created on the loop thread's
# first use of each group; setdefault keeps racing creators consistent)
_async_group_sems: Dict[str, asyncio.Semaphore] = {}


def _get_async_loop() -> asyncio.AbstractEventLoop:
    """Lazily start the worker's single persistent event loop thread."""
    global _async_loop
    with _async_loop_lock:
        if _async_loop is None:
            loop = asyncio.new_event_loop()
            t = threading.Thread(target=loop.run_forever, daemon=True,
                                 name="actor-async-loop")
            t.start()
            _async_loop = loop
    return _async_loop


_group_caps_cache: Optional[Dict[str, int]] = None


def _concurrency_group_caps() -> Dict[str, int]:
    """Declared concurrency groups of this (actor) worker, from the env
    the head set at spawn (``@remote(concurrency_groups={...})``).
    Parsed once — the env is fixed for the worker's lifetime and this
    sits on the async-method execution path."""
    global _group_caps_cache
    if _group_caps_cache is None:
        raw = os.environ.get("RAY_TPU_CONCURRENCY_GROUPS")
        caps: Dict[str, int] = {}
        if raw:
            import json

            try:
                caps = {str(k): int(v) for k, v in json.loads(raw).items()}
            except (ValueError, TypeError, AttributeError):
                caps = {}
        _group_caps_cache = caps
    return _group_caps_cache


async def _ensure_coro(awaitable, trace_ctx=None, group: Optional[str] = None,
                       job_id: Optional[str] = None,
                       namespace: Optional[str] = None):
    if trace_ctx is not None:
        # run_coroutine_threadsafe creates the Task with the LOOP thread's
        # context, not the submitting executor thread's — re-adopt here so
        # nested submissions from async actor methods stay in the trace
        from ray_tpu.util import tracing

        tracing._current.set(trace_ctx)
    # same re-adoption for tenant identity: the coroutine body must see
    # the SUBMITTER's job/namespace (runtime context, get_actor default,
    # nested-submission stamping), not the loop thread's leftovers
    _job_ctx.set(job_id)
    _ns_ctx.set(namespace)
    # max_concurrency must bound RUNNING coroutines, not just threads: the
    # head pipelines extra calls beyond max_concurrency (actor_pipeline_depth)
    # and an async method frees its executor thread immediately, so without
    # this gate pipelined coroutines would interleave past the user's limit
    # (an async actor declared max_concurrency=1 expects serial execution).
    # Concurrency groups get one semaphore EACH (the asyncio half of the
    # reference's ConcurrencyGroupManager<FiberState>): a saturated default
    # group never starves a named group's coroutines.
    caps = _concurrency_group_caps()
    if group is not None and group in caps:
        sem = _async_group_sems.get(group)
        if sem is None:
            sem = _async_group_sems.setdefault(
                group, asyncio.Semaphore(caps[group]))
    else:
        global _async_sem
        if _async_sem is None:
            _async_sem = asyncio.Semaphore(
                int(os.environ.get("RAY_TPU_MAX_CONCURRENCY", "1")))
        sem = _async_sem
    async with sem:
        return await awaitable


_completion_pool = None
_completion_pool_lock = threading.Lock()


def _completion_executor():
    """Single side thread that seals async-method results so the event loop
    never blocks on serialization/shm writes."""
    global _completion_pool
    with _completion_pool_lock:
        if _completion_pool is None:
            _completion_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="async-complete"
            )
        return _completion_pool


def _resolve_args(spec: dict, dep_locs: Dict[bytes, ObjectLocation]) -> Tuple[tuple, dict]:
    if spec.get("args_oid"):
        conv_args, conv_kwargs = read_value(dep_locs[spec["args_oid"]], spec["args_oid"])
    else:
        conv_args, conv_kwargs = serialization.deserialize(memoryview(spec["args_blob"]))

    def _resolve(v):
        if isinstance(v, _ArgPlaceholder):
            return read_value(dep_locs[v.oid], v.oid)
        return v

    args = tuple(_resolve(a) for a in conv_args)
    kwargs = {k: _resolve(v) for k, v in conv_kwargs.items()}
    return args, kwargs


def _execute_task(msg: dict) -> None:
    from ray_tpu.exceptions import RayTaskError

    w = global_worker
    spec = msg["spec"]
    if spec["task_id"] in _cancelled_ids:
        # cancelled while queued at this worker: report without executing
        # (the head pre-sealed the returns; our duplicate seal is dropped)
        from ray_tpu.exceptions import TaskCancelledError

        _cancelled_ids.discard(spec["task_id"])
        _seal_and_report(
            w, spec,
            [TaskCancelledError("task was cancelled")] * spec["num_returns"],
            True, "TaskCancelledError: cancelled before start", time.time())
        return
    dep_locs = msg.get("dep_locs", {})
    tpu_ids = msg.get("tpu_ids", [])
    # Overwrite (not setdefault): a pooled worker may be reused for a task
    # holding different chips than its previous one.  (jax/libtpu read the
    # env at first init, so chip isolation is only airtight for dedicated
    # actor workers — same caveat as CUDA_VISIBLE_DEVICES in the reference.)
    if tpu_ids:
        os.environ["TPU_VISIBLE_CHIPS"] = ",".join(str(i) for i in tpu_ids)
        os.environ["RAY_TPU_ASSIGNED_TPUS"] = os.environ["TPU_VISIBLE_CHIPS"]
    elif "RAY_TPU_ASSIGNED_TPUS" in os.environ and spec.get("actor_id") is None:
        os.environ.pop("TPU_VISIBLE_CHIPS", None)
        os.environ.pop("RAY_TPU_ASSIGNED_TPUS", None)
    w.current_task_id = spec["task_id"]
    # tenant context: nested submissions and get_runtime_context() inside
    # this task see the submitting job/namespace (set even when absent so
    # a pooled worker never leaks the previous tenant's identity)
    w.current_job_id = spec.get("job_id")
    w.current_namespace = spec.get("namespace")
    # continue the submitter's trace: nested submissions from this thread
    # chain under it (tracing_helper.py span-resume analog).  Set even when
    # None — a pooled worker must not leak the previous task's context.
    from ray_tpu.util import tracing

    tracing._current.set(spec.get("trace_ctx"))
    exec_start = time.time()  # profile event (core_worker profiling.h:30)
    failed = False
    error_str = None
    on_main = threading.current_thread() is threading.main_thread()
    if on_main:
        _main_exec["spec"] = spec
        _main_exec["tid"] = spec["task_id"]
    try:
        try:
            args, kwargs = _resolve_args(spec, dep_locs)
        except FileNotFoundError:
            # a dep's segment was spilled between dispatch and attach —
            # refetch locations once (same guard Worker.get has)
            fresh = w.client.get_locations(list(dep_locs), timeout=60)
            args, kwargs = _resolve_args(spec, fresh or dep_locs)
        if spec.get("is_actor_creation"):
            cls = w.fetch_function(spec["fn_id"])
            w.task_depth += 1
            try:
                w.actor_instance = cls(*args, **kwargs)
            finally:
                w.task_depth -= 1
            w.current_actor_id = spec["actor_id"]
            # a dedicated actor worker belongs to its actor's tenant for
            # life: method calls without a job context still resolve
            # namespace-scoped lookups against the actor's own namespace
            w.job_id = spec.get("job_id") or w.job_id
            w.namespace = spec.get("namespace") or w.namespace
            log_plane.bump_context_epoch()  # job_id is a plain attribute
            results = [None]
        elif spec.get("compiled_graph"):
            # compiled-graph control op (dag/compiled.py): a shipped
            # function run with the actor instance, outside the
            # method-name lane.  The op returns quickly; any execution
            # loop it installs runs on its own thread.
            fn = w.fetch_function(spec["fn_id"])
            w.task_depth += 1
            try:
                out = fn(w.actor_instance, *args, **kwargs)
            finally:
                w.task_depth -= 1
            results = _split_returns(out, spec["num_returns"])
        elif spec.get("actor_id") is not None:
            method = getattr(w.actor_instance, spec["method_name"])
            w.task_depth += 1
            try:
                out = method(*args, **kwargs)
                if inspect.isawaitable(out):
                    # async actor method: hand the coroutine to the worker's
                    # persistent event loop and finish via callback (fiber.h
                    # / asyncio concurrency-group analog).  No thread parks
                    # on the result, so in-flight concurrency is bounded by
                    # the loop, not the executor pool — 1000 awaiting calls
                    # cost 1000 loop tasks, not 1000 threads.
                    fut = asyncio.run_coroutine_threadsafe(
                        _ensure_coro(out, spec.get("trace_ctx"),
                                     spec.get("concurrency_group"),
                                     spec.get("job_id"),
                                     spec.get("namespace")),
                        _get_async_loop()
                    )
                    with _async_futs_lock:
                        _async_futs[spec["task_id"]] = fut
                        if spec["task_id"] in _cancelled_ids:
                            fut.cancel()  # cancel raced the registration

                    def _complete(f, spec=spec, exec_start=exec_start):
                        with _async_futs_lock:
                            _async_futs.pop(spec["task_id"], None)
                        # runs on the loop thread: compute the outcome only,
                        # then seal on a side thread — result serialization
                        # must never stall the other in-flight coroutines
                        try:
                            res = _split_returns(f.result(), spec["num_returns"])
                            failed_, err_str = False, None
                        except BaseException as e:  # noqa: BLE001
                            tb = traceback.format_exc()
                            err = e if isinstance(e, RayTaskError) else RayTaskError(
                                f"Task {spec.get('name')} failed:\n{tb}", cause=e
                            )
                            res = [err] * spec["num_returns"]
                            failed_, err_str = True, f"{type(e).__name__}: {e}"
                        _completion_executor().submit(
                            _seal_and_report, w, spec, res, failed_, err_str,
                            exec_start,
                        )

                    fut.add_done_callback(_complete)
                    if on_main:  # the coroutine owns reporting from here
                        _main_exec["spec"] = None
                    return
            finally:
                w.task_depth -= 1
            results = _split_returns(out, spec["num_returns"])
        else:
            fn = w.fetch_function(spec["fn_id"])
            w.task_depth += 1
            try:
                out = fn(*args, **kwargs)
                if inspect.isawaitable(out):  # async remote function
                    out = asyncio.run_coroutine_threadsafe(
                        _ensure_coro(out, spec.get("trace_ctx"),
                                     None, spec.get("job_id"),
                                     spec.get("namespace")),
                        _get_async_loop()
                    ).result()
                if spec.get("dynamic_returns"):
                    out = _stream_dynamic_returns(w, spec, out)
            finally:
                w.task_depth -= 1
            results = (
                [out] if spec.get("dynamic_returns")
                else _split_returns(out, spec["num_returns"])
            )
    except BaseException as e:  # noqa: BLE001
        failed = True
        tb = traceback.format_exc()
        error_str = f"{type(e).__name__}: {e}"
        err = e if isinstance(e, RayTaskError) else RayTaskError(
            f"Task {spec.get('name')} failed:\n{tb}", cause=e
        )
        results = [err] * spec["num_returns"]
    finally:
        if on_main:  # close the cancellation-interrupt window
            _main_exec["tid"] = None
    _seal_and_report(w, spec, results, failed, error_str, exec_start)


def _seal_and_report(w, spec: dict, results: List[Any], failed: bool,
                     error_str: Optional[str],
                     exec_start: Optional[float] = None) -> None:
    """Seal the return objects and tell the head the task finished.  Runs on
    the executing thread for sync tasks and on the event-loop thread (via
    add_done_callback) for async actor methods."""
    from ray_tpu.exceptions import RayTaskError

    seals = []
    for oid, value in zip(spec["return_ids"], results):
        ref = ObjectRef(oid)
        try:
            loc, contained = store_value(ref, value, is_error=failed)
        except BaseException as e:  # unserializable result
            loc, contained = store_value(
                ref, RayTaskError(f"Failed to serialize result of {spec.get('name')}: {e}"),
                is_error=True,
            )
        seals.append((oid, loc, [r.binary() for r in contained]))
    # returns ride inside task_done — one message per task instead of
    # num_returns+1; the head seals them before the done bookkeeping
    w.client.send({
        "type": "task_done",
        "seals": seals,
        "spec_ref": {
            "task_id": spec["task_id"],
            "return_ids": spec["return_ids"],
            "is_actor_creation": spec.get("is_actor_creation"),
            "actor_id": spec.get("actor_id"),
            "name": spec.get("name"),
        },
        "failed": failed,
        "error_str": error_str,
        # profile event window (Profiler/ProfileEvent analog) — the head
        # stores it on TaskInfo for `ray_tpu timeline`
        "exec_start": exec_start,
        "exec_end": time.time(),
        "worker_pid": os.getpid(),
    })
    w.current_task_id = None
    if threading.current_thread() is threading.main_thread():
        _main_exec["spec"] = None  # reported; nothing left to recover


def _stream_dynamic_returns(w: Worker, spec: dict, out) -> "ObjectRefGenerator":
    """``num_returns="dynamic"`` executor half (reference
    ``_raylet.pyx`` dynamic-return storing): each yielded value becomes its
    own object sealed AS PRODUCED — the head's yield directory streams the
    refs to any ObjectRefGenerator consumer before the task even finishes.
    The terminal return is the materialized generator, whose contained refs
    pin the yielded objects."""
    from ray_tpu._private.object_ref import ObjectRefGenerator

    refs = []
    for item in out:
        r = ObjectRef.random()
        loc, contained = store_value(r, item)
        w.client.seal(r.binary(), loc, [c.binary() for c in contained])
        w.client.send({"type": "dynamic_yield",
                       "task_id": spec["task_id"], "oid": r.binary()})
        refs.append(w.track_ref(r, owned=True))
    return ObjectRefGenerator(refs)


def _split_returns(out: Any, num_returns: int) -> List[Any]:
    if num_returns == 1:
        return [out]
    if not isinstance(out, (tuple, list)) or len(out) != num_returns:
        raise ValueError(
            f"Task declared num_returns={num_returns} but returned {type(out)}"
        )
    return list(out)


def _redirect_output_to_log() -> None:
    """Redirect this worker's stdout/stderr into its per-worker rotating
    log file (``RAY_TPU_WORKER_LOG``, set at spawn), stamped with live
    task/actor/job/trace context so the log plane can correlate plain
    ``print()`` output (reference: per-worker log files under the session
    dir + the log monitor's line attribution).  dup2 at the fd level
    catches subprocess and C-level writes too; self-redirection works for
    every spawn path, including forkserver forks that inherit the
    template's fds.  Failures are swallowed inside
    ``redirect_process_output`` — logging must never block a worker
    boot."""
    path = os.environ.get("RAY_TPU_WORKER_LOG")
    if not path:
        return
    from ray_tpu._private.log_plane import redirect_process_output

    redirect_process_output(path)


def main() -> None:
    """Worker process entry point (python -m ray_tpu._private.worker)."""
    _redirect_output_to_log()
    address = os.environ["RAY_TPU_ADDRESS"]
    authkey = bytes.fromhex(os.environ["RAY_TPU_AUTHKEY"])
    node_id = os.environ["RAY_TPU_NODE_ID"]
    worker_id = bytes.fromhex(os.environ["RAY_TPU_WORKER_ID"])

    w = global_worker
    w.mode = "worker"
    w.node_id = node_id
    w.worker_id = worker_id
    from ray_tpu._private import object_transfer

    object_transfer.configure(authkey)  # cross-node pulls (SURVEY §3.3)
    from multiprocessing import AuthenticationError

    try:
        client = CoreClient(address, authkey, worker_id=worker_id, node_id=node_id)
        client._exec_queue = queue.Queue()
        w.client = client
    except (OSError, EOFError, AuthenticationError):
        # our head died while we were booting (connect refused / reset) or
        # we're a straggler from a killed session whose port got reused
        # (authkey mismatch): exit quietly — a traceback on the inherited
        # stderr reads like a live-session failure
        os._exit(0)

    # materialize package URIs (working_dir chdir / py_modules sys.path)
    # BEFORE registering: a persistently failing package then dies
    # pre-registration, which is what the spawn-failure circuit breaker
    # counts — registering first would reset the breaker every respawn
    # and loop forever (the same pre-registration invariant the pip
    # bootstrap shim keeps by exiting 77 before exec)
    try:
        from ray_tpu._private.runtime_env_packaging import (
            apply_packages_in_worker,
        )

        apply_packages_in_worker(client)
    except Exception as e:  # noqa: BLE001
        print(f"runtime_env package setup failed: {e}", file=sys.stderr)
        os._exit(77)

    try:
        client.register_worker()
    except (OSError, EOFError, AuthenticationError):
        os._exit(0)

    # ad-hoc worker profiling: RAY_TPU_SAMPLE_PROFILE=/path/prefix dumps a
    # sampled stack report to <prefix>-<pid>.txt at exit
    _profiler = None
    _profile_prefix = os.environ.get("RAY_TPU_SAMPLE_PROFILE")
    if _profile_prefix:
        from ray_tpu._private.sampling_profiler import SamplingProfiler

        _profiler = SamplingProfiler().start()

        import atexit

        def _dump_profile():
            _profiler.stop()
            try:
                with open(f"{_profile_prefix}-{os.getpid()}.txt", "w") as f:
                    f.write(_profiler.report_text())
            except OSError:
                pass

        atexit.register(_dump_profile)

    # app metrics recorded in this worker flow to the head's /metrics and
    # its TSDB; the push cadence follows RAY_TPU_METRICS_PUSH_S so the
    # head's sample grid, origin-expiry window, and this pusher agree
    from ray_tpu.util.metrics import MetricsPusher

    _metrics_pusher = MetricsPusher(
        client.send, origin=worker_id.hex(),
        closed_fn=lambda: client.closed).start()

    # flight-recorder events ship to the head's event table; the pusher
    # also rewrites this worker's crash-dump file each cycle, so a
    # SIGKILL'd worker leaves its last-flushed ring in the log dir
    from ray_tpu._private import events as events_mod

    _events_dump = None
    _session_dir = os.environ.get("RAY_TPU_SESSION_DIR")
    if _session_dir:
        _events_dump = os.path.join(
            _session_dir, "logs", f"events-worker-{worker_id.hex()}.jsonl")
    _events_pusher = events_mod.EventsPusher(
        client.send, origin=worker_id.hex(), dump_path=_events_dump,
        closed_fn=lambda: client.closed).start()

    # the always-on flamegraph plane: low-duty-cycle stack bursts ship to
    # the head's ProfileStore over this same control connection
    from ray_tpu._private import sampling_profiler as _sp

    _cont_profiler = None
    if _sp.continuous_enabled():
        _cont_profiler = _sp.ContinuousProfiler(
            worker_id.hex(), send_fn=client.send,
            closed_fn=lambda: client.closed).start()

    # Threaded/async actor support: with max_concurrency > 1 the head
    # pipelines up to N methods at us; a BoundedExecutor-analog pool runs
    # them concurrently (creation always runs inline, before any method).
    # Declared concurrency groups each get their OWN bounded pool
    # (ConcurrencyGroupManager<BoundedExecutor> analog) — and force the
    # default lane through a pool too, even at max_concurrency=1:
    # executing the default group inline on this loop thread would stop
    # message draining and starve the named groups it exists to protect.
    max_concurrency = int(os.environ.get("RAY_TPU_MAX_CONCURRENCY", "1"))
    group_caps = _concurrency_group_caps()
    pool = None
    group_pools: Dict[str, Any] = {}
    if max_concurrency > 1 or group_caps:
        from concurrent.futures import ThreadPoolExecutor

        # Threads are created lazily; async methods release their thread as
        # soon as the coroutine is scheduled, so the pool only fills when
        # the user runs that many *sync* methods concurrently.
        pool = ThreadPoolExecutor(
            max_workers=max_concurrency, thread_name_prefix="actor-exec"
        )
        for gname, cap in group_caps.items():
            # one pool per group: FIFO within the group (a single executor
            # queue), non-interfering across groups (disjoint threads)
            group_pools[gname] = ThreadPoolExecutor(
                max_workers=max(1, cap), thread_name_prefix=f"cg-{gname}"
            )

    client._cancel_handler = _on_cancel_message

    def _on_reclaim_message(msg):
        """The head reclaims this worker's UNSTARTED pipelined tasks while
        the current task is blocked in a get: a pipelined task whose output
        the blocked task is waiting on would otherwise deadlock behind it
        in this FIFO queue.  Drain execute messages out of the local queue
        and report their ids; the head requeues exactly those (any message
        the main loop already claimed simply runs here, unreported)."""
        returned = []
        keep = []
        while True:
            try:
                m = client._exec_queue.get_nowait()
            except queue.Empty:
                break
            spec = m.get("spec") or {}
            if m.get("type") == "execute" and spec.get("actor_id") is None:
                returned.append(spec["task_id"])
            else:
                keep.append(m)
        for m in keep:
            client._exec_queue.put(m)
        client.send({"type": "pipeline_returned", "task_ids": returned})

    client._reclaim_handler = _on_reclaim_message

    def _on_profile_message(msg):
        # dashboard on-demand profiling (profile_manager.py analog): sample
        # this process for the requested window, report back to the head
        from ray_tpu._private.sampling_profiler import profile_for

        report = profile_for(float(msg.get("duration", 3.0)),
                             top=int(msg.get("top", 40)))
        client.send({"type": "profile_result", "token": msg.get("token"),
                     "report": report})

    client._profile_handler = _on_profile_message
    while True:
        try:
            msg = client._exec_queue.get()
            if msg["type"] == "exit":
                break
            if msg["type"] == "execute":
                spec = msg["spec"]
                if (
                    pool is not None
                    and spec.get("actor_id") is not None
                    and not spec.get("is_actor_creation")
                ):
                    # route to the method's concurrency group's pool;
                    # unknown/absent group -> default pool
                    target = group_pools.get(
                        spec.get("concurrency_group"), pool)
                    target.submit(_execute_task, msg)
                else:
                    _execute_task(msg)
        except KeyboardInterrupt:
            # a cancel's interrupt_main landed outside user code — either
            # between tasks (harmless) or in the tiny window between the
            # user code finishing and task_done going out.  In the latter
            # case the head still thinks the task is running: send the
            # report it was owed so dispatch bookkeeping stays in sync.
            spec = _main_exec.get("spec")
            _main_exec["spec"] = None
            _main_exec["tid"] = None
            if spec is not None:
                from ray_tpu.exceptions import TaskCancelledError

                try:
                    _seal_and_report(
                        w, spec,
                        [TaskCancelledError("task was cancelled")]
                        * spec["num_returns"],
                        True, "TaskCancelledError: cancelled", time.time())
                except Exception:
                    pass
            continue
    if pool is not None:
        pool.shutdown(wait=False)
    for gp in group_pools.values():
        gp.shutdown(wait=False)
    if _profiler is not None:
        _dump_profile()  # os._exit skips atexit
    if _cont_profiler is not None:
        _cont_profiler.stop()  # final profile ship before the hard exit
    _events_pusher.stop()  # final ship + crash-dump before the hard exit
    client.close()
    os._exit(0)


if __name__ == "__main__":
    # Delegate to the canonical module so classes defined here are not
    # duplicated under the __main__ module name (placeholder identity).
    from ray_tpu._private.worker import main as _canonical_main

    _canonical_main()
