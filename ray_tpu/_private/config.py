"""Flag/config system for the runtime.

TPU-native analog of the reference's ``RAY_CONFIG(type, name, default)`` macro
table (``src/ray/common/ray_config_def.h:22-728`` materialized as the
``RayConfig`` singleton in ``src/ray/common/ray_config.h``).  Every flag is
overridable with a ``RAY_TPU_<NAME>`` environment variable, mirroring the
reference's ``RAY_<name>`` env override path.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any


def _env(name: str, default: Any, typ: type) -> Any:
    raw = os.environ.get(f"RAY_TPU_{name.upper()}")
    if raw is None:
        return default
    if typ is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return typ(raw)


@dataclasses.dataclass
class Config:
    # -- object store ------------------------------------------------------
    # Objects at or below this size are carried inline in RPC messages
    # (analog of the reference's in-process memory store for small/direct
    # returns, src/ray/core_worker/store_provider/memory_store/).
    max_direct_call_object_size: int = 100 * 1024
    # Object store capacity (bytes); analog of plasma's arena size.  0 =
    # auto: a fraction of system RAM bounded by the shm mount (the
    # reference's default_object_store_memory sizing) — checkpoint-sized
    # multi-GiB values must fit the arena to take its single-pass write +
    # page-recycling path instead of a fresh per-object file.
    object_store_memory: int = 0
    # Auto sizing: this fraction of total RAM (reference
    # ray_constants.DEFAULT_OBJECT_STORE_MEMORY_PROPORTION).
    object_store_memory_fraction: float = 0.3
    # Task specs retained for object reconstruction (lineage); analog of
    # the reference's max_lineage_bytes bound (task_manager.h:94).
    max_lineage_entries: int = 10_000
    # Host memory fraction above which the OOM killer fires (reference
    # memory_usage_threshold, memory_monitor.h:52); refresh <= 0 disables.
    memory_usage_threshold: float = 0.95
    memory_monitor_refresh_ms: int = 2000
    # Prefix for named shared-memory segments.
    shm_prefix: str = "rtpu"

    # -- scheduler ---------------------------------------------------------
    # Pack nodes until utilization crosses this, then prefer spreading
    # (reference HybridSchedulingPolicy spread_threshold,
    # ray_config_def.h scheduler_spread_threshold).
    scheduler_spread_threshold: float = 0.5
    # Max workers a node will keep warm beyond its CPU count.
    # Simultaneous worker spawns per runtime-env key (the reference's
    # maximum_startup_concurrency role): python boots are expensive on
    # small hosts, so starts are staggered.
    maximum_startup_concurrency: int = 2
    # Plain workers forked at head start (WorkerPool prestart,
    # num_prestart_python_workers analog); -1 = min(num_cpus, 4).  Booting
    # them while the session is idle matters: under load, forked
    # interpreters are starved and the pool never ramps.
    num_prestart_workers: int = -1
    # Seconds an idle worker is kept before being reaped.
    idle_worker_killing_time_threshold_s: float = 300.0
    # Extra actor method calls pushed to a worker beyond max_concurrency so
    # its local queue is never empty between completions (the reference's
    # pipelined actor submitter window, direct_actor_task_submitter.h:67).
    # On a small host this converts one context switch per call into one
    # per burst.
    actor_pipeline_depth: int = 8
    # Same idea for plain tasks: follow-on tasks with an identical resource
    # shape ride to a busy worker's local queue ahead of completion (the
    # reference's worker-lease reuse, direct_task_transport.cc:174); they
    # hold no resources until promoted at the predecessor's completion.
    task_pipeline_depth: int = 8
    # Agent liveness probing (GcsHealthCheckManager analog): ping period
    # and the silence window after which a node is declared dead.
    health_check_period_s: float = 2.0
    health_check_timeout_s: float = 15.0

    # -- fault tolerance ---------------------------------------------------
    task_max_retries: int = 3
    actor_max_restarts: int = 0
    # -- timeouts ----------------------------------------------------------
    get_timeout_warning_s: float = 60.0
    worker_register_timeout_s: float = 30.0

    # -- logging -----------------------------------------------------------
    log_to_driver: bool = True

    def __post_init__(self) -> None:
        for f in dataclasses.fields(self):
            setattr(self, f.name, _env(f.name, getattr(self, f.name), f.type_ if hasattr(f, "type_") else type(getattr(self, f.name))))


_config: Config | None = None


def get_config() -> Config:
    global _config
    if _config is None:
        _config = Config()
    return _config


def resolve_object_store_memory(cfg: Config | None = None) -> int:
    """The effective object-store capacity: the configured value, or (at 0)
    ``object_store_memory_fraction`` of system RAM clamped to [2 GiB, 80% of
    the shm mount].  The shm bound matters because the arena file lives
    there — a capacity past the mount would let puts fail with ENOSPC
    mid-write instead of falling back cleanly at allocation time."""
    cfg = cfg or get_config()
    if cfg.object_store_memory:
        return int(cfg.object_store_memory)
    try:
        total = os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
    except (ValueError, OSError, AttributeError):
        return 2 * 1024**3
    # floor BEFORE the shm clamp: the clamp is the ENOSPC protection and
    # must win on small shm mounts (e.g. docker's 64 MB default), or the
    # arena outgrows its tmpfs and puts die with SIGBUS mid-write
    want = max(2 * 1024**3, int(total * cfg.object_store_memory_fraction))
    try:
        from ray_tpu._private.shm import shm_dir

        st = os.statvfs(shm_dir())
        # clamp to FREE space, not mount size: tmpfs pages are allocated
        # lazily, so an arena sized past what's actually available dies
        # with SIGBUS/ENOSPC mid-write once puts catch up with it
        want = min(want, int(st.f_frsize * st.f_bavail * 0.8))
    except OSError:
        pass
    return want
