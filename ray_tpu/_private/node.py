"""Head process: raylet + GCS + object directory in one event-driven server.

This fuses the roles the reference splits across processes, keeping the same
seams so they can be split later:

- connection fan-in + message dispatch  <-> raylet ``NodeManager`` gRPC
  service (``src/ray/raylet/node_manager.h:144``)
- ``Scheduler``                          <-> ``ClusterTaskManager`` /
  ``LocalTaskManager`` (``src/ray/raylet/scheduling/cluster_task_manager.h:41``,
  ``local_task_manager.h:58``) with a hybrid pack/spread policy
  (``policy/hybrid_scheduling_policy.h:48``)
- ``NodeState`` resource accounting      <-> ``ClusterResourceManager`` /
  ``LocalResourceManager`` with **TPU as a predefined resource** next to CPU
  (the reference's scheduling_ids.h vocabulary extended per SURVEY §2.1)
- worker pool + dedicated actor workers  <-> ``WorkerPool``
  (``src/ray/raylet/worker_pool.h:156``)
- actor restart FSM                      <-> ``GcsActorManager``
  (``gcs_actor_manager.h:270``)
- placement-group bundle reservation     <-> ``GcsPlacementGroupManager`` +
  bundle policies (``bundle_scheduling_policy.h:82-106``)
- get/wait request parking               <-> raylet ``WaitManager`` +
  plasma ``GetRequestQueue``

Multiple ``NodeState``s in one head process emulate a multi-node cluster —
the same trick as the reference's in-process multi-raylet test Cluster
(``python/ray/cluster_utils.py:99``).
"""

from __future__ import annotations

import json
import os
import pickle
import signal
import socket
import subprocess
import sys
import threading
import time
import traceback
from collections import deque
from dataclasses import dataclass, field
from multiprocessing.connection import Connection, Listener
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import events as events_mod
from ray_tpu._private import logging_utils, wire
from ray_tpu._private.config import get_config
from ray_tpu._private.locks import make_lock
from ray_tpu._private.sharding import ShardSet
from ray_tpu._private.gcs import (
    ActorInfo,
    GcsTables,
    NodeInfo,
    PlacementGroupInfo,
    TaskInfo,
)
from ray_tpu._private.object_store import ObjectLocation, ObjectRegistry

logger = logging_utils.get_logger(__name__)

# Resource names (scheduling_ids.h predefined resources, plus TPU).
CPU = "CPU"
TPU = "TPU"
MEMORY = "memory"

# Lazy scheduler metric singletons (registered on first dispatch so a head
# that never runs a task registers nothing).
_SCHED_METRICS = None
# Dispatch EVENTS are sampled 1:N (Dapper-style bounded overhead: the
# latency histogram records every task; the event trail records the 1st,
# N+1th, ... dispatch plus every TPU dispatch).  The emit rides the head's
# reader thread — the task hot path — so it must stay amortized-cheap.
_DISPATCH_EVENT_SAMPLE = max(1, int(os.environ.get(
    "RAY_TPU_EVENTS_DISPATCH_SAMPLE", "8")))


def _sched_metrics():
    global _SCHED_METRICS
    if _SCHED_METRICS is None:
        from ray_tpu.util.metrics import Gauge, Histogram

        _SCHED_METRICS = {
            "dispatch_latency": Histogram(
                "ray_tpu_sched_dispatch_latency_s",
                "task submit -> worker dispatch latency (s)",
                boundaries=[0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5],
            ),
            "queue_depth": Gauge(
                "ray_tpu_sched_queue_depth",
                "tasks pending cluster-wide (not yet staged on a node)",
            ),
        }
    return _SCHED_METRICS


def _worker_pythonpath(existing: str) -> str:
    """Workers see the driver's import universe: the package root plus every
    directory on the driver's sys.path (the reference achieves this through
    runtime-env/working-dir propagation) — functions pickled by reference
    then resolve on the worker side."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    parts = [pkg_root]
    for p in sys.path:
        if p == "":  # interactive/-c drivers resolve imports from cwd
            p = os.getcwd()
        if p not in parts and os.path.exists(p):  # dirs and zip/egg entries
            parts.append(p)
    if existing:
        parts.append(existing)
    return os.pathsep.join(parts)


def _runtime_env_key(runtime_env: Optional[dict]) -> Optional[str]:
    """Stable identity of a runtime_env — pooled workers are keyed by it so a
    task only ever reuses a worker spawned with the same environment (the
    reference's dedicated-worker-per-runtime-env rule,
    ``src/ray/raylet/worker_pool.h:156``)."""
    if not runtime_env:
        return None
    import json

    return json.dumps(runtime_env, sort_keys=True)


def _apply_runtime_env(env: Dict[str, str], runtime_env: Optional[dict]) -> Optional[str]:
    """Fold env_vars into a worker's spawn env; returns the cwd override.

    Package URIs (``gcs://pkg-…`` working_dir / py_modules, uploaded by
    the driver) can't chdir at spawn — the worker materializes them
    itself from ``RAY_TPU_RUNTIME_ENV`` right after it registers
    (``runtime_env_packaging.apply_packages_in_worker``), which works
    identically for head-local and agent-spawned remote workers."""
    if not runtime_env:
        return None
    from ray_tpu._private.runtime_env_packaging import is_package_uri

    env.update(runtime_env.get("env_vars") or {})
    wd = runtime_env.get("working_dir")
    if is_package_uri(wd) or runtime_env.get("py_modules"):
        env["RAY_TPU_RUNTIME_ENV"] = json.dumps({
            "working_dir": wd if is_package_uri(wd) else None,
            "py_modules": runtime_env.get("py_modules"),
        })
    return wd if wd is not None and not is_package_uri(wd) else None


def _worker_argv(runtime_env: Optional[dict]) -> List[str]:
    from ray_tpu._private.runtime_env_setup import worker_argv

    return worker_argv((runtime_env or {}).get("pip"),
                       (runtime_env or {}).get("conda"))


def _set_child_subreaper() -> bool:
    """PR_SET_CHILD_SUBREAPER: forkserver-spawned workers (and any orphan
    a dying worker leaves behind) reparent to THIS process instead of pid
    1, so the reaper loop can waitpid them — the fix for zombie
    accumulation when the head runs as a container's pid 1."""
    try:
        import ctypes

        libc = ctypes.CDLL(None, use_errno=True)
        return libc.prctl(36, 1, 0, 0, 0) == 0  # PR_SET_CHILD_SUBREAPER
    except Exception:
        return False


class _ForkedProc:
    """Popen-compatible handle for a forkserver-spawned worker.  The
    worker is not our direct child (double fork) but reparents to us via
    the subreaper, so waitpid works; without subreaper support, liveness
    falls back to signal 0."""

    def __init__(self, pid: int):
        self.pid = pid
        self.returncode: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        try:
            done, status = os.waitpid(self.pid, os.WNOHANG)
            if done == self.pid:
                self.returncode = os.waitstatus_to_exitcode(status)
        except ChildProcessError:
            try:
                os.kill(self.pid, 0)
            except ProcessLookupError:
                self.returncode = -1
        return self.returncode

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() >= deadline:
                raise subprocess.TimeoutExpired("forked-worker", timeout)
            time.sleep(0.02)
        return self.returncode

    def _signal(self, sig: int) -> None:
        if self.returncode is None:
            try:
                os.kill(self.pid, sig)
            except (ProcessLookupError, PermissionError):
                pass

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        self._signal(signal.SIGKILL)


class _ForkServerClient:
    """Manages the template process and requests spawns from it."""

    def __init__(self, session_dir: str):
        self._sock_path = os.path.join(session_dir, "forkserver.sock")
        self._proc: Optional[subprocess.Popen] = None
        self._lock = make_lock("node.forkserver")
        self._broken = False

    @property
    def pid(self) -> Optional[int]:
        return self._proc.pid if self._proc is not None else None

    def _ensure(self) -> bool:
        if self._broken:
            return False
        if self._proc is not None and self._proc.poll() is None:
            return True
        env = dict(os.environ)
        env["PYTHONPATH"] = _worker_pythonpath(env.get("PYTHONPATH", ""))
        try:
            self._proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.forkserver",
                 self._sock_path],
                env=env,
            )
        except OSError:
            self._broken = True
            return False
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if self._proc.poll() is not None:
                self._broken = True
                return False
            s = socket.socket(socket.AF_UNIX)
            try:
                s.connect(self._sock_path)
                s.close()
                return True
            except OSError:
                s.close()
                time.sleep(0.05)
        self._broken = True
        return False

    def prewarm(self) -> None:
        with self._lock:
            self._ensure()

    def spawn(self, env: Dict[str, str], cwd: Optional[str]) -> Optional[_ForkedProc]:
        """Fork a worker from the warm template; None -> caller should
        fall back to a classic Popen.  Callers may hold head.lock, so the
        per-request timeout stays short — a wedged template degrades to
        Popen spawns instead of freezing the control plane."""
        with self._lock:
            if not self._ensure():
                return None
            try:
                s = socket.socket(socket.AF_UNIX)
                s.settimeout(10)
                # the lock IS the forkserver protocol serializer: one
                # request/response round trip per holder, by design
                s.connect(self._sock_path)  # raylint: disable=R4
                s.sendall((json.dumps({"env": env, "cwd": cwd}) + "\n").encode())  # raylint: disable=R4
                data = b""
                while not data.endswith(b"\n"):
                    chunk = s.recv(1 << 16)  # raylint: disable=R4
                    if not chunk:
                        break
                    data += chunk
                s.close()
                return _ForkedProc(int(json.loads(data)["pid"]))
            except (OSError, ValueError, KeyError):
                # template wedged: drop it; next spawn restarts it
                try:
                    self._proc.kill()
                except Exception:
                    pass
                self._proc = None
                return None

    def close(self) -> None:
        if self._proc is not None:
            try:
                self._proc.kill()
            except Exception:
                pass


def _fits(req: Dict[str, float], avail: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in req.items())


def _acquire(req: Dict[str, float], avail: Dict[str, float]) -> None:
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) - v


def _release(req: Dict[str, float], avail: Dict[str, float]) -> None:
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) + v


@dataclass(eq=False)  # identity semantics: handles live in sets/lists
class WorkerHandle:
    worker_id: bytes
    node_id: str
    proc: Optional[subprocess.Popen] = None
    conn: Optional[Connection] = None
    state: str = "starting"  # starting/idle/busy/dead
    is_actor_worker: bool = False
    actor_id: Optional[bytes] = None
    current_task: Optional[dict] = None
    send_lock: threading.Lock = field(default_factory=threading.Lock)
    # Nested/concurrent ray.get depth: CPUs are released on 0->1 and
    # reacquired on 1->0 (threaded actors can block several methods at once).
    block_depth: int = 0
    runtime_env_key: Optional[str] = None
    # wall time this worker last became idle (idle-pool reaping)
    idle_since: float = 0.0
    # same-shape tasks sent ahead of completion (lease-reuse pipelining);
    # they hold no resources until promoted in _on_task_done
    pipeline: deque = field(default_factory=deque)
    # messages queued under the node lock, written to the pipe outside it
    # by Node._flush_sends — pickling+write syscalls must not extend lock
    # hold times (they were the head's main source of lock contention)
    outbox: deque = field(default_factory=deque)

    def send(self, msg: dict) -> None:
        with self.send_lock:
            self.conn.send(msg)


@dataclass
class NodeState:
    node_id: str
    total: Dict[str, float]
    available: Dict[str, float]
    tpu_free: List[int]
    env: Dict[str, str] = field(default_factory=dict)
    idle: List[WorkerHandle] = field(default_factory=list)
    starting: int = 0
    # in-flight spawns per runtime_env key (None = plain workers)
    starting_by_key: Dict[Optional[str], int] = field(default_factory=dict)
    # consecutive pre-registration deaths per runtime_env key — a worker
    # that cannot boot (bad env) must surface an error, not hang the task
    spawn_failures: Dict[Optional[str], int] = field(default_factory=dict)
    # tasks whose resources are held, waiting for an idle worker.  Lives
    # in the node's dispatch-shard key space: mutations take shard.lock
    # (nested under the head lock where resource accounting requires it)
    ready_queue: deque = field(default_factory=deque)
    shard: Any = None
    alive: bool = True
    # Real remote node (joined via node_agent): control connection to the
    # agent and the address of its object server.  None/"" = emulated or
    # head-local node.
    agent_conn: Optional[Connection] = None
    agent_send_lock: Optional[threading.Lock] = None
    fetch_addr: Optional[tuple] = None
    # failure domain: hosts of one TPU slice share a slice_id and are
    # provisioned/terminated/replaced as one unit (SURVEY §7 hard-part 3)
    slice_id: Optional[str] = None
    # the node's P2P syncer listener (mesh directory entry); None for
    # emulated/head-local nodes and agents with RAY_TPU_SYNCER=0
    syncer_addr: Optional[tuple] = None
    # health checking (GcsHealthCheckManager analog)
    last_heartbeat: float = field(default_factory=time.time)
    last_ping: float = 0.0
    # live host utilization from the agent's last pong (reporter_agent
    # analog); head-local nodes compute theirs at query time
    host_stats: Optional[Dict[str, float]] = None

    def agent_send(self, msg: dict) -> None:
        # read once: the death path nulls agent_conn concurrently, and an
        # AttributeError mid-send would escape callers expecting OSError
        conn = self.agent_conn
        if conn is None:
            raise OSError("node has no agent connection")
        with self.agent_send_lock:
            conn.send(msg)

    def utilization(self) -> float:
        fracs = []
        for k, tot in self.total.items():
            if tot > 0:
                fracs.append(1.0 - self.available.get(k, 0.0) / tot)
        return max(fracs) if fracs else 0.0


@dataclass
class ActorRuntime:
    info: ActorInfo
    # home dispatch shard: queue/inflight/inflight_groups and the
    # dispatch-gating info.state transitions are only touched under
    # shard.lock (hot paths take it alone; head-lock holders nest it)
    shard: Any = None
    worker: Optional[WorkerHandle] = None
    queue: deque = field(default_factory=deque)  # pending method specs
    # in-flight method specs by task id; up to max_concurrency of them
    # (threaded/async actors — OutOfOrderActorSchedulingQueue analog)
    inflight: Dict[bytes, dict] = field(default_factory=dict)
    # in-flight count per concurrency group (ConcurrencyGroupManager
    # analog: each named group has its own dispatch window so a saturated
    # default pool cannot starve e.g. health checks)
    inflight_groups: Dict[str, int] = field(default_factory=dict)
    held: Dict[str, float] = field(default_factory=dict)
    tpu_ids: List[int] = field(default_factory=list)
    node_id: Optional[str] = None
    # concurrency_groups pre-serialized for the spawn env (computed
    # outside the node lock at creation; R4 keeps serialization out of
    # locked regions)
    groups_env: Optional[str] = None

    @property
    def max_concurrency(self) -> int:
        return int(self.info.creation_spec.get("max_concurrency") or 1)

    @property
    def concurrency_groups(self) -> Dict[str, int]:
        return self.info.creation_spec.get("concurrency_groups") or {}


@dataclass
class ClientState:
    """One registered driver connection (in-process driver, external
    driver, thin client, or a proxied tenant driver).  The head attributes
    everything the connection creates — actors, sealed objects, handle
    pins — to its ``job_id``/``namespace`` so a disconnect can release
    exactly what it owned (reference ``GcsJobManager`` + the proxier's
    per-connection ``SpecificServer`` ownership)."""

    job_id: str
    namespace: str
    conn: Any
    pid: Optional[int] = None
    proxied: bool = False
    connected_at: float = field(default_factory=time.time)
    # oids whose head-side entry holds an initial count on this client's
    # behalf (puts, task/actor returns) — the client sends ONE remove_ref
    # when its last local handle dies; if it never can (SIGKILL), the
    # disconnect reap sends it instead
    owned: set = field(default_factory=set)
    # oids pinned via announced add_ref (deserialized borrows): oid -> n
    pinned: Dict[bytes, int] = field(default_factory=dict)


@dataclass
class BundleRuntime:
    node_id: str
    reserved: Dict[str, float]
    available: Dict[str, float]
    # Set when the owning placement group is removed: releases of resources
    # still held by in-flight tasks then go back to the node, not the bundle.
    detached: bool = False


@dataclass
class PGRuntime:
    info: PlacementGroupInfo
    bundles: List[BundleRuntime] = field(default_factory=list)
    ready_oid: Optional[bytes] = None


def _placement_shape(spec: dict):
    """Hashable (resources, strategy) placement identity: two specs with
    the same shape place identically, so one failure covers both within a
    scheduling pass."""
    strat = spec.get("scheduling_strategy")
    skey = None
    if isinstance(strat, dict):
        skey = (
            strat.get("kind"),
            strat.get("node_id"),
            strat.get("pg_id"),
            strat.get("bundle_index"),
            strat.get("soft"),
        )
    return (tuple(sorted(spec.get("resources", {}).items())), skey)


@dataclass
class _PendingGet:
    req_id: int
    conn_send: Any  # callable(msg)
    oids: List[bytes]
    deadline: Optional[float]
    kind: str = "get"  # get | wait
    num_returns: int = 0
    # oids not yet sealed, maintained by _notify_sealed so a seal touches
    # only the gets waiting on that oid (O(1) instead of rescanning every
    # waiter's full oid list — the old path was O(waiters x oids) per seal)
    unsealed: Any = None  # set[bytes]
    done: bool = False
    # consumer's node ("" = head) — location replies pick the copy nearest
    # to it (location-set pull spreading)
    node_id: str = ""


class Node:
    """The head runtime: owns every table and thread of the session."""

    def __init__(
        self,
        num_cpus: Optional[int] = None,
        num_tpus: Optional[int] = None,
        resources: Optional[Dict[str, float]] = None,
        session_dir: Optional[str] = None,
        gcs_persistence_path: Optional[str] = None,
    ):
        from ray_tpu._private.resource_spec import autodetect_resources

        from ray_tpu._private import shm as shm_mod

        self.cfg = get_config()
        self.session_dir = session_dir or (
            # raylint: disable=R3 (once per session)
            f"/tmp/ray_tpu/session_{os.getpid()}_{os.urandom(4).hex()}"
        )
        os.makedirs(self.session_dir, exist_ok=True)
        self.address = os.path.join(self.session_dir, "raylet.sock")
        self.authkey = os.urandom(16)  # raylint: disable=R3 (one-shot, off the per-task path)

        # Session-scoped shm namespace: sweep segments a SIGKILL'd previous
        # head orphaned, then mark this session alive for the next sweeper.
        self.session_id = os.urandom(4).hex()  # raylint: disable=R3 (one-shot, off the per-task path)
        os.environ[shm_mod._SESSION_ENV] = self.session_id  # workers inherit
        swept = shm_mod.sweep_orphaned_segments()
        if swept:
            logger.info("swept %d orphaned shm segments from dead sessions", swept)
        shm_mod.write_session_marker(self.session_id, os.getpid())

        from ray_tpu._private import usage as _usage

        _usage.reset()  # per-session scope for the usage report

        self.lock = make_lock("node.registry", rlock=True)
        self.cond = threading.Condition(self.lock)
        # Dispatch shards (RAY_TPU_HEAD_SHARDS): actor tasks shard by
        # actor id, leased plain tasks by target node.  Hot actor paths
        # take ONLY their shard lock; anything also holding self.lock
        # takes it FIRST (the witness-verified fixed order).
        self.shards = ShardSet()
        from ray_tpu._private.config import resolve_object_store_memory

        store_capacity = resolve_object_store_memory(self.cfg)
        self.registry = ObjectRegistry(
            capacity_bytes=store_capacity,
            spill_dir=os.path.join(self.session_dir, "spill"),
        )
        # lineage: return oid -> creating task spec, kept while the object
        # lives so a lost copy can be recomputed (TaskManager lineage,
        # reference task_manager.h:87; bounded like max_lineage_bytes).
        # Lineage PINS the spec's argument objects (incl. the big-args
        # payload) — without the pin, args are refcount-deleted at first
        # completion and reconstruction could never re-run the task.
        self.lineage: Dict[bytes, dict] = {}
        self._lineage_pins: Dict[bytes, List[bytes]] = {}  # task_id -> dep oids
        self._lineage_refcnt: Dict[bytes, int] = {}  # task_id -> live entries
        self.registry.on_delete = self._on_object_deleted
        # Native arena store (plasma analog, src/store_core) for this
        # process's objects; per-object files remain the fallback and the
        # worker-side path.
        self.arena = None
        try:
            from ray_tpu._private import native, object_store as ostore_mod

            if native.available():
                arena_path = os.path.join(
                    shm_mod.shm_dir(),
                    f"{self.cfg.shm_prefix}-{self.session_id}-arena",
                )
                # sized to the resolved capacity: the file is sparse
                # (ftruncate), so a large arena costs nothing until used,
                # and multi-GiB values fit its recycled-page write path
                self.arena = native.NativeArena(arena_path, store_capacity)
                ostore_mod.set_owned_arena(self.arena)
                self.registry.arena_delete = self.arena.delete
                logger.info("native arena store at %s (%d MiB)",
                            arena_path, self.arena.capacity >> 20)
        except Exception:
            logger.warning("native arena unavailable:\n%s", traceback.format_exc())
        self.gcs = GcsTables()

        # GCS fault tolerance: with a persistent store, replay the prior
        # head's metadata (GcsInitData analog) and flush periodically
        self.gcs_store = None
        persist = gcs_persistence_path or os.environ.get("RAY_TPU_GCS_PERSISTENCE")
        if persist:
            from ray_tpu._private.gcs_storage import SqliteStoreClient

            existed = os.path.exists(persist)
            self.gcs_store = SqliteStoreClient(persist)
            if existed:
                self.gcs.replay(self.gcs_store)
                logger.info("replayed GCS state from %s (%d kv namespaces, "
                            "%d historical actors)", persist,
                            len(self.gcs.kv), len(self.gcs.actors))

        self.nodes: Dict[str, NodeState] = {}
        # P2P mesh bookkeeping: highest snapshot version folded per node
        # (version-gated merge at the head too), pruned on node removal
        self._syncer_versions: Dict[str, int] = {}
        # slices being terminated ON PURPOSE (slice-atomic replacement /
        # idle scale-down): their member deaths are not "degraded".
        # Self-cleaning: the last member's removal discards the mark.
        self._draining_slices: set = set()
        self.actors: Dict[bytes, ActorRuntime] = {}
        self.pgs: Dict[bytes, PGRuntime] = {}
        self.pending_tasks: deque = deque()
        # Resource-starved backlog, keyed by placement shape (the
        # reference queues per scheduling class for exactly this reason,
        # cluster_task_manager.h): once a shape fails to place, its
        # tasks wait HERE and a scheduler pass costs O(shapes) + O(new
        # arrivals), never O(backlog).  Rescanning a 1M-task deque every
        # 0.2s pass was quadratic — the head spent whole cores walking
        # tasks that could not possibly place.  FIFO holds within a
        # shape (each shape is one deque); cross-shape order is not
        # guaranteed (same as the reference's scheduling classes).
        self._starved: Dict[tuple, deque] = {}
        self.pending_pgs: deque = deque()
        self.running: Dict[bytes, dict] = {}  # task_id -> {spec, worker, node_id, held, tpu_ids}
        self.workers: Dict[bytes, WorkerHandle] = {}
        self.pending_gets: List[_PendingGet] = []
        # oid -> waiters parked on it (seal-driven O(1) get/wait wakeups)
        self._get_waiters: Dict[bytes, List[_PendingGet]] = {}
        # pubsub channels: long-poll publisher/subscriber analog
        # (src/ray/pubsub/ — node_change/error/log + app channels)
        self.subscribers: Dict[str, List[Connection]] = {}
        import queue as _queue

        self._pub_queue: "_queue.Queue" = _queue.Queue()
        self._req_counter = 0
        self._shutdown = False
        self._head_node_id: str
        # Scheduler wakeup coalescing: N notifications during one pass
        # collapse into a single follow-up pass (the flag survives the
        # notify, so a wake that lands mid-pass is never lost).  The loop
        # also self-polls every 0.2s, so a missed wake costs bounded
        # latency, never a hang.
        self._sched_work = False
        # actors whose next queued method is dep-blocked; a seal retries
        # them inline instead of waking the scheduler (direct actor
        # dispatch stays off the scheduler thread)
        self._dep_blocked_actors: set = set()
        # workers with queued outbox messages awaiting a flush.  Guarded
        # by its own lock (NOT self.lock): execute messages are queued
        # from shard-locked actor dispatch as well as head-locked plain
        # dispatch, and the flush snapshot+clear must be atomic against
        # both.
        self._outbox_pending: set = set()
        self._outbox_lock = make_lock("node.outbox")
        # broadcast fan-out acks: token -> {"event", "ok", "error"}
        self._pull_acks: Dict[str, dict] = {}
        # on-demand worker profiling acks: token -> {"event", "report"}
        self._profile_acks: Dict[str, dict] = {}
        # accepted connections whose reader threads are alive: shutdown
        # force-closes them (close alone never wakes a blocked recv — the
        # leak that accumulated threads across sessions in one process)
        self._live_conns: set = set()
        # dynamic-return yield directory: task_id -> {"attempt": n, "oids":
        # [..]} in yield order (streamed to ObjectRefGenerator consumers;
        # the attempt counter lets a consumer detect a mid-stream retry)
        self._dynamic_yields: Dict[bytes, dict] = {}
        # parked dynamic_yields long-polls: task_id -> [waiter, ...]
        self._dynamic_waiters: Dict[bytes, List[dict]] = {}
        # multi-tenancy: registered driver connections and the job
        # directory.  ``clients`` holds live connections only; ``_jobs``
        # keeps (bounded) per-job metadata — namespace, pid, liveness —
        # for audit rollups and `ray_tpu list tenants` after a driver dies.
        self.clients: Dict[Any, ClientState] = {}
        self._jobs: Dict[str, dict] = {}
        self._job_counter = 0
        # flipped off by ray_tpu.shutdown() so the in-process driver's own
        # disconnect doesn't run a full tenant reap against a dying head
        self._reap_on_disconnect = True

        total, tpus = autodetect_resources(num_cpus, num_tpus, resources)
        self._head_node_id = "node-head"
        self.add_node_state(self._head_node_id, total, tpus)

        self._conn_locks: Dict[int, threading.Lock] = {}
        self._listener = Listener(self.address, family="AF_UNIX", authkey=self.authkey, backlog=64)
        # TCP control plane: real nodes (node_agent) and their workers join
        # here — the gRPC server of the reference's GCS/raylet (SURVEY §5.8).
        host = os.environ.get("RAY_TPU_HOST", "127.0.0.1")
        self._tcp_listener = Listener((host, 0), family="AF_INET",
                                      authkey=self.authkey, backlog=64)
        self.tcp_address: tuple = self._tcp_listener.address
        # Object-transfer plane: every node serves pulls of its local shm
        # segments (ObjectManager analog).
        from ray_tpu._private import object_transfer

        object_transfer.configure(self.authkey)
        self.object_server = object_transfer.ObjectServer(host, self.authkey)
        self.nodes[self._head_node_id].fetch_addr = tuple(self.object_server.addr)
        self.registry.broadcast_unlink = self._broadcast_unlink
        # warm-template worker spawns + orphan reaping: forked workers
        # reparent to this process (subreaper), the reaper loop collects
        # them AND any zombie a dying worker leaves when we're pid 1
        self._subreaper = _set_child_subreaper()
        self._forkserver = (
            None if os.environ.get("RAY_TPU_DISABLE_FORKSERVER")
            else _ForkServerClient(self.session_dir))
        self._zombie_seen: Dict[int, float] = {}
        # bounded: one entry per service thread, joined at shutdown
        self._threads = []  # raylint: disable=R5
        t = threading.Thread(target=self._reaper_loop, name="reaper", daemon=True)
        t.start()
        self._threads.append(t)
        if self._forkserver is not None:
            # boot the template OFF the scheduler path: the first worker
            # spawn must never pay the ~2s template boot under head.lock
            t = threading.Thread(target=self._forkserver.prewarm,
                                 name="forkserver-warm", daemon=True)
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._accept_loop, name="accept", daemon=True)
        t.start()
        self._threads.append(t)
        t = threading.Thread(
            target=self._accept_loop, args=(self._tcp_listener,),
            name="accept-tcp", daemon=True,
        )
        t.start()
        self._threads.append(t)
        t = threading.Thread(target=self._scheduler_loop, name="scheduler", daemon=True)
        t.start()
        self._threads.append(t)
        t = threading.Thread(target=self._timeout_loop, name="timeouts", daemon=True)
        t.start()
        self._threads.append(t)
        t = threading.Thread(target=self._publisher_loop, name="publisher", daemon=True)
        t.start()
        self._threads.append(t)
        t = threading.Thread(target=self._gcs_flush_loop, name="gcs-flush", daemon=True)
        t.start()
        self._threads.append(t)
        if self.cfg.memory_monitor_refresh_ms > 0:
            t = threading.Thread(
                target=self._memory_monitor_loop, name="memory-monitor", daemon=True
            )
            t.start()
            self._threads.append(t)
        # Dashboard + merged worker metrics (DashboardHead analog); port -1
        # disables, 0 picks an ephemeral port.
        from ray_tpu._private.job_manager import JobManager
        from ray_tpu.util import metrics as metrics_mod

        self.job_manager = JobManager(self)
        self.worker_metrics_registry = metrics_mod._Registry()
        # Resource accounting over time: every registry snapshot that
        # reaches the head (worker pushes, node-agent resource samples,
        # the head's own self-sample loop) also folds into a bounded
        # in-memory TSDB, so trends — RSS slopes, store growth, queue
        # climbs — are queryable instead of inferable (util/tsdb.py).
        from ray_tpu.util import tsdb as tsdb_mod

        self.tsdb = tsdb_mod.TimeSeriesStore()
        # Two expiry horizons off the push cadence (stretched if the
        # resource sampler runs slower than the pusher): the LIVE merged
        # registry drops origins after 3 missed pushes (a dead worker
        # must leave /metrics promptly; the next push self-heals a false
        # positive), while the TSDB keeps series 4x longer — history is
        # the thing a transient pusher backoff must not erase, and a
        # dead process's recent trend is exactly what a post-mortem
        # wants to read.
        # RAY_TPU_RESOURCE_SAMPLE_S: unset -> /proc sampling every push
        # tick; > 0 -> that cadence; <= 0 -> disabled (the head honors
        # the same knob the node agents document)
        raw = os.environ.get("RAY_TPU_RESOURCE_SAMPLE_S")
        self._resource_sample_s = (
            None if raw is None else events_mod._float_env(
                "RAY_TPU_RESOURCE_SAMPLE_S", metrics_mod.push_interval_s()))
        base_s = max(metrics_mod.push_interval_s(),
                     self._resource_sample_s or 0.0)
        self._origin_expiry_s = tsdb_mod.ORIGIN_EXPIRY_INTERVALS * base_s
        self._tsdb_expiry_s = 4 * self._origin_expiry_s
        # latest per-entity /proc stats for the top view:
        # worker_id hex (or "head"/"agent:<node>") -> stats dict.
        # _proc_lock guards it — folded by connection-handler threads,
        # rebuilt by the sampler tick, read by top_snapshot.
        self._proc_live: Dict[str, dict] = {}
        self._proc_lock = make_lock("node.proc_live")
        self._tsdb_stop = threading.Event()
        t = threading.Thread(target=self._tsdb_loop, name="tsdb-sampler",
                             daemon=True)
        t.start()
        self._threads.append(t)
        # flight recorder: worker-shipped events fold in here; the head's
        # own emits live in the process-local ring and merge at query time
        self.events = events_mod.EventTable()
        self._events_dumped_seq = 0
        # request traces: span-carrying events (trace source + traced
        # compiled-graph spans) assemble into per-trace span trees here;
        # the head process's own ring is folded lazily at query time
        self.traces = events_mod.TraceTable()
        self._traces_local_seq = 0
        self._traces_fold_lock = make_lock("node.traces_fold")
        self._dispatch_n = 0  # dispatch-event sampling counter
        # continuous profiling plane: every process's ContinuousProfiler
        # batch-ships folded stacks over its existing control connection
        # (profile_report frames); they land here.  The head samples
        # itself straight into the store — no loopback connection.
        from ray_tpu.util.profile_store import ProfileStore

        self.profile_store = ProfileStore()
        self._head_profiler = None
        from ray_tpu._private import sampling_profiler as _sp

        if _sp.continuous_enabled():
            self._head_profiler = _sp.ContinuousProfiler(
                "head", ingest_fn=self.profile_store.ingest,
                closed_fn=lambda: self._shutdown).start()
        # cluster log plane: local capture files (head, local workers,
        # job drivers, tenant drivers) tail into the head's bounded
        # store; node agents ship their workers' files as log_report
        # frames into the same ingest.  Driver streaming rides pubsub
        # on "logs:<job>" channels.
        from ray_tpu._private import log_plane as log_plane_mod
        from ray_tpu.util.log_store import LogStore

        self.log_store = LogStore(emit_fn=events_mod.emit)
        self._log_monitor = None
        self._head_log_handler = None
        if log_plane_mod.enabled():
            self._log_monitor = log_plane_mod.LogMonitor(
                self._head_node_id, ingest_fn=self._ingest_log_report,
                closed_fn=lambda: self._shutdown)
            # the head shares the driver's process and cannot dup2 the
            # user's tty away; its ray_tpu.* logger records mirror into
            # logs/head.log instead
            head_log = os.path.join(self.session_dir, "logs", "head.log")
            self._head_log_handler = log_plane_mod.attach_logger_capture(
                head_log)
            self._log_monitor.register(
                "head", head_log, node=self._head_node_id,
                pid=os.getpid(), src="I")
            self._log_monitor.start()
        # watchdog plane: continuous incremental-doctor + SLO burn-rate
        # evaluation folding into the incident lifecycle; post-mortem
        # bundles land under <session>/incidents/<id>/
        from ray_tpu.util import watchdog as watchdog_mod

        self.watchdog = None
        if watchdog_mod.enabled():
            try:
                self.watchdog = watchdog_mod.Watchdog(self)
                self.watchdog.start()
            except Exception:
                logger.warning("watchdog failed to start:\n%s",
                               traceback.format_exc())
        self.dashboard = None
        dash_port = int(os.environ.get("RAY_TPU_DASHBOARD_PORT", "0"))
        if dash_port >= 0:
            try:
                from ray_tpu.dashboard import Dashboard

                self.dashboard = Dashboard(self, host=host, port=dash_port)
                logger.info("dashboard at http://%s:%d", *self.dashboard.address)
            except Exception:
                logger.warning("dashboard failed to start:\n%s", traceback.format_exc())
        # session discovery for `ray_tpu.init(address="auto")` / the CLI
        self._write_session_file()
        # Prestart the plain worker pool up to the CPU count (WorkerPool
        # prestart, reference worker_pool.h:156 num_prestart_python_workers).
        # Boots overlap with early driver work; spawning lazily instead
        # means later parallel load starves the forked interpreters of CPU
        # and the pool never ramps while the cluster is busy.
        with self.lock:
            head_ns = self.nodes[self._head_node_id]
            n_prestart = self.cfg.num_prestart_workers
            if n_prestart < 0:
                n_prestart = max(1, min(int(head_ns.total.get(CPU, 1)), 4))
            for _ in range(n_prestart):
                self._spawn_worker(head_ns)

    def _write_session_file(self) -> None:
        """Discovery record for address="auto" drivers and the CLI (the
        reference's /tmp/ray/ray_current_cluster analog)."""
        import json

        path = "/tmp/ray_tpu/last_session.json"
        os.makedirs(os.path.dirname(path), exist_ok=True)
        host, port = self.tcp_address
        payload = {
            "address": f"tcp://{host}:{port}",
            "authkey": self.authkey.hex(),
            "session_dir": self.session_dir,
            "session_id": self.session_id,
            "pid": os.getpid(),
            "dashboard": list(self.dashboard.address) if self.dashboard else None,
        }
        fd = os.open(path + ".tmp", os.O_CREAT | os.O_TRUNC | os.O_WRONLY, 0o600)
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f)
        os.replace(path + ".tmp", path)

    # ------------------------------------------------------------------
    # topology
    # ------------------------------------------------------------------
    def add_node_state(
        self,
        node_id: str,
        total: Dict[str, float],
        tpu_ids: Optional[List[int]] = None,
        env: Optional[Dict[str, str]] = None,
        slice_id: Optional[str] = None,
    ) -> None:
        with self.lock:
            ns = NodeState(
                node_id=node_id,
                total=dict(total),
                available=dict(total),
                tpu_free=list(tpu_ids or []),
                env=dict(env or {}),
                slice_id=slice_id,
                shard=self.shards.for_node(node_id),
            )
            self.nodes[node_id] = ns
            self.gcs.nodes[node_id] = NodeInfo(node_id=node_id, resources=dict(total),
                                               slice_id=slice_id)
            self._wake_scheduler()
        events_mod.emit("node", "node joined", entity_id=node_id,
                        resources=dict(total), slice_id=slice_id)

    def remove_node_state(self, node_id: str) -> None:
        """Simulate node death (Cluster.remove_node / chaos NodeKiller analog)."""
        slice_state = None  # (slice_id, alive_siblings, gang_size) | None
        with self.lock:
            ns = self.nodes.get(node_id)
            if ns is None or not ns.alive:
                # already removed — this path now has concurrent callers
                # (missed-pong monitor, conn EOF, syncer death rumor /
                # suspect quorum); re-running the body would double-emit
                # 'node removed'/'slice degraded' and re-reconstruct
                return
            ns.alive = False
            ns.agent_conn = None
            self._syncer_versions.pop(node_id, None)
            if node_id in self.gcs.nodes:
                self.gcs.nodes[node_id].alive = False
            if ns.slice_id is not None:
                siblings = [n for n in self.nodes.values()
                            if n.slice_id == ns.slice_id
                            and n.node_id != node_id]
                alive_sib = sum(1 for n in siblings if n.alive)
                if alive_sib == 0:
                    # last member gone: the slice is fully drained/dead;
                    # the draining mark has done its job
                    self._draining_slices.discard(ns.slice_id)
                elif ns.slice_id not in self._draining_slices:
                    # an UNEXPECTED member death leaves the slice degraded
                    # (a deliberate slice-atomic termination marks the
                    # slice draining first and stays silent here)
                    slice_state = (ns.slice_id, alive_sib, len(siblings) + 1)
            # tasks staged on the dead node (resources held, waiting for a
            # worker) go back to the cluster-wide pending queue — their
            # held resources died with the node
            with ns.shard.lock:
                staged = list(ns.ready_queue)
                ns.ready_queue.clear()
            for spec, _tpu_ids, _bundle in staged:
                self.pending_tasks.append(spec)
            victims = [w for w in self.workers.values() if w.node_id == node_id and w.state != "dead"]
        for w in victims:
            try:
                if w.proc:
                    w.proc.kill()
                elif w.conn is not None:
                    # remote worker orphaned by its agent's death: tell it
                    # to exit (we cannot signal a process on another host)
                    w.send({"type": "exit"})
            except Exception:
                pass
            self._on_worker_death(w, reason=f"node {node_id} removed")
        self.publish("node_change", {"node_id": node_id, "alive": False})
        events_mod.emit("node", "node removed", severity="WARNING",
                        entity_id=node_id, staged_tasks=len(staged))
        if slice_state is not None:
            # a slice is ONE failure domain: a dead member wedges any
            # STRICT gang on it — doctor's slice_degraded rule watches
            # for this event without a replacement in flight
            sid, alive_sib, gang = slice_state
            events_mod.emit(
                "node", "slice degraded", severity="ERROR", entity_id=sid,
                dead_node=node_id, alive_members=alive_sib, gang_size=gang)
        self._broadcast_syncer_peers()
        self._reconstruct_lost_objects(node_id)
        with self.lock:
            self._wake_scheduler()

    def _reconstruct_lost_objects(self, node_id: str) -> None:
        """Lineage reconstruction (ObjectRecoveryManager +
        TaskManager-resubmission analog, reference
        ``object_recovery_manager.h:41``): finished objects whose only copy
        lived on the dead node are recomputed by resubmitting their
        creating task; objects with no lineage (ray.put data, actor
        returns, evicted lineage) seal an ObjectLostError instead."""
        from ray_tpu.exceptions import ObjectLostError
        from ray_tpu._private.object_ref import ObjectRef
        from ray_tpu._private.object_store import store_value

        lost = self.registry.mark_node_lost(node_id)
        if not lost:
            return
        resubmitted = set()
        n_rebuilt = 0
        for oid in lost:
            spec = self.lineage.get(oid)
            if spec is None or spec.get("actor_id"):
                err = ObjectLostError(
                    f"object {oid.hex()} lost with node {node_id} and has no "
                    "lineage (ray.put data and actor returns are not "
                    "reconstructable)"
                )
                loc, _ = store_value(ObjectRef(oid), err, is_error=True)
                self.registry.seal(oid, loc, only_if_live=True)
                self._notify_sealed(oid)
                continue
            tid = spec["task_id"]
            if tid in resubmitted:
                continue
            resubmitted.add(tid)
            # a dep whose registry entry is gone (refcount-deleted) can
            # never seal again — the resubmission would wait forever.
            # Seal errors directly: the spec's pins were already released
            # at its first completion, so _seal_error_returns (which
            # releases them again) must not run here.
            if any(not self.registry.contains(d) for d in spec.get("dep_ids", [])):
                err = ObjectLostError(
                    f"cannot reconstruct {oid.hex()}: an argument object "
                    "was already released"
                )
                for rid in spec["return_ids"]:
                    # only live entries, checked atomically inside seal:
                    # resurrecting a refcount-deleted return would leak
                    loc, _ = store_value(ObjectRef(rid), err, is_error=True)
                    self.registry.seal(rid, loc, only_if_live=True)
                    self._notify_sealed(rid)
                continue
            n_rebuilt += 1
            # deps that died in the same event are themselves in `lost` and
            # get resubmitted by this same loop; _deps_ready blocks until
            # they re-seal, so the reconstruction recursion falls out of
            # ordinary scheduling
            copy = dict(spec)
            # the original pins were popped at first completion; re-pin the
            # args for the re-execution (released again when it finishes)
            repin = [d for d in copy.get("dep_ids", []) if self.registry.contains(d)]
            for d in repin:
                self.registry.add_ref(d, reason="task_arg")
            copy["pinned_refs"] = repin
            # an affinity to the dead node would leave the resubmission
            # unschedulable forever; reconstruction may run anywhere
            strat = copy.get("scheduling_strategy")
            if isinstance(strat, dict) and strat.get("node_id") == node_id:
                copy["scheduling_strategy"] = None
            self.submit_task(copy, _resubmit=True)
        if n_rebuilt or len(lost):
            logger.warning(
                "node %s: %d objects lost; resubmitted %d creating tasks",
                node_id, len(lost), n_rebuilt,
            )

    # ------------------------------------------------------------------
    # connection handling
    # ------------------------------------------------------------------
    def _accept_loop(self, listener: Optional[Listener] = None) -> None:
        from multiprocessing import AuthenticationError

        listener = listener or self._listener
        failures = 0
        while not self._shutdown:
            try:
                conn = wire.wrap(listener.accept())
                failures = 0
            except (AuthenticationError, OSError, EOFError):
                # one peer dying mid-handshake (EOF/reset) or failing auth
                # must not kill the listener; only stop when we're shutting
                # down or the listener socket itself is persistently broken
                if self._shutdown:
                    break
                failures += 1
                if failures > 100:
                    logger.error("accept loop: listener persistently failing; exiting")
                    break
                continue
            t = threading.Thread(target=self._reader_loop, args=(conn,), daemon=True)
            t.start()

    def _reader_loop(self, conn: Connection) -> None:
        handle: Optional[WorkerHandle] = None
        agent_node_id: Optional[str] = None
        is_client = False
        with self.lock:
            self._conn_locks[id(conn)] = make_lock("node.conn")
            self._live_conns.add(conn)
        try:
            while not self._shutdown:
                try:
                    msg = conn.recv()
                except (EOFError, OSError, pickle.UnpicklingError):
                    break
                mtype = msg["type"]
                if mtype == "register_worker":
                    handle = self._on_register_worker(conn, msg)
                elif mtype == "register_client":
                    is_client = True  # driver or external client connection
                    self._on_register_client(conn, msg)
                elif mtype == "register_node":
                    agent_node_id = self._on_register_node(conn, msg)
                elif mtype == "worker_exited":
                    self._on_remote_worker_exited(msg)
                elif mtype == "pong":
                    if agent_node_id is not None:
                        with self.lock:
                            ns = self.nodes.get(agent_node_id)
                            if ns is not None:
                                ns.last_heartbeat = time.time()
                                if msg.get("stats"):
                                    ns.host_stats = msg["stats"]
                elif mtype == "object_pulled":
                    holder = self._pull_acks.pop(msg.get("token"), None)
                    if holder is not None:
                        holder["ok"] = bool(msg.get("ok"))
                        holder["error"] = msg.get("error")
                        holder["event"].set()
                elif mtype == "syncer_report":
                    self._on_syncer_report(msg)
                else:
                    self._handle_message(conn, handle, msg)
        finally:
            # release the fd NOW: WorkerHandle/agent references keep the
            # Connection object alive long after EOF, and unclosed accepted
            # conns were the per-session fd leak
            try:
                conn.close()
            except Exception:
                pass
            with self.lock:
                self._live_conns.discard(conn)
                # a disconnected peer's pubsub subscriptions die with it
                for subs in self.subscribers.values():
                    if conn in subs:
                        subs.remove(conn)
            if handle is not None:
                self._on_worker_death(handle, reason="connection closed")
            elif agent_node_id is not None:
                with self.lock:
                    ns = self.nodes.get(agent_node_id)
                    stale = ns is None or ns.agent_conn is not conn
                if stale:
                    # a newer incarnation of this node re-registered while
                    # this connection lingered; don't kill the replacement
                    pass
                else:
                    logger.warning("node %s lost (agent connection closed)", agent_node_id)
                    self.remove_node_state(agent_node_id)
            elif is_client:
                self._on_client_disconnect(conn)

    # ------------------------------------------------------------------
    # driver/tenant connections (multi-tenancy half of GcsJobManager)
    # ------------------------------------------------------------------
    _MAX_JOB_RECORDS = 1024

    def _on_register_client(self, conn: Connection, msg: dict) -> None:
        """A driver registered: assign it a job id, record its namespace,
        and reply with the identity (``get_runtime_context().job_id``).
        Proxied tenant drivers arrive with ``proxied=True`` and the driver
        subprocess's pid — the pid chaos kills and doctor explains."""
        with self.lock:
            self._job_counter += 1
            job_id = f"job-{self._job_counter:04d}"
            namespace = msg.get("namespace") or "default"
            st = ClientState(
                job_id=job_id, namespace=namespace, conn=conn,
                pid=msg.get("pid"), proxied=bool(msg.get("proxied")))
            self.clients[conn] = st
            self._jobs[job_id] = {
                "job_id": job_id, "namespace": namespace, "pid": st.pid,
                "proxied": st.proxied, "alive": True,
                "connected_at": st.connected_at, "job_name": msg.get("job_name"),
            }
            if len(self._jobs) > self._MAX_JOB_RECORDS:
                # bounded directory: retire the oldest DEAD records first
                for jid in [j for j, r in self._jobs.items()
                            if not r["alive"]][:len(self._jobs) // 4]:
                    del self._jobs[jid]
        events_mod.emit(
            "client_proxy",
            f"tenant registered ({'proxied' if st.proxied else 'direct'})",
            severity="DEBUG", entity_id=job_id, namespace=namespace,
            pid=st.pid)
        if msg.get("req_id") is not None:
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": {"job_id": job_id,
                                         "namespace": namespace}})

    def _on_client_disconnect(self, conn: Connection) -> None:
        """A driver connection closed: release everything the job owned.
        Non-detached actors it created are killed, its named entries leave
        the namespace directory, and every object pin it held (initial
        put/return counts + announced borrows) is dropped.  Detached
        actors survive by design (reference Ray Client proxier semantics:
        driver death reaps the SpecificServer and its job's state)."""
        with self.lock:
            st = self.clients.pop(conn, None)
            if st is not None:
                rec = self._jobs.get(st.job_id)
                if rec is not None:
                    rec["alive"] = False
                    rec["disconnected_at"] = time.time()
        if st is None or self._shutdown or not self._reap_on_disconnect:
            return
        with self.gcs.lock:
            owned_actors = [a for a in self.gcs.actors.values()
                            if a.job_id == st.job_id]
        to_kill = [a for a in owned_actors
                   if a.lifetime != "detached" and a.state != "DEAD"]
        detached = sum(1 for a in owned_actors if a.lifetime == "detached")
        if not to_kill and not st.owned and not st.pinned:
            # nothing owned: a clean exit, not an incident (keeps doctor
            # quiet for every CLI session and tidy driver shutdown)
            events_mod.emit(
                "client_proxy", "tenant disconnected", severity="DEBUG",
                entity_id=st.job_id, namespace=st.namespace)
            return
        # the died/reaped event PAIR is the doctor's tenant_killed food:
        # died opens the incident, reaped closes it (a crash between the
        # two leaves an open ERROR — the reap really is wedged then)
        events_mod.emit(
            "client_proxy", "tenant driver died", severity="WARNING",
            entity_id=st.job_id, namespace=st.namespace, pid=st.pid,
            live_actors=len(to_kill))
        for info in to_kill:
            self.kill_actor(info.actor_id)
        released = len(st.owned)
        self.registry.remove_refs(list(st.owned), reason="handle")
        for oid, n in list(st.pinned.items()):
            self.registry.remove_ref(oid, n=n, reason="handle")
            released += 1
        st.owned.clear()
        st.pinned.clear()
        events_mod.emit(
            "client_proxy", "tenant reaped", severity="INFO",
            entity_id=st.job_id, namespace=st.namespace,
            killed_actors=len(to_kill), detached_survivors=detached,
            released_refs=released)
        logger.info(
            "tenant %s (namespace %s) disconnected: reaped %d actors, "
            "released %d pins, %d detached survivors",
            st.job_id, st.namespace, len(to_kill), released, detached)

    def _on_register_node(self, conn: Connection, msg: dict) -> str:
        """A node_agent joined over TCP (the raylet-registers-with-GCS path,
        ``GcsNodeManager`` analog)."""
        node_id = msg["node_id"]
        self.add_node_state(node_id, msg["resources"], msg.get("tpu_ids"),
                            slice_id=msg.get("slice_id"))
        with self.lock:
            ns = self.nodes[node_id]
            ns.agent_conn = conn
            ns.agent_send_lock = self._conn_lock(conn)
            ns.fetch_addr = tuple(msg["fetch_addr"]) if msg.get("fetch_addr") else None
            ns.syncer_addr = tuple(msg["syncer_addr"]) if msg.get("syncer_addr") else None
            self._wake_scheduler()
        logger.info("node %s joined with %s", node_id, msg["resources"])
        self.publish("node_change", {"node_id": node_id, "alive": True,
                                     "resources": msg["resources"]})
        self._broadcast_syncer_peers()
        return node_id

    # ------------------------------------------------------------------
    # P2P resource/health mesh (head side of _private/syncer.py)
    # ------------------------------------------------------------------
    def _broadcast_syncer_peers(self) -> None:
        """Ship the mesh directory to every agent (on membership change).
        The directory is the union of alive syncer-capable nodes; agents
        prune their stores to it."""
        with self.lock:
            peers = {nid: list(ns.syncer_addr)
                     for nid, ns in self.nodes.items()
                     if ns.alive and ns.syncer_addr}
            agents = [ns for ns in self.nodes.values()
                      if ns.alive and ns.agent_conn is not None]
        if not peers:
            return
        for ns in agents:
            try:
                ns.agent_send({"type": "syncer_peers", "peers": peers})
            except (OSError, ValueError):
                pass  # its conn-close path will reap it

    def mark_slice_draining(self, slice_id: str, draining: bool = True) -> None:
        """Deliberate slice-atomic termination in progress: member deaths
        of a draining slice are expected, not 'degraded'.  The mark
        self-clears when the last member is removed."""
        with self.lock:
            if draining:
                self._draining_slices.add(slice_id)
            else:
                self._draining_slices.discard(slice_id)

    def _on_syncer_report(self, msg: dict) -> None:
        """Fold one agent's converged mesh view.

        Version-gated exactly like the agents' own merges: any snapshot
        strictly newer than what the head has folded counts as a
        heartbeat for THAT node (its author was alive at snap ts) — so a
        node whose direct link to the head is broken stays alive and
        fresh through its peers' reports, and the head is no longer the
        sole fan-in for liveness.  Death rumors (connection refused — the
        peer's listener is gone) and suspect quorums (>= SUSPECT_QUORUM
        distinct observers of an unresponsive peer) remove nodes ahead of
        the missed-pong timeout; both are double-checked against the
        head's own recent direct contact so a one-sided partition can't
        kill a node the head still hears from."""
        from ray_tpu._private.syncer import SUSPECT_QUORUM

        now = time.time()
        period = self.cfg.health_check_period_s
        to_remove: Dict[str, Tuple[str, dict]] = {}  # nid -> (why, data);
        # dict, not list: a paused-then-killed node sits in BOTH the
        # deaths and suspects tables — remove it once
        with self.lock:
            for nid, snap in (msg.get("snaps") or {}).items():
                ns = self.nodes.get(nid)
                if ns is None or not ns.alive:
                    continue
                version = int(snap.get("version", 0))
                if version <= self._syncer_versions.get(nid, 0):
                    continue
                self._syncer_versions[nid] = version
                ts = min(float(snap.get("ts", now)), now)
                if ts > ns.last_heartbeat:
                    ns.last_heartbeat = ts
                if snap.get("stats") and ns.agent_conn is not None:
                    ns.host_stats = snap["stats"]
            for nid, death in (msg.get("deaths") or {}).items():
                ns = self.nodes.get(nid)
                if (ns is not None and ns.alive
                        and now - ns.last_heartbeat > period):
                    to_remove[nid] = ("peer-detected node death", {
                        "observer": death.get("by"),
                        "detect_latency_s": round(now - death.get("ts", now), 3),
                    })
            for nid, observers in (msg.get("suspects") or {}).items():
                ns = self.nodes.get(nid)
                if (nid not in to_remove and ns is not None and ns.alive
                        and len(observers) >= SUSPECT_QUORUM
                        and now - ns.last_heartbeat > 2 * period):
                    to_remove[nid] = ("peer-quorum node unresponsive", {
                        "observers": sorted(observers)[:8],
                        "quorum": len(observers),
                    })
        for nid, (why, data) in to_remove.items():
            logger.warning("syncer: removing node %s (%s)", nid, why)
            events_mod.emit("syncer", why, severity="ERROR", entity_id=nid,
                            **data)
            self.remove_node_state(nid)

    def _on_remote_worker_exited(self, msg: dict) -> None:
        wid = bytes.fromhex(msg["worker_id"])
        with self.lock:
            h = self.workers.get(wid)
        if h is not None and h.state != "dead":
            rc = msg.get("returncode")
            extra = f" ({msg['error']})" if msg.get("error") else ""
            self._on_worker_death(
                h, reason=f"exited with code {rc}{extra}"
                          + ("" if h.conn else " before registering")
            )

    def _conn_lock(self, conn: Connection) -> threading.Lock:
        with self.lock:
            return self._conn_locks.setdefault(id(conn), make_lock("node.conn"))

    # execute-message spec subset: everything the worker's executor reads
    # (ray_tpu/_private/worker.py _execute_task/_seal_and_report); head-only
    # bookkeeping fields (pins, retries, placement) stay off the wire
    _EXEC_KEYS = (
        "task_id", "name", "fn_id", "args_blob", "args_oid",
        "is_actor_creation", "actor_id", "method_name",
        "num_returns", "return_ids", "trace_ctx", "dynamic_returns",
        "compiled_graph",
        # tenant identity (runtime context + namespace-scoped lookups in
        # the task) and concurrency-group routing at the worker's pools
        "job_id", "namespace", "concurrency_group",
    )

    def _agent_node_or_head(self, node_id: str) -> str:
        """Normalize a consumer's node for location selection: emulated /
        head-local nodes share the head's shm namespace, so they read as
        the head ("")."""
        ns = self.nodes.get(node_id)
        return node_id if ns is not None and ns.agent_conn is not None else ""

    def _queue_execute(self, w: WorkerHandle, spec: dict,
                       tpu_ids: List[int]) -> None:
        """Queue an execute message for ``w`` (caller holds the lock that
        serializes this worker's dispatch: the node lock for plain tasks,
        the actor's shard lock for actor methods).  The actual pipe write
        happens in _flush_sends, outside every dispatch lock; per-worker
        FIFO order is the outbox append order, which that lock serializes."""
        spec_wire = {k: spec[k] for k in self._EXEC_KEYS
                     if spec.get(k) is not None}
        msg = {"type": "execute", "spec": spec_wire}
        dep_locs = self._dep_locations(spec, self._agent_node_or_head(w.node_id))
        if dep_locs:
            msg["dep_locs"] = dep_locs
        if tpu_ids:
            msg["tpu_ids"] = tpu_ids
        w.outbox.append(msg)
        with self._outbox_lock:
            self._outbox_pending.add(w)

    def _flush_sends(self) -> None:
        """Drain queued worker messages outside the dispatch locks.  Safe
        to call from any thread; concurrent flushers serialize per worker
        on its send_lock, and deque append/popleft are GIL-atomic, so
        per-worker order is preserved.  Send failures surface as worker
        death."""
        with self._outbox_lock:
            if not self._outbox_pending:
                return
            pending = list(self._outbox_pending)
            self._outbox_pending.clear()
        dead: List[WorkerHandle] = []
        for w in pending:
            with w.send_lock:
                while w.outbox:
                    try:
                        msg = w.outbox.popleft()
                    except IndexError:
                        break
                    try:
                        w.conn.send(msg)
                    except (OSError, ValueError, AttributeError):
                        w.outbox.clear()
                        dead.append(w)
                        break
        for w in dead:
            self._on_worker_death(w, reason="send failed")

    def _reply(self, conn: Connection, msg: dict) -> None:
        try:
            with self._conn_lock(conn):
                conn.send(msg)
        except (OSError, ValueError):
            pass

    def _on_put_blob(self, conn: Connection, msg: dict) -> None:
        """Store a thin client's shipped payload head-side and seal it
        (Ray Client put).  Failures reply as errors — they must not tear
        down the connection's serve loop."""
        from ray_tpu._private.object_store import store_blob
        from ray_tpu._private.object_ref import ObjectRef as _Ref

        try:
            loc = store_blob(_Ref(msg["oid"]), msg["blob"],
                             is_error=msg.get("is_error", False))
            client = self.clients.get(conn)
            if client is not None:
                client.owned.add(msg["oid"])
            self.seal_object(msg["oid"], loc, msg.get("contained", []),
                             client=client)
            value = True
        except Exception as e:  # noqa: BLE001 — ANY failure must reply,
            # or the client blocks on its 300 s request timeout
            value = {"error": f"put failed: {type(e).__name__}: {e}"}
        self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                           "value": value})
        self._flush_sends()  # the seal may have unblocked actor dispatches

    def _on_get_blob(self, conn: Connection, msg: dict) -> None:
        """Ship an object's serialized payload to a thin client."""
        from ray_tpu._private.object_store import payload_bytes

        loc = self.registry.wait_sealed_existing(msg["oid"], msg.get("timeout"))
        if loc == "missing":
            reply = {"error": f"unknown or released object {msg['oid'].hex()}"}
        elif loc is None:
            reply = {"timeout": True}
        else:
            try:
                reply = {"blob": payload_bytes(loc), "is_error": loc.is_error}
            except FileNotFoundError:
                # segment spilled/moved between the location read and the
                # attach — one refetch gets the fresh location (same race
                # the fat-client get handles)
                loc = self.registry.wait_sealed_existing(msg["oid"], 5.0)
                try:
                    if loc in (None, "missing"):
                        # the broad arm below turning this into an
                        # error reply IS the handling
                        # raylint: disable=R2
                        raise FileNotFoundError(msg["oid"].hex())
                    reply = {"blob": payload_bytes(loc), "is_error": loc.is_error}
                except (OSError, ValueError) as e:
                    reply = {"error": f"payload read failed: {e}"}
            except (OSError, ValueError) as e:
                reply = {"error": f"payload read failed: {e}"}
        self._reply(conn, {"type": "reply", "req_id": msg["req_id"], "value": reply})

    def _handle_message(self, conn: Connection, worker: Optional[WorkerHandle], msg: dict) -> None:
        mtype = msg["type"]
        # driver connections own what they create: returns/puts/borrows are
        # recorded on the ClientState so a disconnect releases exactly them
        client = self.clients.get(conn) if worker is None else None
        if mtype == "submit_batch":
            # coalesced submissions from one client, in submission order
            for kind, spec in msg["batch"]:
                if client is not None:
                    client.owned.update(spec.get("return_ids", ()))
                if kind == "task":
                    self.submit_task(spec)
                else:
                    self.submit_actor_task(spec)
        elif mtype == "seal":
            if client is not None:
                client.owned.add(msg["oid"])
            self.seal_object(msg["oid"], msg["loc"], msg.get("contained", []),
                             sealer=worker, client=client)
        elif mtype == "get_locations":
            self._on_get_request(conn, msg, worker)
        elif mtype == "wait":
            self._on_wait_request(conn, msg, worker)
        elif mtype == "task_done":
            # returns travel inside the done message (one send per task);
            # seal them first so dependents and parked gets wake in order
            for oid, loc, contained in msg.get("seals", ()):
                self.seal_object(oid, loc, contained, sealer=worker)
            self._on_task_done(worker, msg)
        elif mtype == "create_actor":
            if client is not None:
                client.owned.update(msg["spec"].get("return_ids", ()))
            self.create_actor(msg["spec"])
        elif mtype == "kill_actor":
            self.kill_actor(msg["actor_id"], no_restart=msg.get("no_restart", True))
        elif mtype == "cancel_task":
            try:
                self.cancel_task(msg["oid"], force=msg.get("force", False),
                                 recursive=msg.get("recursive", True))
                err = None
            except ValueError as e:
                err = str(e)
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": err})
        elif mtype == "kv_put":
            self.gcs.kv_put(msg["ns"], msg["key"], msg["value"])
        elif mtype == "kv_get":
            val = self.gcs.kv_get(msg["ns"], msg["key"])
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"], "value": val})
        elif mtype == "blocked":
            self._on_blocked(worker, True)
        elif mtype == "unblocked":
            self._on_blocked(worker, False)
        elif mtype == "pipeline_returned":
            self._on_pipeline_returned(worker, msg)
        elif mtype == "add_ref":
            reason = msg.get("reason", "handle")
            if client is not None and reason == "handle":
                for oid in msg["oids"]:
                    client.pinned[oid] = client.pinned.get(oid, 0) + 1
            # one batch call into the ref index (GIL-released in the
            # native build) instead of a per-oid registry-lock hop
            self.registry.add_refs(msg["oids"], reason=reason)
        elif mtype == "remove_ref":
            reason = msg.get("reason", "handle")
            if client is not None and reason == "handle":
                for oid in msg["oids"]:
                    # one remove covers the client's whole local count:
                    # either the initial owned pin or its announced borrow
                    if oid in client.owned:
                        client.owned.discard(oid)
                    else:
                        n = client.pinned.pop(oid, 1) - 1
                        if n > 0:
                            client.pinned[oid] = n
            self.registry.remove_refs(msg["oids"], reason=reason)
        elif mtype == "create_pg":
            self.create_placement_group(msg["spec"])
        elif mtype == "remove_pg":
            self.remove_placement_group(msg["pg_id"])
        elif mtype == "get_actor_by_name":
            # namespace-scoped: the caller names its namespace explicitly
            # (client resolves from its runtime context); a tenant cannot
            # see another namespace's entries without asking for them
            ns_name = msg.get("namespace") or (
                client.namespace if client is not None else "default")
            with self.lock:
                aid = self.gcs.named_actors.get((ns_name, msg["name"]))
                info = self.actors[aid].info if aid in self.actors else None
                if info is not None and info.state == "DEAD":
                    aid = info = None  # dead actors are not lookup targets
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": (aid, info.creation_spec.get("class_blob_id") if info else None)})
        elif mtype == "state_snapshot":
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"], "value": self._state_snapshot()})
        elif mtype == "subscribe":
            with self.lock:
                subs = self.subscribers.setdefault(msg["channel"], [])
                if conn not in subs:
                    subs.append(conn)
        elif mtype == "unsubscribe":
            with self.lock:
                subs = self.subscribers.get(msg["channel"], [])
                if conn in subs:
                    subs.remove(conn)
        elif mtype == "publish":
            self.publish(msg["channel"], msg["data"])
        elif mtype == "whoami":
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": {"session_id": self.session_id,
                                         "head_node_id": self._head_node_id}})
        elif mtype == "put_blob":
            # off-thread like get_blob: a multi-GB shm write must not stall
            # this connection's reader loop (the client multiplexes
            # concurrent requests over it)
            threading.Thread(
                target=self._on_put_blob, args=(conn, msg), daemon=True
            ).start()
        elif mtype == "get_blob":
            # served off-thread: wait_sealed may block for minutes and this
            # reader loop must keep handling the connection's other traffic
            threading.Thread(
                target=self._on_get_blob, args=(conn, msg), daemon=True
            ).start()
        elif mtype == "submit_job":
            jid = self.job_manager.submit(
                msg["entrypoint"], msg.get("runtime_env"), msg.get("job_id"),
                msg.get("metadata"))
            if self._log_monitor is not None:
                # the job driver's log file joins the tail set, so its
                # lines reach the store/CLI like any worker's
                self._log_monitor.register(
                    f"job-{jid}",
                    os.path.join(self.session_dir, "jobs", f"{jid}.log"),
                    node=self._head_node_id, job=jid)
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"], "value": jid})
        elif mtype == "job_info":
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": self.job_manager.info(msg["job_id"])})
        elif mtype == "job_logs":
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": self.job_manager.logs(msg["job_id"])})
        elif mtype == "stop_job":
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": self.job_manager.stop(msg["job_id"])})
        elif mtype == "list_state":
            rows, total = self._list_state_page(
                msg["what"], msg.get("limit", 1000), msg.get("filters"))
            # total rides next to the rows so clients can surface
            # truncation instead of passing a partial view off as complete
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": rows, "total": total})
        elif mtype == "replica_added":
            self._on_replica_added(worker, msg)
        elif mtype == "dynamic_yield":
            # a dynamic task produced one more return (already sealed — the
            # seal precedes this message on the same connection)
            with self.lock:
                d = self._dynamic_yields.setdefault(
                    msg["task_id"], {"attempt": 0, "oids": []})
                d["oids"].append(msg["oid"])
            self._wake_dynamic_waiters(msg["task_id"])
        elif mtype == "dynamic_yields":
            self._on_dynamic_yields_request(conn, msg)
        elif mtype == "broadcast":
            # fan-out takes seconds for big objects — never on a reader thread
            threading.Thread(
                target=self._on_broadcast, args=(conn, msg), daemon=True
            ).start()
        elif mtype == "profile_result":
            holder = self._profile_acks.pop(msg.get("token"), None)
            if holder is not None:
                holder["report"] = msg.get("report")
                holder["event"].set()
        elif mtype == "metrics_report":
            self.worker_metrics_registry.merge(msg["origin"], msg["metrics"])
            from ray_tpu.util import tsdb as tsdb_mod

            if tsdb_mod.ENABLED:
                self.tsdb.ingest(msg["origin"], msg["metrics"])
                self._fold_resource_report(msg["origin"], msg["metrics"])
        elif mtype == "profile_report":
            self.profile_store.ingest(msg["origin"], msg.get("buckets", []),
                                      msg.get("meta"))
        elif mtype == "list_profiles":
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": self.profile_store.stats()})
        elif mtype == "get_profile":
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": self.profile_store.query(
                                   msg.get("window_s", 300.0),
                                   origin=msg.get("origin"))})
        elif mtype == "profile_diff":
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": self.profile_store.diff(
                                   msg.get("window_a", 600.0),
                                   msg.get("window_b", 60.0),
                                   origin=msg.get("origin"))})
        elif mtype == "profile_ledger":
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": self._profile_ledger(
                                   msg.get("window_s", 300.0),
                                   tasks=msg.get("tasks"))})
        elif mtype == "list_metrics":
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": self.tsdb.list_metrics()})
        elif mtype == "query_metric":
            try:
                value = self.tsdb.query(
                    msg["name"], window_s=msg.get("window_s", 3600.0),
                    step_s=msg.get("step_s", 0.0), tags=msg.get("tags"),
                    agg=msg.get("agg"))
            except ValueError as e:
                value = {"__state_error__": str(e)}
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": value})
        elif mtype == "memory_audit":
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": self._memory_audit(
                                   limit=msg.get("limit", 200))})
        elif mtype == "top_snapshot":
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": self._top_snapshot()})
        elif mtype == "perf_summary":
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": self._perf_summary(
                                   window_s=msg.get("window_s", 1800.0))})
        elif mtype == "events_report":
            self.events.add(msg["origin"], msg["events"])
            self.traces.add(msg["origin"], msg["events"])
        elif mtype == "get_trace":
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": self._get_trace(msg["trace_id"])})
        elif mtype == "log_report":
            self._ingest_log_report(msg["origin"], msg.get("records") or [],
                                    msg.get("streams"))
        elif mtype == "get_log":
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": self._get_log(msg)})
        elif mtype == "tail_log":
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": self.log_store.tail_text(
                                   msg["stream"], msg.get("n", 100),
                                   bool(msg.get("errors")))})
        elif mtype == "get_incident":
            wd = self.watchdog
            if wd is None:
                value = {"__state_error__": "watchdog disabled"}
            else:
                value = wd.incidents.get(msg["incident_id"]) or {
                    "__state_error__":
                        f"no incident {msg['incident_id']!r}"}
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": value})
        elif mtype == "ack_incident":
            wd = self.watchdog
            if wd is None:
                value = {"__state_error__": "watchdog disabled"}
            else:
                value = wd.ack(msg["incident_id"]) or {
                    "__state_error__":
                        f"no open incident {msg['incident_id']!r}"}
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": value})
        elif mtype == "doctor_report":
            # head-side diagnosis: the same incremental path the watchdog
            # tick runs, against head-local tables — the client never
            # pulls the event/task rows over the wire
            try:
                value = self._doctor_report(
                    msg.get("trend_window_s", 1800.0))
            except Exception as e:
                value = {"__state_error__": str(e)}
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": value})
        elif mtype == "debug_dump":
            wd = self.watchdog
            if wd is None:
                value = {"__state_error__": "watchdog disabled"}
            else:
                try:
                    value = {"path": wd.debug_dump(msg.get("label"))}
                except Exception as e:
                    value = {"__state_error__": str(e)}
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": value})
        elif mtype == "summarize_state":
            try:
                value = self._summarize_state(msg["what"])
            except ValueError as e:
                # in-band error marker: a top-level "error" key means a
                # transport failure to the client, not a bad argument
                value = {"__state_error__": str(e)}
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": value})
        else:
            logger.warning("unknown message type %s", mtype)
        # write out any execute messages this message's handling queued
        # (dispatches happen under the node lock; pipe writes here, outside)
        self._flush_sends()

    # ------------------------------------------------------------------
    # workers
    # ------------------------------------------------------------------
    def _spawn_worker_process(
        self,
        ns: NodeState,
        worker_id: bytes,
        runtime_env: Optional[dict],
        extra_env: Optional[Dict[str, str]] = None,
    ) -> subprocess.Popen:
        """Env assembly + Popen shared by pooled and dedicated actor workers.

        User env_vars apply first so harness-critical vars always win (a
        runtime_env can never clobber the worker's ability to boot and
        register); a user PYTHONPATH is merged, not replaced.  Raises
        OSError when the process cannot spawn (e.g. working_dir vanished)."""
        env = dict(os.environ)
        env.update(ns.env)
        cwd = _apply_runtime_env(env, runtime_env)
        env["RAY_TPU_ADDRESS"] = self.address
        env["RAY_TPU_AUTHKEY"] = self.authkey.hex()
        env["RAY_TPU_NODE_ID"] = ns.node_id
        env["RAY_TPU_WORKER_ID"] = worker_id.hex()
        env["RAY_TPU_SESSION_DIR"] = self.session_dir
        env["RAY_TPU_WORKER_LOG"] = os.path.join(
            self.session_dir, "logs", f"worker-{worker_id.hex()}.log")
        if extra_env:
            env.update(extra_env)
        env["PYTHONPATH"] = _worker_pythonpath(env.get("PYTHONPATH", ""))
        # plain workers fork from the warm template (~20ms vs a ~2s cold
        # CPython boot); pip runtime_envs need the venv's interpreter, so
        # they (and any forkserver failure) take the classic Popen path
        if self._forkserver is not None and not (
                (runtime_env or {}).get("pip")
                or (runtime_env or {}).get("conda")):
            proc = self._forkserver.spawn(env, cwd)
            if proc is not None:
                self._register_worker_log(worker_id, ns.node_id, proc)
                return proc
        proc = subprocess.Popen(
            _worker_argv(runtime_env), env=env, cwd=cwd
        )
        self._register_worker_log(worker_id, ns.node_id, proc)
        return proc

    def _register_worker_log(self, worker_id: bytes, node_id: str,
                             proc) -> None:
        """A locally spawned worker's capture file joins the head's tail
        set.  Remote workers are the agents' to tail — registration-based
        ownership is what keeps each line shipped exactly once when an
        emulated multi-node run shares one session dir."""
        if self._log_monitor is None:
            return
        self._log_monitor.register(
            f"worker-{worker_id.hex()}",
            os.path.join(self.session_dir, "logs",
                         f"worker-{worker_id.hex()}.log"),
            node=node_id, pid=getattr(proc, "pid", None))

    def _spawn_on_node(
        self,
        ns: NodeState,
        worker_id: bytes,
        runtime_env: Optional[dict],
        extra_env: Optional[Dict[str, str]] = None,
    ) -> Optional[subprocess.Popen]:
        """Spawn a worker locally or delegate to the node's agent.  Returns
        the Popen for local spawns, None for remote ones.  Raises OSError
        when the spawn cannot happen on either path."""
        if ns.agent_conn is not None:
            env, cwd = self._remote_env_overrides(worker_id, runtime_env, extra_env)
            ns.agent_send({"type": "spawn_worker", "worker_id": worker_id.hex(),
                           "env_overrides": env, "cwd": cwd,
                           "pip": (runtime_env or {}).get("pip"),
                           "conda": (runtime_env or {}).get("conda")})
            return None
        return self._spawn_worker_process(ns, worker_id, runtime_env, extra_env)

    def _remote_env_overrides(
        self, worker_id: bytes, runtime_env: Optional[dict],
        extra_env: Optional[Dict[str, str]] = None,
    ) -> Tuple[Dict[str, str], Optional[str]]:
        """Env overrides shipped to a node agent for a remote worker spawn.
        User env_vars first; harness vars after so they always win (the
        agent merges over its own os.environ and fixes node identity)."""
        env: Dict[str, str] = {}
        cwd = _apply_runtime_env(env, runtime_env)
        env["RAY_TPU_ADDRESS"] = f"tcp://{self.tcp_address[0]}:{self.tcp_address[1]}"
        env["RAY_TPU_AUTHKEY"] = self.authkey.hex()
        env["RAY_TPU_WORKER_ID"] = worker_id.hex()
        env["RAY_TPU_SESSION_DIR"] = self.session_dir
        # remote workers log under the AGENT host's session dir; the
        # head's viewer shows local streams (per-node log agents are the
        # reference's split too)
        env["RAY_TPU_WORKER_LOG"] = os.path.join(
            self.session_dir, "logs", f"worker-{worker_id.hex()}.log")
        if extra_env:
            env.update(extra_env)
        return env, cwd

    def _spawn_worker(self, ns: NodeState, runtime_env: Optional[dict] = None) -> None:
        """Fork/exec a language worker (WorkerPool::StartWorkerProcess analog).

        With a runtime_env, the worker is spawned inside that environment
        (env_vars + working_dir) and only ever serves tasks declaring the
        identical env.  On a remote node the spawn is delegated to its
        agent (the worker still connects straight back to the head)."""
        worker_id = os.urandom(8)  # raylint: disable=R3 (per spawn, not per task)
        key = _runtime_env_key(runtime_env)
        try:
            proc = self._spawn_on_node(ns, worker_id, runtime_env)
        except (OSError, ValueError) as e:
            logger.warning("worker spawn failed for env %r: %s", key, e)
            if ns.agent_conn is None and key is not None:
                # trip the env's circuit breaker; plain (key=None) workers
                # keep retrying — a transient fork failure must not
                # permanently poison the default pool (agent-side spawn
                # failures come back as worker_exited messages instead)
                with self.lock:
                    ns.spawn_failures[key] = ns.spawn_failures.get(key, 0) + 3
            return
        h = WorkerHandle(worker_id=worker_id, node_id=ns.node_id, proc=proc,
                         runtime_env_key=key)
        self.workers[worker_id] = h
        ns.starting += 1
        ns.starting_by_key[key] = ns.starting_by_key.get(key, 0) + 1
        events_mod.emit("worker_pool", "worker spawning", severity="DEBUG",
                        entity_id=worker_id.hex(), node=ns.node_id,
                        runtime_env=bool(key))

    def _on_register_worker(self, conn: Connection, msg: dict) -> WorkerHandle:
        worker_id = bytes.fromhex(msg["worker_id"])
        with self.lock:
            h = self.workers.get(worker_id)
            if h is None:  # externally started worker (not via pool)
                h = WorkerHandle(worker_id=worker_id, node_id=msg["node_id"])
                self.workers[worker_id] = h
            h.conn = conn
            h.send_lock = self._conn_lock(conn)
            h.state = "idle"
            ns = self.nodes.get(h.node_id)
            if ns is not None:
                # Dedicated actor workers never join the general idle pool
                # and are not counted in the pool's spawn accounting.
                if not h.is_actor_worker:
                    ns.starting = max(0, ns.starting - 1)
                    k = h.runtime_env_key
                    ns.starting_by_key[k] = max(0, ns.starting_by_key.get(k, 0) - 1)
                    ns.spawn_failures.pop(k, None)  # a successful boot resets
                    h.idle_since = time.time()
                    ns.idle.append(h)
            self._wake_scheduler()
        events_mod.emit("worker_pool", "worker registered", severity="DEBUG",
                        entity_id=worker_id.hex(), node=h.node_id,
                        actor=h.is_actor_worker)
        return h

    def _on_worker_death(self, h: WorkerHandle, reason: str) -> None:
        from ray_tpu.exceptions import RayActorError, WorkerCrashedError

        with self.lock:
            if h.state == "dead":
                return
            was_starting = h.state == "starting"
            h.state = "dead"
            ns = self.nodes.get(h.node_id)
            if ns and h in ns.idle:
                ns.idle.remove(h)
            if ns and was_starting and not h.is_actor_worker:
                # died before registering: release the in-flight spawn slot
                # and count the failure so a boot-looping runtime_env
                # surfaces an error instead of deferring forever (plain
                # workers retry indefinitely — see _spawn_worker)
                ns.starting = max(0, ns.starting - 1)
                k = h.runtime_env_key
                ns.starting_by_key[k] = max(0, ns.starting_by_key.get(k, 0) - 1)
                if k is not None:
                    ns.spawn_failures[k] = ns.spawn_failures.get(k, 0) + 1
            spec = h.current_task
            h.current_task = None
            pipelined = list(h.pipeline)
            h.pipeline.clear()
        if self._shutdown:
            return
        events_mod.emit(
            "worker_pool", f"worker died: {reason}",
            severity="WARNING" if (spec is not None or h.actor_id) else "INFO",
            entity_id=h.worker_id.hex(), node=h.node_id,
            running_task=(spec or {}).get("name"))
        self._retire_worker_log(h, reason, busy=spec is not None
                                or h.actor_id is not None)
        if h.actor_id is not None:
            self._on_actor_worker_death(h, reason)
        elif spec is not None or pipelined:
            if spec is not None:
                tid = spec["task_id"]
                with self.lock:
                    rt = self.running.pop(tid, None)
                if rt is not None:
                    self._release_task_resources(rt)
            if spec is not None:
                if spec.get("retries_left", 0) > 0:
                    spec["retries_left"] -= 1
                    logger.warning("task %s failed (%s); retrying", spec.get("name"), reason)
                    self.submit_task(spec, _resubmit=True)
                else:
                    err = WorkerCrashedError(
                        f"Worker died while running task {spec.get('name')}: {reason}"
                    )
                    self._seal_error_returns(spec, err)
            # pipelined specs never started executing (only the promoted
            # task runs): resubmit them WITHOUT spending a retry, the way
            # the reference requeues leased-but-unpushed tasks — otherwise
            # one worker kill burns up to pipeline_depth+1 retry budgets
            for s in pipelined:
                self.submit_task(s, _resubmit=True)
        with self.lock:
            self._wake_scheduler()
        self._flush_sends()  # resubmits may have queued execute messages

    def _on_blocked(self, h: Optional[WorkerHandle], blocked: bool) -> None:
        """Release a blocked worker's CPUs so dependents can run — the
        reference's NotifyDirectCallTaskBlocked/Unblocked path that prevents
        nested ray.get deadlock."""
        if h is None:
            return
        with self.lock:
            held = None
            node_id = None
            if h.is_actor_worker and h.actor_id in self.actors:
                held = self.actors[h.actor_id].held
                node_id = self.actors[h.actor_id].node_id
            elif h.current_task is not None:
                tid = h.current_task["task_id"]
                if tid in self.running:
                    held = self.running[tid]["held"]
                    node_id = self.running[tid]["node_id"]
            if held is None:
                return
            # depth-counted: only the 0->1 and 1->0 transitions move CPUs
            # (threaded actors may have several methods blocked at once)
            if blocked:
                h.block_depth += 1
                if h.block_depth != 1:
                    return
                if not h.is_actor_worker and h.pipeline:
                    # this task's get may be waiting on the OUTPUT of a
                    # task pipelined behind it in this worker's FIFO queue
                    # — a scheduling deadlock.  Ask the worker to hand its
                    # unstarted pipelined tasks back; _on_pipeline_returned
                    # requeues whatever it actually returns.
                    h.outbox.append({"type": "reclaim_pipeline"})
                    with self._outbox_lock:
                        self._outbox_pending.add(h)
                    events_mod.emit(
                        "scheduler", "pipeline reclaim requested",
                        severity="DEBUG", entity_id=h.worker_id.hex(),
                        queued=len(h.pipeline))
            else:
                if h.block_depth == 0:
                    return
                h.block_depth -= 1
                if h.block_depth != 0:
                    return
            cpus = {CPU: held.get(CPU, 0.0)}
            ns = self.nodes.get(node_id)
            if ns is None or cpus[CPU] == 0.0:
                return
            if blocked:
                _release(cpus, ns.available)
            else:
                _acquire(cpus, ns.available)
            self._wake_scheduler()

    # ------------------------------------------------------------------
    # objects
    # ------------------------------------------------------------------
    def seal_object(
        self, oid: bytes, loc: ObjectLocation, contained: List[bytes],
        sealer: Optional[WorkerHandle] = None,
        client: Optional[ClientState] = None,
    ) -> None:
        # annotate the location with its node + object-server address so
        # any consumer anywhere can attach-or-pull ("" = head node).
        # Workers on emulated (fake-cluster) nodes share the head's shm
        # namespace, so only real agent nodes count as remote — otherwise
        # their segments would silently escape capacity/spill accounting.
        if loc.shm_name:
            node_id = sealer.node_id if sealer else self._head_node_id
            with self.lock:
                ns = self.nodes.get(node_id)
            is_remote = ns is not None and ns.agent_conn is not None
            loc.node_id = node_id if is_remote else ""
            if is_remote:
                loc.fetch_addr = tuple(ns.fetch_addr) if ns.fetch_addr else None
            else:
                head = self.nodes.get(self._head_node_id)
                loc.fetch_addr = tuple(head.fetch_addr) if head and head.fetch_addr else None
        # ownership audit: attribute the payload to its producer — the
        # sealing actor/worker, or the driver for puts over a client
        # connection (`ray memory`'s owner column)
        if sealer is not None:
            if sealer.actor_id is not None:
                owner, owner_kind = sealer.actor_id.hex(), "actor"
            else:
                owner, owner_kind = sealer.worker_id.hex(), "worker"
        elif client is not None:
            # per-tenant attribution: the job id, not an anonymous
            # "driver" — `ray_tpu memory` then rolls bytes up per tenant
            owner, owner_kind = client.job_id, "driver"
        else:
            owner, owner_kind = "driver", "driver"
        # contained refs are counted (and remembered for cascade-decrement
        # when this object dies) inside the registry
        self.registry.seal(oid, loc, contained, owner=owner,
                           owner_kind=owner_kind)
        self._notify_sealed(oid)
        with self.lock:
            # retry dep-blocked actor queues inline (the seal may be the
            # missing dependency); wake the scheduler only when something
            # it owns can actually make progress — a blanket notify here
            # was one scheduler pass per sealed object under load
            if self._dep_blocked_actors:
                for aid in list(self._dep_blocked_actors):
                    self._dep_blocked_actors.discard(aid)
                    art = self.actors.get(aid)
                    if art is not None:
                        with art.shard.lock:  # head lock -> shard lock
                            self._dispatch_actor_next_locked(art)
            if self.pending_tasks or self.pending_pgs:
                self._wake_scheduler()

    def _dynamic_state(self, tid: bytes):
        """(attempt, oids, done) snapshot for a dynamic task."""
        with self.lock:
            d = self._dynamic_yields.get(tid)
            attempt = d["attempt"] if d else 0
            oids = list(d["oids"]) if d else []
        with self.gcs.lock:
            ti = self.gcs.tasks.get(tid)
            done = ti is None or ti.state in ("FINISHED", "FAILED")
        return attempt, oids, done

    def _on_dynamic_yields_request(self, conn: Connection, msg: dict) -> None:
        """Long-poll for new dynamic yields: reply immediately when there
        is news (new oids past ``after``, a retry bumped the attempt, or
        the task ended); otherwise park until a yield/done wakes us (or the
        timeout sweep replies empty)."""
        tid = msg["task_id"]
        after = int(msg.get("after", 0))
        attempt, oids, done = self._dynamic_state(tid)
        if oids[after:] or done or attempt != int(msg.get("attempt", 0)):
            self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                               "value": {"oids": oids[after:], "done": done,
                                         "attempt": attempt}})
            return
        with self.lock:
            self._dynamic_waiters.setdefault(tid, []).append({
                "conn": conn, "req_id": msg["req_id"], "after": after,
                "attempt": int(msg.get("attempt", 0)),
                "deadline": time.monotonic() + 20.0,
            })

    def _wake_dynamic_waiters(self, tid: bytes, expire: bool = False) -> None:
        attempt, oids, done = self._dynamic_state(tid)
        with self.lock:
            waiters = self._dynamic_waiters.pop(tid, None)
            if not waiters:
                return
            keep = []
            fire = []
            now = time.monotonic()
            for wtr in waiters:
                if (oids[wtr["after"]:] or done or attempt != wtr["attempt"]
                        or (expire and now >= wtr["deadline"])):
                    fire.append(wtr)
                else:
                    keep.append(wtr)
            if keep:
                self._dynamic_waiters[tid] = keep
        for wtr in fire:
            self._reply(wtr["conn"], {
                "type": "reply", "req_id": wtr["req_id"],
                "value": {"oids": oids[wtr["after"]:], "done": done,
                          "attempt": attempt}})

    def _sweep_dynamic_waiters(self) -> None:
        """Expire parked long-polls (called from the timeout loop)."""
        with self.lock:
            tids = list(self._dynamic_waiters)
        for tid in tids:
            self._wake_dynamic_waiters(tid, expire=True)

    def _on_replica_added(self, worker: Optional[WorkerHandle], msg: dict) -> None:
        """A consumer finished pulling a copy onto its node — extend the
        object's location set (only real agent nodes count; emulated nodes
        share the head's shm namespace)."""
        if worker is None:
            return
        with self.lock:
            ns = self.nodes.get(worker.node_id)
            if ns is None or ns.agent_conn is None or ns.fetch_addr is None:
                return
            addr = tuple(ns.fetch_addr)
        self.registry.add_replica(msg["oid"], worker.node_id, addr)

    def _on_broadcast(self, conn: Connection, msg: dict) -> None:
        n_ok, err = self._broadcast_object(
            msg["oid"], timeout=msg.get("timeout", 120.0))
        self._reply(conn, {"type": "reply", "req_id": msg["req_id"],
                           "value": {"replicas": n_ok, "error": err}})

    def _broadcast_object(self, oid: bytes, timeout: float = 120.0):
        """Proactively replicate ``oid``'s payload to every alive agent node
        (PushManager analog, ``src/ray/object_manager/push_manager.h:29``)
        with doubling fan-out: each completed copy becomes a source for the
        next wave, so N nodes take O(log N) waves of the origin's bandwidth
        instead of N pulls from one server."""
        loc = self.registry.wait_sealed_existing(oid, min(30.0, timeout))
        if loc in (None, "missing"):
            return 0, f"object not available ({'unknown' if loc == 'missing' else 'timeout'})"
        if loc.inline is not None or not loc.shm_name or not loc.fetch_addr:
            return 0, None  # inline/spilled payloads ride messages instead
        existing = set(self.registry.replica_nodes(oid))
        with self.lock:
            targets = [
                ns for ns in self.nodes.values()
                if ns.alive and ns.agent_conn is not None and ns.fetch_addr
                and ns.node_id != loc.node_id and ns.node_id not in existing
            ]
        origin_arena = (loc.arena_path, loc.arena_off) if loc.arena_path else None
        sources = [(tuple(loc.fetch_addr), origin_arena)]
        n_ok, err = 0, None
        pending = list(targets)
        deadline = time.monotonic() + timeout  # ONE budget across all waves
        while pending:
            wave, pending = pending[:len(sources)], pending[len(sources):]
            acks = []
            for i, ns in enumerate(wave):
                addr, arena = sources[i % len(sources)]
                token = os.urandom(8).hex()  # raylint: disable=R3 (per pull)
                holder = {"event": threading.Event(), "ok": False, "error": None}
                self._pull_acks[token] = holder
                try:
                    ns.agent_send({
                        "type": "pull_object", "name": loc.shm_name,
                        "size": loc.size, "addr": addr, "arena": arena,
                        "token": token,
                    })
                except (OSError, ValueError):
                    self._pull_acks.pop(token, None)
                    err = f"send to {ns.node_id} failed"
                    continue
                acks.append((ns, token, holder))
            for ns, token, holder in acks:
                remaining = deadline - time.monotonic()
                if remaining > 0 and holder["event"].wait(remaining) and holder["ok"]:
                    self.registry.add_replica(oid, ns.node_id, ns.fetch_addr)
                    sources.append((tuple(ns.fetch_addr), None))
                    n_ok += 1
                else:
                    self._pull_acks.pop(token, None)
                    err = holder["error"] or "broadcast timed out"
            if time.monotonic() >= deadline:
                if pending:
                    err = err or "broadcast timed out"
                break
        return n_ok, err

    def _release_spec_pins(self, spec: dict) -> None:
        """Release a task spec's argument pins (idempotent — pops the
        lists).  The pins were counted by the submitting client at
        spec-build time (while its arg handles were provably alive, so the
        increment can't race a finalizer's decrement); ``owned_oids`` are
        spec-private objects (the big-args payload) whose initial refcount
        belongs to the spec itself."""
        pinned = spec.pop("pinned_refs", None)
        if pinned:
            self.registry.remove_refs(pinned, reason="task_arg")
        owned = spec.pop("owned_oids", None)
        if owned:
            self.registry.remove_refs(owned, reason="handle")

    def _register_pending_get(self, pg: _PendingGet) -> None:
        replies = []
        with self.lock:
            pg.unsealed = {
                oid for oid in pg.oids if not self.registry.is_sealed(oid)
            }
            reply = self._try_complete(pg, time.monotonic())
            if reply is not None:
                pg.done = True
                replies.append((pg, reply))
            else:
                self.pending_gets.append(pg)
                for oid in pg.unsealed:
                    lst = self._get_waiters.get(oid)
                    if lst is None:
                        self._get_waiters[oid] = [pg]
                    else:
                        # compact completed waiters on touch — without this
                        # a poll loop on a never-sealing oid grows the list
                        # one dead entry per poll, forever
                        lst[:] = [p for p in lst if not p.done]
                        lst.append(pg)
        for pg, reply in replies:
            pg.conn_send(reply)

    def _on_get_request(self, conn: Connection, msg: dict, worker: Optional[WorkerHandle]) -> None:
        oids = msg["oids"]
        timeout = msg.get("timeout")
        deadline = time.monotonic() + timeout if timeout is not None else None
        self._register_pending_get(_PendingGet(
            req_id=msg["req_id"],
            conn_send=lambda m: self._reply(conn, m),
            oids=oids,
            deadline=deadline,
            node_id=self._agent_node_or_head(worker.node_id) if worker else "",
        ))

    def _on_wait_request(self, conn: Connection, msg: dict, worker: Optional[WorkerHandle]) -> None:
        timeout = msg.get("timeout")
        deadline = time.monotonic() + timeout if timeout is not None else None
        self._register_pending_get(_PendingGet(
            req_id=msg["req_id"],
            conn_send=lambda m: self._reply(conn, m),
            oids=msg["oids"],
            deadline=deadline,
            kind="wait",
            num_returns=msg["num_returns"],
            node_id=self._agent_node_or_head(worker.node_id) if worker else "",
        ))

    def _try_complete(self, pg: _PendingGet, now: float) -> Optional[dict]:
        """Completion/expiry check for one waiter using its cached unsealed
        set (lock held).  Returns the reply, or None to keep waiting."""
        expired = pg.deadline is not None and now >= pg.deadline
        if pg.kind == "get":
            if not pg.unsealed:
                locs = {oid: self.registry.get_location(oid, prefer_node=pg.node_id)
                        for oid in pg.oids}
                if any(v is None for v in locs.values()):
                    # an oid un-sealed again (node loss between seal and
                    # completion): recompute and keep waiting
                    pg.unsealed = {
                        oid for oid in pg.oids if not self.registry.is_sealed(oid)
                    }
                    for oid in pg.unsealed:
                        self._get_waiters.setdefault(oid, []).append(pg)
                    if pg.unsealed:
                        if expired:
                            return {"type": "reply", "req_id": pg.req_id,
                                    "timeout": True}
                        return None
                    locs = {oid: self.registry.get_location(oid, prefer_node=pg.node_id)
                            for oid in pg.oids}
                return {"type": "reply", "req_id": pg.req_id, "locations": locs}
            if expired:
                return {"type": "reply", "req_id": pg.req_id, "timeout": True}
            return None
        # wait — the cached set can overstate sealing (node loss un-seals),
        # so completion is always confirmed against the registry
        n_sealed = len(pg.oids) - len(pg.unsealed)
        if n_sealed >= pg.num_returns or expired:
            sealed = [oid for oid in pg.oids if self.registry.is_sealed(oid)]
            if len(sealed) < pg.num_returns and not expired:
                pg.unsealed = {
                    oid for oid in pg.oids if not self.registry.is_sealed(oid)
                }
                for oid in pg.unsealed:
                    self._get_waiters.setdefault(oid, []).append(pg)
                return None
            locs = {oid: self.registry.get_location(oid, prefer_node=pg.node_id)
                    for oid in sealed}
            return {"type": "reply", "req_id": pg.req_id,
                    "ready": sealed, "locations": locs}
        return None

    def _notify_sealed(self, oid: bytes) -> None:
        """A seal wakes only the waiters parked on that oid."""
        now = time.monotonic()
        replies: List[Tuple[_PendingGet, dict]] = []
        with self.lock:
            waiters = self._get_waiters.pop(oid, None)
            if not waiters:
                return
            for pg in waiters:
                if pg.done:
                    continue
                pg.unsealed.discard(oid)
                reply = self._try_complete(pg, now)
                if reply is not None:
                    pg.done = True
                    replies.append((pg, reply))
        for pg, reply in replies:
            pg.conn_send(reply)

    def _service_pending_gets(self, now: Optional[float] = None) -> None:
        """Periodic sweep: deadline expiry + pruning of completed waiters
        (seal-driven wakeups go through _notify_sealed)."""
        now = now or time.monotonic()
        done: List[Tuple[_PendingGet, dict]] = []
        with self.lock:
            remaining = []
            for pg in self.pending_gets:
                if pg.done:
                    continue  # prune: replied via _notify_sealed
                reply = self._try_complete(pg, now)
                if reply is not None:
                    pg.done = True
                    done.append((pg, reply))
                else:
                    remaining.append(pg)
            self.pending_gets = remaining
        for pg, reply in done:
            pg.conn_send(reply)

    def _timeout_loop(self) -> None:
        while not self._shutdown:
            time.sleep(0.05)
            self._service_pending_gets()
            self._sweep_dynamic_waiters()

    def _reaper_loop(self) -> None:
        """Collect exited forkserver workers and any zombie reparented to
        us (subreaper / pid-1 container): a Z-state child that no live
        Popen object owns gets waitpid'ed here, nowhere else."""
        while not self._shutdown:
            time.sleep(2.0)
            try:
                with self.lock:
                    forked = [w.proc for w in self.workers.values()
                              if isinstance(w.proc, _ForkedProc)]
                    popen_pids = {w.proc.pid for w in self.workers.values()
                                  if isinstance(w.proc, subprocess.Popen)}
                if self._forkserver is not None and self._forkserver.pid:
                    popen_pids.add(self._forkserver.pid)
                for p in forked:
                    p.poll()  # reaps on exit; handle keeps the status
                    popen_pids.add(p.pid)  # sweep must not steal statuses
                self._reap_unknown_zombies(popen_pids)
            except Exception:
                pass

    def _reap_unknown_zombies(self, popen_pids: set) -> None:
        """Reap ORPHANED zombies only: a zombie owned by a live Popen
        (job drivers, node agents, user subprocesses) is collected by its
        owner within moments of exit — so anything still Z-state across
        two sweeps ~30s apart has no owner (a worker's abandoned child
        reparented to us), and waitpid'ing it cannot steal an exit status
        another subsystem is waiting on."""
        try:
            tids = os.listdir("/proc/self/task")
        except OSError:
            return
        children: set = set()
        for tid in tids:
            try:
                with open(f"/proc/self/task/{tid}/children") as f:
                    children.update(int(p) for p in f.read().split())
            except (OSError, ValueError):
                continue
        now = time.monotonic()
        seen = self._zombie_seen
        zombies: set = set()
        for pid in children - popen_pids:
            try:
                with open(f"/proc/{pid}/stat") as f:
                    if f.read().split(")")[-1].split()[0] != "Z":
                        continue  # alive (a _ForkedProc worker, fine)
            except (OSError, IndexError):
                continue
            zombies.add(pid)
            first = seen.setdefault(pid, now)
            if now - first < 30.0:
                continue  # young zombie: its owner may still collect it
            try:
                os.waitpid(pid, os.WNOHANG)
            except ChildProcessError:
                pass
            seen.pop(pid, None)
        # forget pids that got collected (or whose pid was recycled)
        for pid in list(seen):
            if pid not in zombies:
                seen.pop(pid, None)

    def _gcs_flush_loop(self) -> None:
        """Periodic persistence on its own thread (never in the path of
        pending-get servicing); prunes old terminal task records so the
        flush (and the table) stays bounded on long-lived heads."""
        while not self._shutdown:
            time.sleep(2.0)
            self._prune_task_history()
            self._dump_head_events()
            try:
                # periodic fold so head-local span events reach the trace
                # table before the ring evicts them (queries also fold)
                self._fold_local_traces()
            except Exception:
                pass
            if self.gcs_store is None:
                continue
            try:
                self.gcs.flush(self.gcs_store)
            except Exception:
                logger.warning("gcs flush failed:\n%s", traceback.format_exc())

    def _dump_head_events(self) -> None:
        """Append the head's new events to its crash-dump trail — a
        SIGKILL'd head still leaves its last-flushed events on disk.
        Incremental (O(new events) per cycle): rewriting the whole ring
        held the GIL long enough to cost ~4% of task throughput."""
        if not events_mod.ENABLED:
            return
        rows = events_mod.buffer().since(self._events_dumped_seq)
        if not rows:
            return
        path = os.path.join(self.session_dir, "logs", "events-head.jsonl")
        if events_mod.append_dump(path, rows):
            self._events_dumped_seq = rows[-1]["seq"]

    _MAX_TASK_HISTORY = 10_000

    def _prune_task_history(self) -> None:
        with self.gcs.lock:
            if len(self.gcs.tasks) <= self._MAX_TASK_HISTORY:
                return
            terminal = [
                (ti.end_time or 0.0, tid)
                for tid, ti in self.gcs.tasks.items()
                if ti.state in ("FINISHED", "FAILED")
            ]
            excess = len(self.gcs.tasks) - self._MAX_TASK_HISTORY
            terminal.sort()
            pruned = [tid for _, tid in terminal[:excess]]
            for tid in pruned:
                del self.gcs.tasks[tid]
        with self.lock:
            for tid in pruned:
                self._dynamic_yields.pop(tid, None)

    # ------------------------------------------------------------------
    # tasks
    # ------------------------------------------------------------------
    def submit_task(self, spec: dict, _resubmit: bool = False) -> None:
        with self.lock:
            if _resubmit and spec.get("dynamic_returns"):
                # a retried generator re-yields from the start: new attempt,
                # fresh yield list (consumers detect the bump and error out
                # mid-stream rather than receive duplicates)
                d = self._dynamic_yields.setdefault(
                    spec["task_id"], {"attempt": 0, "oids": []})
                d["attempt"] += 1
                d["oids"] = []
            if not _resubmit:
                # under gcs.lock too: flush/snapshot/prune iterate this
                # dict under gcs.lock alone, and an insert racing those
                # iterations is a "dictionary changed size" crash in the
                # gcs-flush thread (seen under a 1k-client serve soak)
                with self.gcs.lock:
                    self.gcs.tasks[spec["task_id"]] = TaskInfo(
                        task_id=spec["task_id"], name=spec.get("name", "task"),
                        trace_ctx=spec.get("trace_ctx"),
                        job_id=spec.get("job_id"),
                    )
                track = (
                    not spec.get("actor_id")
                    and len(self.lineage) < self.cfg.max_lineage_entries
                )
                if track:
                    tid = spec["task_id"]
                    deps = list(dict.fromkeys(spec.get("dep_ids", [])))
                    if deps:
                        self.registry.add_refs(deps, reason="lineage")
                    self._lineage_pins[tid] = deps
                    self._lineage_refcnt[tid] = len(spec["return_ids"])
                self.registry.create_pending_batch(spec["return_ids"])
                # idempotent tasks are the reconstructable kind (actor
                # methods mutate state and are excluded, as in the
                # reference's lineage rules)
                if track:
                    for oid in spec["return_ids"]:
                        self.lineage[oid] = spec
            self.pending_tasks.append(spec)
            # inline dispatch on the submitting thread (idle worker or a
            # same-shape lease) skips the scheduler hop for the hot path;
            # anything it can't place falls back to a scheduler pass
            if not self._try_inline_dispatch():
                self._wake_scheduler()

    def _try_inline_dispatch(self) -> bool:
        """Dispatch the pending-queue head inline if a worker can take it
        now (lock held).  Returns True when the head moved — plain
        strategy-free CPU specs only, FIFO order preserved because only
        the head is ever considered."""
        spec = self.pending_tasks[0] if self.pending_tasks else None
        if spec is None:
            return True
        req = spec.get("resources", {})
        if (
            spec.get("scheduling_strategy") is not None
            or req.get(TPU, 0)
            or not self._deps_ready(spec)
        ):
            return False
        key = _runtime_env_key(spec.get("runtime_env"))
        for ns in self.nodes.values():
            if not ns.alive:
                continue
            w = next((c for c in ns.idle if c.runtime_env_key == key), None)
            if w is not None and _fits(req, ns.available):
                self.pending_tasks.popleft()
                _acquire(req, ns.available)
                ns.idle.remove(w)
                self._dispatch(ns, w, spec, [], None)
                self._pipeline_topup(ns, w)
                return True
        # no idle worker: try riding an existing same-shape lease
        for w2 in self.workers.values():
            if (
                w2.state == "busy"
                and not w2.is_actor_worker
                and w2.current_task is not None
                and len(w2.pipeline) < self.cfg.task_pipeline_depth
            ):
                ns2 = self.nodes.get(w2.node_id)
                if ns2 is None or not ns2.alive:
                    continue
                before = len(self.pending_tasks)
                self._pipeline_topup(ns2, w2)
                if len(self.pending_tasks) < before:
                    return True
        return False

    def _on_pipeline_returned(self, w: Optional[WorkerHandle],
                              msg: dict) -> None:
        """A blocked worker handed back its unstarted pipelined tasks (see
        the reclaim in _on_blocked).  Requeue exactly the specs the worker
        reports — anything its main loop had already claimed runs there and
        is absent from the report, so nothing double-executes.  Pipelined
        specs never acquired resources (they swap at promotion), so the
        requeue is accounting-neutral."""
        if w is None:
            return
        ids = set(msg.get("task_ids", []))
        if not ids:
            return
        with self.lock:
            reclaimed = [s for s in w.pipeline if s["task_id"] in ids]
            w.pipeline = deque(
                s for s in w.pipeline if s["task_id"] not in ids)
            # a spec PROMOTED to current_task between the reclaim send and
            # this reply was already drained from the worker's local queue
            # and will never run there: undo the promotion bookkeeping and
            # requeue it ahead of the rest (it was FIFO-earlier)
            cur = w.current_task
            if (cur is not None and not w.is_actor_worker
                    and cur["task_id"] in ids
                    and cur["task_id"] in self.running):
                rt = self.running.pop(cur["task_id"])
                self._release_task_resources_locked(rt)
                reclaimed.insert(0, cur)
                w.current_task = None
                w.state = "idle"
                ns = self.nodes.get(w.node_id)
                if ns is not None and ns.alive:
                    w.idle_since = time.time()
                    ns.idle.append(w)
            if not reclaimed:
                return
            events_mod.emit(
                "scheduler", "pipeline reclaimed", severity="DEBUG",
                entity_id=w.worker_id.hex(), n_tasks=len(reclaimed))
            # front of the queue, original order: these were FIFO-earlier
            # than anything still pending
            for s in reversed(reclaimed):
                self.pending_tasks.appendleft(s)
                ti = self.gcs.tasks.get(s["task_id"])
                if ti:
                    ti.state = "PENDING"
                    ti.node_id = None
            self._wake_scheduler()  # cond wraps self.lock: notify under it

    def _on_object_deleted(self, oid: bytes) -> None:
        """Registry delete hook: drop the object's lineage entry and, when
        the creating task has no live lineage entries left, release the
        argument pins lineage was holding (cascades dep cleanup)."""
        with self.lock:  # hook runs on whichever thread dropped the last ref
            spec = self.lineage.pop(oid, None)
            if spec is None:
                return
            tid = spec["task_id"]
            left = self._lineage_refcnt.get(tid, 1) - 1
            if left > 0:
                self._lineage_refcnt[tid] = left
                return
            self._lineage_refcnt.pop(tid, None)
            pins = self._lineage_pins.pop(tid, [])
        for d in pins:  # registry calls outside the node lock
            self.registry.remove_ref(d, reason="lineage")

    def _seal_error_returns(self, spec: dict, err: Exception) -> None:
        from ray_tpu._private.object_store import store_value
        from ray_tpu._private.object_ref import ObjectRef

        self._release_spec_pins(spec)
        for oid in spec["return_ids"]:
            loc, _ = store_value(ObjectRef(oid), err, is_error=True)
            self.registry.seal(oid, loc)
            self._notify_sealed(oid)
        self.publish("error", {"task": spec.get("name"),
                               "task_id": spec["task_id"].hex(),
                               "error": str(err)})
        with self.lock:
            ti = self.gcs.tasks.get(spec["task_id"])
            if ti:
                ti.state = "FAILED"
                ti.end_time = time.time()
            wake_dynamic = (spec["task_id"] in self._dynamic_yields
                            or spec["task_id"] in self._dynamic_waiters)
        if wake_dynamic:
            self._wake_dynamic_waiters(spec["task_id"])

    def _deps_ready(self, spec: dict) -> bool:
        return all(self.registry.is_sealed(d) for d in spec.get("dep_ids", []))

    def _dep_locations(self, spec: dict, node_id: str = "") -> Dict[bytes, ObjectLocation]:
        deps = spec.get("dep_ids", [])
        if not deps:
            return {}
        return self.registry.get_locations_batch(deps, prefer_node=node_id)

    def _select_node(self, spec: dict) -> Optional[Tuple[NodeState, Optional[BundleRuntime]]]:
        """Hybrid pack/spread node selection (HybridSchedulingPolicy analog)."""
        req = spec.get("resources", {})
        strategy = spec.get("scheduling_strategy")
        if isinstance(strategy, dict) and strategy.get("kind") == "placement_group":
            pgrt = self.pgs.get(strategy["pg_id"])
            if pgrt is None or pgrt.info.state != "CREATED":
                return None
            idx = strategy.get("bundle_index", -1)
            if idx >= len(pgrt.bundles):
                raise ValueError(
                    f"placement group bundle index {idx} out of range "
                    f"({len(pgrt.bundles)} bundles)"
                )
            candidates = pgrt.bundles if idx < 0 else [pgrt.bundles[idx]]
            for b in candidates:
                ns = self.nodes.get(b.node_id)
                if ns and ns.alive and _fits(req, b.available):
                    return ns, b
            return None
        if isinstance(strategy, dict) and strategy.get("kind") == "node_affinity":
            ns = self.nodes.get(strategy["node_id"])
            if ns and ns.alive and _fits(req, ns.available):
                return ns, None
            if strategy.get("soft"):
                pass  # fall through to default policy
            else:
                return None
        alive = [n for n in self.nodes.values() if n.alive and _fits(req, n.total)]
        avail = [n for n in alive if _fits(req, n.available)]
        if not avail:
            return None
        thr = self.cfg.scheduler_spread_threshold
        below = [n for n in avail if n.utilization() < thr]
        if below:
            # pack: most utilized node under the threshold
            best = max(below, key=lambda n: (n.utilization(), n.node_id == self._head_node_id))
        else:
            best = min(avail, key=lambda n: n.utilization())
        return best, None

    def _wake_scheduler(self) -> None:
        """Mark scheduler work and wake the loop (lock must be held).  The
        loop clears the flag before each pass, so skipping the notify while
        it is still set can never lose a wake — it just coalesces them."""
        if not self._sched_work:
            self._sched_work = True
            self.cond.notify_all()

    def _scheduler_loop(self) -> None:
        last_sweep = 0.0
        while not self._shutdown:
            with self.lock:
                if not self._sched_work:
                    self.cond.wait(timeout=0.2)
                self._sched_work = False
            try:
                now = time.time()
                # sweeping polls every worker proc (a syscall each) — rate
                # limit it so a wake storm doesn't turn into a poll storm
                if now - last_sweep >= 0.2:
                    last_sweep = now
                    self._sweep_workers()
                self._schedule_once()
                # also the safety net for any queue site missing a flush:
                # the loop runs at least every 0.2s
                self._flush_sends()
            except Exception:
                logger.error("scheduler error:\n%s", traceback.format_exc())

    def _sweep_workers(self) -> None:
        """Detect pre-registration deaths and reap stale env-keyed idle
        workers.

        A worker that crashes before connecting has no connection whose
        close would report it (the reference's WorkerPool learns this from
        the process monitor); poll those procs here.  Env-keyed idle
        workers only serve their exact runtime_env, so past the idle
        threshold they are killed to return their pool slot."""
        dead, reap = [], []
        now = time.time()
        with self.lock:
            for w in self.workers.values():
                if w.state == "starting" and w.proc is not None and w.proc.poll() is not None:
                    dead.append(w)
            thr = self.cfg.idle_worker_killing_time_threshold_s
            for ns in self.nodes.values():
                for w in list(ns.idle):
                    if w.runtime_env_key is not None and now - w.idle_since > thr:
                        reap.append(w)
        for w in dead:
            self._on_worker_death(
                w, reason=f"exited with code {w.proc.returncode} before registering"
            )
        for w in reap:
            self._kill_worker(w, reason="idle runtime_env worker reaped")
        self._health_check(now)

    def _health_check(self, now: float) -> None:
        """Active agent liveness probing (GcsHealthCheckManager analog,
        ``gcs_health_check_manager.h:39``): a hung agent whose TCP
        connection stays open is detected by missed pongs, not only by a
        connection close."""
        period = self.cfg.health_check_period_s
        timeout = self.cfg.health_check_timeout_s
        ping_nodes, dead_nodes = [], []
        with self.lock:
            for ns in self.nodes.values():
                if not ns.alive or ns.agent_conn is None:
                    continue
                if now - ns.last_heartbeat > timeout:
                    dead_nodes.append(ns.node_id)
                elif now - ns.last_ping >= period:
                    ns.last_ping = now
                    ping_nodes.append(ns)
        for ns in ping_nodes:
            try:
                ns.agent_send({"type": "ping", "ts": now})
            except (OSError, ValueError):
                pass  # conn-close path will reap it
        for node_id in dead_nodes:
            logger.warning("node %s failed health check (%.0fs without a pong)",
                           node_id, timeout)
            self.remove_node_state(node_id)

    # ------------------------------------------------------------------
    # memory monitor + worker killing policy (MemoryMonitor
    # memory_monitor.h:52 -> WorkerKillingPolicy worker_killing_policy.h:30)
    # ------------------------------------------------------------------
    @staticmethod
    def _memory_fraction() -> float:
        """Host memory in use as a fraction (MemAvailable-based, the same
        signal the reference's MemoryMonitor reads from /proc)."""
        try:
            total = avail = None
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        total = float(line.split()[1])
                    elif line.startswith("MemAvailable:"):
                        avail = float(line.split()[1])
                    if total is not None and avail is not None:
                        break
            if not total or avail is None:
                # no MemAvailable (old kernels/containers): report no
                # pressure rather than fabricating 100% and killing workers
                return 0.0
            return 1.0 - avail / total
        except OSError:
            return 0.0

    def _pick_oom_victim(self) -> Optional[WorkerHandle]:
        """Newest retriable task first, then newest non-retriable — killing
        young retriable work preserves the most progress (the reference's
        group-by-retriable LIFO policy)."""
        with self.lock:
            cands = []
            for tid, rt in self.running.items():
                w = rt.get("worker")
                if w is None or w.state == "dead" or w.is_actor_worker:
                    continue
                ti = self.gcs.tasks.get(tid)
                started = ti.start_time if ti else 0.0
                retriable = rt["spec"].get("retries_left", 0) > 0
                cands.append((retriable, started, w))
            if not cands:
                return None
            # sort: retriable group first, newest (max start) first in group
            cands.sort(key=lambda c: (not c[0], -c[1]))
            return cands[0][2]

    def _check_memory_pressure(self) -> bool:
        frac = self._memory_fraction()
        if frac < self.cfg.memory_usage_threshold:
            return False
        victim = self._pick_oom_victim()
        if victim is None:
            return False
        logger.warning(
            "memory pressure %.1f%% >= %.1f%%: killing worker %s (task %s) "
            "to free memory",
            frac * 100, self.cfg.memory_usage_threshold * 100,
            victim.worker_id.hex(),
            victim.current_task.get("name") if victim.current_task else "?",
        )
        self.publish("error", {
            "type": "oom_kill",
            "worker_id": victim.worker_id.hex(),
            "memory_fraction": frac,
        })
        events_mod.emit(
            "scheduler", "OOM kill", severity="WARNING",
            entity_id=victim.worker_id.hex(),
            memory_fraction=round(frac, 3),
            task=(victim.current_task or {}).get("name"))
        self._kill_worker(victim, reason=f"OOM killer (host memory {frac:.0%})")
        return True

    def _memory_monitor_loop(self) -> None:
        interval = self.cfg.memory_monitor_refresh_ms / 1000.0
        while not self._shutdown:
            time.sleep(interval)
            try:
                self._check_memory_pressure()
            except Exception:  # noqa: BLE001 — monitor must never die
                logger.exception("memory monitor check failed")

    def _kill_worker(self, w: WorkerHandle, reason: str) -> None:
        self._on_worker_death(w, reason=reason)
        try:
            if w.proc is not None:
                w.proc.kill()
            else:
                with self.lock:
                    ns = self.nodes.get(w.node_id)
                if ns is not None and ns.agent_conn is not None:
                    ns.agent_send({"type": "kill_worker",
                                   "worker_id": w.worker_id.hex()})
        except Exception:
            pass

    def publish(self, channel: str, data) -> None:
        """Queue a message for fan-out to ``channel`` subscribers (the
        Publisher half of src/ray/pubsub/).  Enqueue-only: core threads
        (scheduler, client-serving) must never block on a slow
        subscriber's pipe.  Messages drop when the publisher falls 1000
        behind (pubsub is best-effort, like the reference's long-poll)."""
        if self._pub_queue.qsize() > 1000:
            return
        self._pub_queue.put((channel, data))

    def _publisher_loop(self) -> None:
        while not self._shutdown:
            item = self._pub_queue.get()
            if item is None:
                return
            channel, data = item
            with self.lock:
                subs = list(self.subscribers.get(channel, []))
            dead = []
            for conn in subs:
                lock = self._conn_lock(conn)
                try:
                    with lock:
                        conn.send({"type": "pubsub", "channel": channel, "data": data})
                except (OSError, ValueError):
                    dead.append(conn)
            if dead:
                with self.lock:
                    cur = self.subscribers.get(channel, [])
                    for conn in dead:
                        if conn in cur:
                            cur.remove(conn)

    def _broadcast_unlink(self, shm_name: str) -> None:
        """Registry callback: a deleted object's segment (origin or pulled
        replica) may live on any node — tell every agent to unlink."""
        with self.lock:
            agents = [ns for ns in self.nodes.values()
                      if ns.alive and ns.agent_conn is not None]
        for ns in agents:
            try:
                ns.agent_send({"type": "unlink", "name": shm_name})
            except (OSError, ValueError):
                pass

    def _schedule_once(self) -> None:
        if events_mod.ENABLED:
            with self.lock:  # _starved is mutated under it; bare
                # iteration races a concurrent del (dict-changed-size)
                depth = (len(self.pending_tasks)
                         + sum(len(q) for q in self._starved.values()))
            _sched_metrics()["queue_depth"].set(depth)
        self._schedule_pgs()
        self._schedule_actor_creations_and_tasks()
        # phase 1: move pending tasks to a node's ready queue (resources held)
        with self.lock:
            still_pending = deque()
            failed_specs = []

            def stage(spec, sel) -> None:
                ns, bundle = sel
                req = spec.get("resources", {})
                pool = bundle.available if bundle is not None else ns.available
                _acquire(req, pool)
                tpu_ids: List[int] = []
                n_tpu = int(req.get(TPU, 0))
                if n_tpu > 0:
                    tpu_ids = [ns.tpu_free.pop()
                               for _ in range(min(n_tpu, len(ns.tpu_free)))]
                with ns.shard.lock:  # runnable queues live in shard space
                    ns.ready_queue.append((spec, tpu_ids, bundle))

            # starved shapes first (FIFO-older than any new arrival):
            # each shape costs ONE placement probe when still starved —
            # a 1M-task backlog is never walked, only its shape heads
            for shape in list(self._starved):
                q = self._starved[shape]
                while q:
                    spec = q[0]
                    if not self._deps_ready(spec):
                        # deps un-sealed after entry (node loss): send it
                        # back through the arrival queue's dep re-checks
                        q.popleft()
                        still_pending.append(spec)
                        continue
                    try:
                        sel = self._select_node(spec)
                    except Exception as e:
                        q.popleft()
                        failed_specs.append((spec, e))
                        continue
                    if sel is None:
                        break  # shape still starved; q keeps FIFO order
                    q.popleft()
                    stage(spec, sel)
                if not q:
                    del self._starved[shape]
            # then new arrivals; a shape that fails to place (or already
            # has a starved queue — FIFO within the shape) parks there
            stuck_shapes = set()
            while self.pending_tasks:
                spec = self.pending_tasks.popleft()
                if not self._deps_ready(spec):
                    still_pending.append(spec)
                    continue
                shape = _placement_shape(spec)
                if shape in stuck_shapes or shape in self._starved:
                    self._starved.setdefault(shape, deque()).append(spec)
                    continue
                try:
                    sel = self._select_node(spec)
                except Exception as e:
                    # A bad scheduling strategy (e.g. bundle index out of
                    # range) fails only this task — the error is sealed into
                    # its returns so the caller sees it on get().
                    failed_specs.append((spec, e))
                    continue
                if sel is None:
                    stuck_shapes.add(shape)
                    self._starved.setdefault(shape, deque()).append(spec)
                    continue
                stage(spec, sel)
            self.pending_tasks = still_pending
        for spec, e in failed_specs:
            self._seal_error_returns(spec, e)
        env_failed: List[Tuple[dict, Optional[str]]] = []
        with self.lock:
            # phase 2: dispatch ready tasks to idle workers whose runtime_env
            # matches; spawn env-keyed workers for the rest
            for ns in self.nodes.values():
                if not ns.alive:
                    continue
                deferred = []
                with ns.shard.lock:  # node's runnable queue: shard-owned
                    staged = list(ns.ready_queue)
                    ns.ready_queue.clear()
                for spec, tpu_ids, bundle in staged:
                    key = _runtime_env_key(spec.get("runtime_env"))
                    w = next((c for c in ns.idle if c.runtime_env_key == key), None)
                    if w is None:
                        deferred.append((spec, tpu_ids, bundle, key))
                        continue
                    ns.idle.remove(w)
                    self._dispatch(ns, w, spec, tpu_ids, bundle)
                    if bundle is None and not tpu_ids:
                        self._pipeline_topup(ns, w)
                if deferred:
                    # Pool size is resource-feasible, not a fixed headroom:
                    # workers beyond the CPU count can never dispatch (the
                    # resource gate holds them) but their spawns starve a
                    # small host.  Blocked workers released their CPUs, so
                    # each one justifies a replacement (nested-get progress).
                    # count REGISTERED workers only — in-flight boots are
                    # already in ns.starting, and counting them twice makes
                    # each one eat two cap slots (stalling env spawns for
                    # the whole prestart boot window)
                    n_workers = 0
                    blocked = 0
                    for w in self.workers.values():
                        if (w.node_id == ns.node_id
                                and w.state not in ("dead", "starting")
                                and not w.is_actor_worker):
                            n_workers += 1
                            if w.block_depth > 0:
                                blocked += 1
                    cap = int(ns.total.get(CPU, 1)) + blocked
                    # Spawn only what the queues need; python startup is
                    # expensive, so never boot more than 2 at a time per env.
                    need_by_key: Dict[Optional[str], int] = {}
                    env_by_key: Dict[Optional[str], Optional[dict]] = {}
                    for spec, _, _, key in deferred:
                        need_by_key[key] = need_by_key.get(key, 0) + 1
                        env_by_key.setdefault(key, spec.get("runtime_env"))
                    for key, need in need_by_key.items():
                        if ns.spawn_failures.get(key, 0) >= 3:
                            continue  # boot-looping env; failed below
                        starting = ns.starting_by_key.get(key, 0)
                        while (
                            need > starting
                            and starting < self.cfg.maximum_startup_concurrency
                            and n_workers + ns.starting < max(1, cap)
                        ):
                            self._spawn_worker(ns, runtime_env=env_by_key[key])
                            starting += 1
                            n_workers += 1
                        if need > starting and n_workers + ns.starting >= max(1, cap):
                            # at the worker cap: evict an idle worker whose
                            # env can't serve any queued task so this env
                            # gets a slot (env-keyed pooling stays live)
                            victim = next(
                                (w for w in ns.idle if w.runtime_env_key not in need_by_key),
                                None,
                            )
                            if victim is not None:
                                self._kill_worker(victim, reason="evicted for new runtime_env")
                                n_workers -= 1
                                self._spawn_worker(ns, runtime_env=env_by_key[key])
                                n_workers += 1
                    for spec, tpu_ids, bundle, key in deferred:
                        if ns.spawn_failures.get(key, 0) >= 3:
                            # release the resources phase 1 acquired; the
                            # error is sealed below, outside the lock
                            pool = bundle.available if bundle is not None else ns.available
                            _release(spec.get("resources", {}), pool)
                            ns.tpu_free.extend(tpu_ids)
                            env_failed.append((spec, key))
                        else:
                            with ns.shard.lock:
                                ns.ready_queue.append((spec, tpu_ids, bundle))
        for spec, key in env_failed:
            self._seal_error_returns(
                spec,
                RuntimeError(
                    f"runtime_env setup failed: workers for env {key!r} died "
                    f"3 times before registering (bad env_vars/working_dir?)"
                ),
            )

    def _dispatch(self, ns: NodeState, w: WorkerHandle, spec: dict, tpu_ids: List[int], bundle) -> None:
        w.state = "busy"
        w.current_task = spec
        self.running[spec["task_id"]] = {
            "spec": spec,
            "worker": w,
            "node_id": ns.node_id,
            "held": dict(spec.get("resources", {})),
            "tpu_ids": tpu_ids,
            "bundle": bundle,
        }
        ti = self.gcs.tasks.get(spec["task_id"])
        if ti:
            ti.state = "RUNNING"
            ti.node_id = ns.node_id
        if events_mod.ENABLED:
            if ti:
                _sched_metrics()["dispatch_latency"].observe(
                    max(0.0, time.time() - ti.start_time))
            self._dispatch_n += 1
            if self._dispatch_n % _DISPATCH_EVENT_SAMPLE == 1 \
                    or _DISPATCH_EVENT_SAMPLE == 1 or tpu_ids:
                events_mod.emit(
                    "scheduler", f"dispatch {spec.get('name', 'task')}",
                    severity="DEBUG", entity_id=spec["task_id"].hex(),
                    node=ns.node_id, worker=w.worker_id.hex(),
                    tpus=len(tpu_ids), sample=_DISPATCH_EVENT_SAMPLE)
        self._queue_execute(w, spec, tpu_ids)

    def _release_task_resources(self, rt: dict) -> None:
        with self.lock:
            self._release_task_resources_locked(rt)

    def _release_task_resources_locked(self, rt: dict) -> None:
        ns = self.nodes.get(rt["node_id"])
        if ns is None:
            return
        held = dict(rt["held"])
        if rt["worker"].block_depth > 0:
            held[CPU] = 0.0  # CPUs already released by the blocked path
            rt["worker"].block_depth = 0
        bundle = rt.get("bundle")
        pool = bundle.available if bundle is not None and not bundle.detached else ns.available
        _release(held, pool)
        ns.tpu_free.extend(rt.get("tpu_ids", []))
        self._wake_scheduler()

    def _on_task_done(self, w: WorkerHandle, msg: dict) -> None:
        spec = msg["spec_ref"]
        tid = spec["task_id"]
        if w.is_actor_worker and not spec.get("is_actor_creation"):
            # HOT PATH: actor-method completion runs entirely inside the
            # actor's shard — methods hold no node resources (the actor's
            # dedicated worker does), so completion only advances the
            # actor's dispatch window.  No head lock.
            self._on_actor_task_done(w, msg, tid)
            return
        with self.lock:
            rt = self.running.pop(tid, None)
            full_spec = w.current_task  # has pinned_refs (spec_ref doesn't)
            w.current_task = None
        # The task is over: its argument pins drop.  Borrowing workers have
        # already registered their own handle refs (their add_ref messages
        # precede this task_done on the same connection).  Actor creation
        # specs keep their pins — they are re-dispatched on restart.
        if full_spec is not None and not spec.get("is_actor_creation"):
            self._release_spec_pins(full_spec)
        if msg.get("failed"):
            self.publish("error", {"task": spec.get("name"), "task_id": tid.hex(),
                                   "error": msg.get("error_str")})
        self._finish_task_record(tid, msg)
        # return objects were sealed by the worker via "seal" messages already
        is_creation = spec.get("is_actor_creation")
        if is_creation:
            if rt is not None:
                self._release_task_resources(rt)
            self._on_actor_started(spec, w, failed=msg.get("failed"), error=msg.get("error_str"))
        with self.lock:
            # release + pipeline promotion under ONE lock hold: releasing
            # first and re-acquiring in a separate critical section lets a
            # concurrent dispatch take the freed CPUs and the promotion's
            # "identical shape always fits" invariant would oversubscribe
            if rt is not None and not is_creation:
                self._release_task_resources_locked(rt)
            if w.state == "busy" and not w.is_actor_worker:
                ns = self.nodes.get(w.node_id)
                nxt = None
                if ns and ns.alive and w.pipeline:
                    nxt = w.pipeline.popleft()
                if nxt is not None:
                    # promote the pipelined successor: the completed task's
                    # identical resource shape was released above, so this
                    # acquire always fits; the worker is already executing it
                    _acquire(nxt.get("resources", {}), ns.available)
                    w.current_task = nxt
                    self.running[nxt["task_id"]] = {
                        "spec": nxt,
                        "worker": w,
                        "node_id": ns.node_id,
                        "held": dict(nxt.get("resources", {})),
                        "tpu_ids": [],
                        "bundle": None,
                    }
                    self._pipeline_topup(ns, w)
                else:
                    w.state = "idle"
                    if ns and ns.alive:
                        w.idle_since = time.time()
                        ns.idle.append(w)
                        # OnWorkerIdle fast path (direct_task_transport.cc:174):
                        # hand this worker the next compatible pending task
                        # right here, skipping a scheduler-thread round trip
                        # per completion (the hot-loop latency of a task wave)
                        self._fast_redispatch(ns, w)
    def _finish_task_record(self, tid: bytes, msg: dict) -> None:
        """Terminal task-table bookkeeping shared by the plain and actor
        task_done paths.  gcs.lock guards the row (NOT the head lock —
        the actor path completes on its shard without ever taking it, so
        gcs.lock is the one lock every writer of this table holds); the
        per-tid writer is unique, so field writes never race each other.
        Dynamic-waiter membership probes are GIL-atomic; a stale read
        costs one redundant wake."""
        with self.gcs.lock:
            ti = self.gcs.tasks.get(tid)
            if ti:
                ti.state = "FAILED" if msg.get("failed") else "FINISHED"
                ti.exec_start = msg.get("exec_start")
                ti.exec_end = msg.get("exec_end")
                ti.worker_pid = msg.get("worker_pid")
                ti.end_time = time.time()
        if tid in self._dynamic_yields or tid in self._dynamic_waiters:
            self._wake_dynamic_waiters(tid)

    def _on_actor_task_done(self, w: WorkerHandle, msg: dict,
                            tid: bytes) -> None:
        """Actor-method completion on the actor's home shard (no head
        lock): pop the in-flight entry, drop its pins, update the task
        table, and dispatch the next queued method in the freed window."""
        spec = msg["spec_ref"]
        art = self.actors.get(w.actor_id)  # dict read: GIL-safe
        full_spec = None
        if art is not None:
            with art.shard.lock:
                # concurrent actors complete out of order — find by task id
                full_spec = art.inflight.pop(tid, None)
                if full_spec is not None and art.inflight_groups:
                    g = full_spec.get("concurrency_group") or "_default"
                    n = art.inflight_groups.get(g, 1) - 1
                    if n > 0:
                        art.inflight_groups[g] = n
                    else:
                        art.inflight_groups.pop(g, None)
        # The task is over: its argument pins drop (borrowing workers
        # already registered their own handle refs — their add_ref frames
        # precede this task_done on the same connection).
        if full_spec is not None:
            self._release_spec_pins(full_spec)
        if msg.get("failed"):
            self.publish("error", {"task": spec.get("name"),
                                   "task_id": tid.hex(),
                                   "error": msg.get("error_str")})
        self._finish_task_record(tid, msg)
        if art is not None:
            with art.shard.lock:
                # a concurrency slot opened: dispatch the next queued
                # method right here (no scheduler wake — resources didn't
                # change, only this actor's pipeline advanced)
                self._dispatch_actor_next_locked(art)

    def _fast_redispatch(self, ns: NodeState, w: WorkerHandle) -> None:
        """Dispatch the next plain task this idle worker can run (lock
        held).  Only strategy-free CPU-only specs qualify — anything with
        affinity/PG/TPU placement goes through the full scheduler.
        Sources, in order: the resource-starved backlog (FIFO-older than
        any arrival; O(shapes) to probe, never O(backlog)), then the
        arrival queue's head (only the head: skipping past it would
        reorder submissions)."""
        if w.state != "idle" or not ns.alive:
            return

        def eligible(spec) -> bool:
            req = spec.get("resources", {})
            return not (
                spec.get("scheduling_strategy") is not None
                or req.get(TPU, 0)
                or _runtime_env_key(spec.get("runtime_env")) != w.runtime_env_key
                or not self._deps_ready(spec)
                or not _fits(req, ns.available)
            )

        spec = None
        src_shape = None
        for shape, q in list(self._starved.items()):
            resources, strat_key = shape
            if strat_key is not None or dict(resources).get(TPU, 0):
                continue
            head = q[0]
            if eligible(head):
                q.popleft()
                if not q:
                    del self._starved[shape]
                spec = head
                src_shape = shape
                break
        if spec is None:
            if not self.pending_tasks:
                return
            head = self.pending_tasks[0]
            if not eligible(head):
                return  # needs the real scheduler pass
            self.pending_tasks.popleft()
            spec = head
        req = spec.get("resources", {})
        _acquire(req, ns.available)
        try:
            ns.idle.remove(w)
        except ValueError:
            _release(req, ns.available)
            if src_shape is not None:
                # back to its shape queue's HEAD — pending_tasks would
                # re-park it at the tail, behind later same-shape arrivals
                self._starved.setdefault(src_shape, deque()).appendleft(spec)
            else:
                self.pending_tasks.appendleft(spec)
            return
        self._dispatch(ns, w, spec, [], None)
        self._pipeline_topup(ns, w)

    def _pipeline_topup(self, ns: NodeState, w: WorkerHandle) -> None:
        """Send up to task_pipeline_depth follow-on pending tasks to a busy
        plain worker's local queue (lock held).  Only strategy-free,
        TPU-free specs with the SAME resource shape as the running task
        qualify — promotion at completion then swaps the released resources
        for the promoted task's identical request, so accounting never goes
        negative.  The worker executes its queue FIFO, so ordering holds."""
        cur = w.current_task
        if cur is None or w.is_actor_worker:
            return
        if w.block_depth:
            # a blocked worker just had its pipeline reclaimed; queueing
            # more behind the blocked task would recreate the deadlock
            return
        req = cur.get("resources", {})
        if req.get(TPU, 0):
            return
        if cur.get("scheduling_strategy") is not None:
            # promotion acquires against ns.available with no bundle; a
            # PG/affinity successor must go through the scheduler so its
            # bundle (not the node pool) is debited
            return
        # pipeline only when the cluster is saturated for this shape — if
        # any node could run the task NOW, committing it to this busy
        # worker would defeat spreading (a remote node would sit idle
        # while tasks queue behind a local lease)
        if any(n.alive and _fits(req, n.available) for n in self.nodes.values()):
            self._wake_scheduler()
            return
        depth = self.cfg.task_pipeline_depth

        def source():
            """Next same-shape spec: the starved backlog first (the
            saturated case is exactly when the backlog lives there),
            then the arrival-queue head."""
            shape = _placement_shape(cur)
            q = self._starved.get(shape)
            if q:
                spec = q[0]
                if (_runtime_env_key(spec.get("runtime_env"))
                        == w.runtime_env_key
                        and spec.get("resources", {}) == req
                        and self._deps_ready(spec)):
                    q.popleft()
                    if not q:
                        del self._starved[shape]
                    return spec
                return None
            if not self.pending_tasks:
                return None
            spec = self.pending_tasks[0]
            if (
                spec.get("scheduling_strategy") is not None
                or spec.get("resources", {}) != req
                or _runtime_env_key(spec.get("runtime_env")) != w.runtime_env_key
                or not self._deps_ready(spec)
            ):
                return None
            self.pending_tasks.popleft()
            return spec

        while len(w.pipeline) < depth:
            spec = source()
            if spec is None:
                return
            w.pipeline.append(spec)
            ti = self.gcs.tasks.get(spec["task_id"])
            if ti:
                ti.state = "RUNNING"
                ti.node_id = ns.node_id
            self._queue_execute(w, spec, [])

    # ------------------------------------------------------------------
    # actors (GcsActorManager FSM analog)
    # ------------------------------------------------------------------
    def create_actor(self, spec: dict) -> None:
        dup_of: Optional[bytes] = None
        groups_env = (json.dumps(spec["concurrency_groups"])
                      if spec.get("concurrency_groups") else None)
        with self.lock:
            info = ActorInfo(
                actor_id=spec["actor_id"],
                name=spec.get("actor_name"),
                class_name=spec.get("name", "Actor").removesuffix(".__init__"),
                max_restarts=spec.get("max_restarts", 0),
                max_task_retries=spec.get("max_task_retries", 0),
                creation_spec=spec,
                namespace=spec.get("namespace") or "default",
                job_id=spec.get("job_id"),
                lifetime=spec.get("lifetime"),
            )
            with self.gcs.lock:  # see submit_task: the tenant reap and
                # flush/snapshot iterate this dict under gcs.lock alone,
                # so inserts must hold it too (node->gcs nesting, same as
                # the gcs.tasks fix)
                self.gcs.actors[spec["actor_id"]] = info
            self.registry.create_pending_batch(spec["return_ids"])
            if info.name:
                key = (info.namespace, info.name)
                existing = self.gcs.named_actors.get(key)
                prior = self.actors.get(existing) if existing else None
                if prior is not None and prior.info.state != "DEAD":
                    # name collision INSIDE one namespace: fail this
                    # creation (two tenants using the same name in their
                    # own namespaces never reach here — distinct keys)
                    dup_of = existing
                    info.state = "DEAD"
                    info.death_cause = (
                        f"actor name {info.name!r} is already taken in "
                        f"namespace {info.namespace!r}")
                else:
                    self.gcs.named_actors[key] = spec["actor_id"]
            if dup_of is None:
                self.actors[spec["actor_id"]] = ActorRuntime(
                    info=info, groups_env=groups_env,
                    shard=self.shards.for_actor(spec["actor_id"]))
                self._wake_scheduler()
        if dup_of is not None:
            from ray_tpu.exceptions import RayActorError

            self._seal_error_returns(
                spec, RayActorError(info.death_cause))
            events_mod.emit(
                "actor", f"{info.class_name} name collision in namespace",
                severity="ERROR", entity_id=spec["actor_id"].hex(),
                namespace=info.namespace)
            return
        events_mod.emit("actor", f"{info.class_name} -> PENDING_CREATION",
                        severity="DEBUG", entity_id=spec["actor_id"].hex())

    def _unregister_named_actor(self, info: ActorInfo) -> None:
        """Drop a permanently-DEAD actor's namespace directory entry (the
        name becomes reusable; lookups of dead actors already miss)."""
        if not info.name:
            return
        with self.lock:
            key = (info.namespace, info.name)
            if self.gcs.named_actors.get(key) == info.actor_id:
                del self.gcs.named_actors[key]

    def _schedule_actor_creations_and_tasks(self) -> None:
        spawn_failed: List[Tuple[ActorRuntime, List[dict], Exception]] = []
        with self.lock:
            for art in list(self.actors.values()):
                info = art.info
                if info.state in ("PENDING_CREATION", "RESTARTING") and art.worker is None:
                    spec = info.creation_spec
                    if not self._deps_ready(spec):
                        continue
                    sel = self._select_node(spec)
                    if sel is None:
                        continue
                    ns, bundle = sel
                    req = spec.get("resources", {})
                    pool = bundle.available if bundle is not None else ns.available
                    _acquire(req, pool)
                    art.held = dict(req)
                    art.node_id = ns.node_id
                    art.bundle = bundle
                    n_tpu = int(req.get(TPU, 0))
                    art.tpu_ids = [ns.tpu_free.pop() for _ in range(min(n_tpu, len(ns.tpu_free)))]
                    # dedicated worker for the actor
                    worker_id = os.urandom(8)  # raylint: disable=R3 (per actor)
                    extra_env: Dict[str, str] = {}
                    if art.tpu_ids:
                        extra_env["TPU_VISIBLE_CHIPS"] = ",".join(str(i) for i in art.tpu_ids)
                        extra_env["RAY_TPU_ASSIGNED_TPUS"] = extra_env["TPU_VISIBLE_CHIPS"]
                    if art.max_concurrency > 1:
                        extra_env["RAY_TPU_MAX_CONCURRENCY"] = str(art.max_concurrency)
                    if art.groups_env:
                        # the worker builds one bounded pool per group from
                        # this (plus the default max_concurrency pool)
                        extra_env["RAY_TPU_CONCURRENCY_GROUPS"] = art.groups_env
                    try:
                        proc = self._spawn_on_node(
                            ns, worker_id, spec.get("runtime_env"), extra_env
                        )
                    except (OSError, ValueError) as e:
                        # cannot even fork (bad working_dir, fd/memory
                        # pressure): give the resources back and fail the
                        # actor — re-acquiring every pass would drain the
                        # node's availability with nothing to show for it
                        _release(art.held, pool)
                        ns.tpu_free.extend(art.tpu_ids)
                        art.held = {}
                        art.tpu_ids = []
                        with art.shard.lock:
                            info.state = "DEAD"
                            info.death_cause = f"worker spawn failed: {e}"
                            failed = list(art.queue)
                            art.queue.clear()
                        spawn_failed.append((art, failed, e))
                        continue
                    h = WorkerHandle(
                        worker_id=worker_id,
                        node_id=ns.node_id,
                        proc=proc,
                        is_actor_worker=True,
                        actor_id=info.actor_id,
                        runtime_env_key=_runtime_env_key(spec.get("runtime_env")),
                    )
                    self.workers[worker_id] = h
                    art.worker = h
                    info.node_id = ns.node_id
                    info.worker_id = worker_id
                    info.state = "CREATING"
        if spawn_failed:
            from ray_tpu.exceptions import RayActorError

            for art, failed, e in spawn_failed:
                err = RayActorError(
                    f"Actor {art.info.class_name} worker failed to spawn: {e}"
                )
                self._unregister_named_actor(art.info)
                self._seal_error_returns(art.info.creation_spec, err)
                for s in failed:
                    self._seal_error_returns(s, err)
        with self.lock:
            # dispatch actor creation + method calls to registered actor
            # workers (head lock -> per-actor shard lock, one at a time)
            for art in list(self.actors.values()):
                w = art.worker
                if w is None or w.conn is None or w.state == "dead":
                    continue
                with art.shard.lock:
                    if art.info.state == "CREATING":
                        if w.state == "idle":
                            w.state = "busy"
                            spec = art.info.creation_spec
                            w.current_task = spec
                            self._queue_execute(w, spec, art.tpu_ids)
                            art.info.state = "STARTING"
                    elif art.info.state == "ALIVE":
                        self._dispatch_actor_next_locked(art)

    def _dispatch_actor_next_locked(self, art: ActorRuntime) -> None:
        """Pipeline queued methods straight to the actor's worker, up to
        max_concurrency in-flight (the direct actor task submitter fast
        path, reference ``direct_actor_task_submitter.h:67``).  Runs on
        whichever thread made the actor dispatchable — submit, task_done,
        dep seal — so a method call never waits on a scheduler-thread
        round trip.  Caller holds ``art.shard.lock`` (NOT the head lock:
        actors on different shards dispatch concurrently); per-actor FIFO
        order is preserved because every dispatch site pops under that
        one shard lock."""
        w = art.worker
        if (w is None or w.conn is None or w.state == "dead"
                or art.info.state != "ALIVE"):
            return
        # dispatch window = concurrency + pipeline headroom: the worker
        # bounds actual execution concurrency itself (inline loop or its
        # BoundedExecutor pool), so the extra calls just wait in its local
        # queue instead of across a head round trip
        groups = art.concurrency_groups
        if not groups:
            window = art.max_concurrency + self.cfg.actor_pipeline_depth
            while art.queue and len(art.inflight) < window:
                spec = art.queue[0]
                if not self._deps_ready(spec):
                    self._dep_blocked_actors.add(art.info.actor_id)
                    break
                art.queue.popleft()
                art.inflight[spec["task_id"]] = spec
                self._queue_execute(w, spec, art.tpu_ids)
            return
        # concurrency groups: one dispatch window PER group, FIFO within a
        # group, groups independent — a group whose window is full (or
        # whose next method is dep-blocked) is skipped, never the others
        # (the starvation fix: health-group calls dispatch past a
        # saturated default group).  ``_default`` keeps max_concurrency
        # semantics for method calls with no group.  Single left-to-right
        # pass rebuilding the queue: popleft + append keeps this O(n)
        # under the node lock (deque.remove mid-scan was O(n) per
        # dispatch — quadratic exactly when a group is saturated).
        depth = self.cfg.actor_pipeline_depth
        blocked: set = set()
        kept: List[dict] = []
        for _ in range(len(art.queue)):
            spec = art.queue.popleft()
            g = spec.get("concurrency_group") or "_default"
            if g in blocked:
                kept.append(spec)  # per-group FIFO: nothing in g may pass
                continue
            cap = groups.get(g, art.max_concurrency)
            if art.inflight_groups.get(g, 0) >= cap + depth:
                blocked.add(g)
                kept.append(spec)
                continue
            if not self._deps_ready(spec):
                self._dep_blocked_actors.add(art.info.actor_id)
                blocked.add(g)
                kept.append(spec)
                continue
            art.inflight[spec["task_id"]] = spec
            art.inflight_groups[g] = art.inflight_groups.get(g, 0) + 1
            self._queue_execute(w, spec, art.tpu_ids)
        art.queue.extend(kept)

    def _on_actor_started(self, spec: dict, w: WorkerHandle, failed: bool, error: Optional[str]) -> None:
        with self.lock:
            art = self.actors.get(spec["actor_id"])
            if art is None:
                return
            with art.shard.lock:  # head lock -> shard lock (fixed order)
                if failed:
                    art.info.state = "DEAD"
                    art.info.death_cause = f"creation failed: {error}"
                else:
                    art.info.state = "ALIVE"
                    # A defaulted num_cpus=1 was placement-only: reference actors
                    # occupy 0 CPU once created, so long-lived idle actors don't
                    # starve tasks out of the node (actor.py release_cpu_after_start).
                    if art.info.creation_spec.get("release_cpu_after_start") and art.held.get(CPU):
                        ns = self.nodes.get(art.node_id)
                        bundle = getattr(art, "bundle", None)
                        pool = (
                            bundle.available
                            if bundle is not None and not bundle.detached
                            else (ns.available if ns is not None else None)
                        )
                        if pool is not None and w.block_depth == 0:
                            _release({CPU: art.held[CPU]}, pool)
                        art.held[CPU] = 0.0
                    # methods queued while the actor was starting dispatch now
                    self._dispatch_actor_next_locked(art)
            self._wake_scheduler()
        events_mod.emit(
            "actor",
            f"{art.info.class_name} -> {'DEAD (creation failed)' if failed else 'ALIVE'}",
            severity="ERROR" if failed else "INFO",
            entity_id=spec["actor_id"].hex(), node=art.node_id)
        if failed:
            self._release_spec_pins(art.info.creation_spec)
            self._unregister_named_actor(art.info)

    def submit_actor_task(self, spec: dict) -> None:
        from ray_tpu.exceptions import RayActorError

        # HOT PATH: no head lock.  The actor's home shard alone guards its
        # queue and dispatch window, so submissions to different actors
        # (different tenants, different reader threads) run in parallel;
        # the registry and GCS tables have their own locks.
        art = self.actors.get(spec["actor_id"])  # dict read: GIL-safe
        self.registry.create_pending_batch(spec["return_ids"])
        dead_cause = None
        need_wake = False
        if art is None:
            dead_cause = "unknown actor"
        else:
            with self.gcs.lock:  # see submit_task: iterators hold only
                # gcs.lock, so inserts must too
                self.gcs.tasks[spec["task_id"]] = TaskInfo(
                    task_id=spec["task_id"],
                    name=spec.get("name", "actor_task"),
                    trace_ctx=spec.get("trace_ctx"),
                    job_id=spec.get("job_id"),
                )
            with art.shard.lock:
                # state re-checked UNDER the shard lock: the death path
                # drains the queue while holding it, so this append either
                # precedes the drain (which fails the spec) or observes
                # DEAD here — a spec can never strand on a dead queue
                if art.info.state == "DEAD":
                    dead_cause = art.info.death_cause
                else:
                    art.queue.append(spec)
                    # direct dispatch on the submitting connection's reader
                    # thread; the scheduler is only needed while the actor
                    # isn't placed yet
                    self._dispatch_actor_next_locked(art)
                    need_wake = bool(art.queue) and (
                        art.worker is None or art.info.state != "ALIVE")
        if dead_cause is not None:
            err = RayActorError(f"Actor is dead: {dead_cause}")
            threading.Thread(target=self._seal_error_returns, args=(spec, err), daemon=True).start()
            return
        if need_wake:
            with self.lock:
                self._wake_scheduler()

    def _on_actor_worker_death(self, w: WorkerHandle, reason: str) -> None:
        from ray_tpu.exceptions import RayActorError

        with self.lock:
            art = self.actors.get(w.actor_id)
            if art is None:
                return
            info = art.info
            # head lock first, then the actor's shard lock (fixed order):
            # the queue drain and the DEAD transition happen under the
            # shard lock so a concurrent shard-only submit either lands
            # before the drain (and is failed by it) or observes DEAD
            with art.shard.lock:
                will_restart = (info.state != "DEAD"
                                and (info.num_restarts < info.max_restarts
                                     or info.max_restarts == -1))
                # At-most-once by default: methods that were EXECUTING fail
                # with RayActorError.  With max_task_retries they requeue and
                # re-run on the restarted instance (never-started queued
                # methods always survive a restart — they haven't run yet).
                failed_specs = []
                retried = []
                for spec in art.inflight.values():  # dict order = dispatch order
                    attempts = spec.get("_actor_task_attempts", 0)
                    if will_restart and (
                        info.max_task_retries == -1
                        or attempts < info.max_task_retries
                    ):
                        spec["_actor_task_attempts"] = attempts + 1
                        retried.append(spec)
                    else:
                        failed_specs.append(spec)
                # extendleft reverses, so feed it the reversed list to put the
                # retried methods back at the front IN their dispatch order
                art.queue.extendleft(reversed(retried))
                art.inflight.clear()
                art.inflight_groups.clear()
                art.worker = None
                # release resources (skip CPUs a blocked method already gave
                # back through _on_blocked, or the pool double-counts them)
                ns = self.nodes.get(art.node_id) if art.node_id else None
                if ns is not None and art.held:
                    bundle = getattr(art, "bundle", None)
                    pool = bundle.available if bundle is not None and not bundle.detached else ns.available
                    held = dict(art.held)
                    if w.block_depth > 0:
                        held[CPU] = 0.0
                        w.block_depth = 0
                    _release(held, pool)
                    ns.tpu_free.extend(art.tpu_ids)
                    art.held = {}
                    art.tpu_ids = []
                if info.state == "DEAD":
                    return
                if info.num_restarts < info.max_restarts or info.max_restarts == -1:
                    info.num_restarts += 1
                    info.state = "RESTARTING"
                    logger.warning(
                        "actor %s died (%s); restarting (%d/%s)",
                        info.class_name, reason, info.num_restarts,
                        "inf" if info.max_restarts == -1 else info.max_restarts,
                    )
                else:
                    info.state = "DEAD"
                    info.death_cause = reason
                    failed_specs.extend(art.queue)
                    art.queue.clear()
            self._wake_scheduler()
        events_mod.emit(
            "actor", f"{info.class_name} -> {info.state} ({reason})",
            severity="WARNING", entity_id=w.actor_id.hex(),
            restarts=info.num_restarts)
        if info.state == "DEAD":
            # permanently gone: creation-spec arg pins drop now, and the
            # name becomes reusable in its namespace
            self._release_spec_pins(info.creation_spec)
            self._unregister_named_actor(info)
        err = RayActorError(f"Actor {info.class_name} died: {reason}")
        for spec in failed_specs:
            self._seal_error_returns(spec, err)

    # ------------------------------------------------------------------
    # task cancellation (reference ``python/ray/_private/worker.py:2573``
    # ``cancel`` + the core worker's CancelTask RPC)
    # ------------------------------------------------------------------
    def cancel_task(self, oid: bytes, force: bool = False,
                    recursive: bool = True) -> None:
        """Cancel the task that produces ``oid``.

        - queued anywhere head-side (pending/ready/actor queue): dequeued,
          returns sealed with TaskCancelledError, resources released;
        - dispatched to a worker (running or pipelined): returns pre-sealed
          with TaskCancelledError, then the worker is told to skip/interrupt
          it (``force=True`` SIGKILLs the worker instead — plain tasks only;
          the reference likewise refuses force-cancel of actor tasks);
        - finished/unknown: no-op.

        ``recursive`` also cancels tasks submitted BY the cancelled task
        (tracked via the spec's ``parent_task_id``).
        """
        from ray_tpu.exceptions import TaskCancelledError

        queue = deque([oid])
        seen = set()
        while queue:
            o = queue.popleft()
            if o in seen:
                continue
            seen.add(o)
            with self.lock:
                found = self._cancel_locked(o, force)
            if found is None:
                continue
            action, spec, w = found
            tid = spec["task_id"]
            if action == "dequeued":
                self._seal_error_returns(
                    spec, TaskCancelledError(
                        f"task {spec.get('name')} was cancelled before it started"))
            elif action == "at_worker":
                # pre-seal so callers unblock now; the worker's own late
                # seal (if it finishes anyway) loses first-seal-wins
                self._seal_error_returns(
                    spec, TaskCancelledError(
                        f"task {spec.get('name')} was cancelled"))
                if force:
                    self._kill_worker(w, reason="task force-cancelled")
                else:
                    try:
                        w.send({"type": "cancel", "task_id": tid})
                    except (OSError, ValueError):
                        pass
            if recursive:
                with self.lock:
                    queue.extend(self._children_return_oids_locked(tid))

    def _cancel_locked(self, oid: bytes, force: bool):
        """Locate the task producing ``oid`` and dequeue it if still
        head-side.  Returns (action, spec, worker|None) or None.  Lock held."""

        def produces(spec):
            return oid in spec.get("return_ids", ())

        # 1. cluster-pending (arrival queue + resource-starved backlog)
        for spec in self.pending_tasks:
            if produces(spec):
                self.pending_tasks.remove(spec)
                return ("dequeued", spec, None)
        for shape, q in list(self._starved.items()):
            for spec in q:
                if produces(spec):
                    q.remove(spec)
                    if not q:
                        del self._starved[shape]
                    return ("dequeued", spec, None)
        # 2. staged on a node (resources held)
        for ns in self.nodes.values():
            with ns.shard.lock:
                for entry in ns.ready_queue:
                    spec, tpu_ids, bundle = entry
                    if produces(spec):
                        ns.ready_queue.remove(entry)
                        pool = bundle.available if bundle is not None else ns.available
                        _release(spec.get("resources", {}), pool)
                        ns.tpu_free.extend(tpu_ids)
                        return ("dequeued", spec, None)
        # 3. actor method queues (shard-guarded: submits append to these
        # queues under the shard lock only, so the scan must hold it too)
        for art in self.actors.values():
            with art.shard.lock:
                for spec in art.queue:
                    if produces(spec):
                        art.queue.remove(spec)
                        return ("dequeued", spec, None)
                for spec in art.inflight.values():
                    if produces(spec):
                        if force:
                            raise ValueError(
                                "force=True is not supported for actor tasks")
                        return ("at_worker", spec, art.worker)
        # 4. at a worker: running or pipelined behind the running task
        for tid, rt in self.running.items():
            if produces(rt["spec"]):
                rt["spec"]["retries_left"] = 0  # a cancel never retries
                return ("at_worker", rt["spec"], rt["worker"])
        for w in self.workers.values():
            for spec in w.pipeline:
                if produces(spec):
                    spec["retries_left"] = 0
                    return ("at_worker", spec, w)
        return None

    def _children_return_oids_locked(self, tid: bytes) -> List[bytes]:
        """First return oid of every task submitted by task ``tid``."""
        out = []

        def scan(spec):
            if spec.get("parent_task_id") == tid and spec.get("return_ids"):
                out.append(spec["return_ids"][0])

        for spec in self.pending_tasks:
            scan(spec)
        for q in self._starved.values():
            for spec in q:
                scan(spec)
        for ns in self.nodes.values():
            with ns.shard.lock:
                for spec, _, _ in ns.ready_queue:
                    scan(spec)
        for rt in self.running.values():
            scan(rt["spec"])
        for w in self.workers.values():
            for spec in w.pipeline:
                scan(spec)
        for art in self.actors.values():
            with art.shard.lock:
                for spec in art.queue:
                    scan(spec)
                for spec in art.inflight.values():
                    scan(spec)
        return out

    def kill_actor(self, actor_id: bytes, no_restart: bool = True) -> None:
        from ray_tpu.exceptions import RayActorError

        with self.lock:
            art = self.actors.get(actor_id)
            if art is None:
                return
            with art.shard.lock:  # head lock -> shard lock (fixed order)
                if no_restart:
                    art.info.max_restarts = art.info.num_restarts  # disable restart
                w = art.worker
                failed_specs = []
                if w is None and no_restart and art.info.state != "DEAD":
                    # Killed before its worker ever spawned: fail it in place so
                    # it doesn't get scheduled later and run forever.
                    art.info.state = "DEAD"
                    art.info.death_cause = "killed before creation"
                    failed_specs = list(art.queue)
                    art.queue.clear()
                    ns = self.nodes.get(art.node_id) if art.node_id else None
                    if ns is not None and art.held:
                        bundle = getattr(art, "bundle", None)
                        pool = (
                            bundle.available
                            if bundle is not None and not bundle.detached
                            else ns.available
                        )
                        _release(art.held, pool)
                        ns.tpu_free.extend(art.tpu_ids)
                        art.held = {}
                        art.tpu_ids = []
                    self._wake_scheduler()
        if art.info.state == "DEAD":
            self._release_spec_pins(art.info.creation_spec)
            self._unregister_named_actor(art.info)
        err = RayActorError(f"Actor {art.info.class_name} was killed before creation")
        for spec in failed_specs:
            self._seal_error_returns(spec, err)
        if w is not None:
            # _kill_worker, not w.proc.kill(): a REMOTE actor's worker has
            # no head-side proc — the raw kill silently no-op'd, leaving a
            # zombie worker running on its agent AND its bundle capacity
            # held forever (a gang restart on live nodes then wedges: the
            # old gang's CPUs never return to the node pool)
            self._kill_worker(w, reason=f"actor {art.info.class_name} killed")

    # ------------------------------------------------------------------
    # placement groups (GcsPlacementGroupManager + bundle policies analog)
    # ------------------------------------------------------------------
    def create_placement_group(self, spec: dict) -> None:
        with self.lock:
            info = PlacementGroupInfo(
                pg_id=spec["pg_id"],
                bundles=spec["bundles"],
                strategy=spec["strategy"],
                name=spec.get("name"),
            )
            self.gcs.placement_groups[info.pg_id] = info
            rt = PGRuntime(info=info, ready_oid=spec.get("ready_oid"))
            self.pgs[info.pg_id] = rt
            if rt.ready_oid:
                self.registry.create_pending(rt.ready_oid)
            self.pending_pgs.append(rt.info.pg_id)
            self._wake_scheduler()

    def _schedule_pgs(self) -> None:
        """Bundle placement: STRICT_PACK / PACK / SPREAD / STRICT_SPREAD
        (bundle_scheduling_policy.h:82-106)."""
        sealed = []
        with self.lock:
            still = deque()
            while self.pending_pgs:
                pg_id = self.pending_pgs.popleft()
                rt = self.pgs.get(pg_id)
                if rt is None or rt.info.state != "PENDING":
                    continue
                placement = self._try_place_bundles(rt.info)
                if placement is None:
                    still.append(pg_id)
                    continue
                for bundle_req, ns in placement:
                    _acquire(bundle_req, ns.available)
                    rt.bundles.append(
                        BundleRuntime(node_id=ns.node_id, reserved=dict(bundle_req), available=dict(bundle_req))
                    )
                    rt.info.bundle_nodes.append(ns.node_id)
                rt.info.state = "CREATED"
                if rt.ready_oid:
                    sealed.append(rt.ready_oid)
            self.pending_pgs = still
        for oid in sealed:
            from ray_tpu._private.object_store import store_value
            from ray_tpu._private.object_ref import ObjectRef

            loc, _ = store_value(ObjectRef(oid), True)
            self.seal_object(oid, loc, [])

    def _try_place_bundles(self, info: PlacementGroupInfo):
        alive = [n for n in self.nodes.values() if n.alive]
        scratch = {n.node_id: dict(n.available) for n in alive}
        placement = []
        strategy = info.strategy
        if strategy in ("STRICT_PACK", "PACK"):
            # STRICT_PACK: all bundles on one node. PACK: best effort pack.
            for n in alive:
                avail = dict(scratch[n.node_id])
                ok = True
                for b in info.bundles:
                    if _fits(b, avail):
                        _acquire(b, avail)
                    else:
                        ok = False
                        break
                if ok:
                    return [(b, n) for b in info.bundles]
            if strategy == "STRICT_PACK":
                # Gang lease at slice granularity: when no single node
                # holds the gang, the pack unit widens to one FAILURE
                # DOMAIN — all bundles land within one slice (hosts
                # sharing a slice_id), leased atomically or not at all
                # (the TPU pod-slice semantics; a bundle-per-host gang
                # across a 16-host slice is exactly this shape).
                return self._try_pack_in_slice(info, alive, scratch)
        used_nodes = set()
        for b in info.bundles:
            cands = [n for n in alive if _fits(b, scratch[n.node_id])]
            if strategy == "STRICT_SPREAD":
                cands = [n for n in cands if n.node_id not in used_nodes]
            if not cands:
                return None
            if strategy in ("SPREAD", "STRICT_SPREAD"):
                cands.sort(key=lambda n: (n.node_id in used_nodes, len([1 for _, m in placement if m.node_id == n.node_id])))
            n = cands[0]
            _acquire(b, scratch[n.node_id])
            used_nodes.add(n.node_id)
            placement.append((b, n))
        return placement

    def _try_pack_in_slice(self, info: PlacementGroupInfo, alive, scratch):
        """STRICT_PACK fallback: fit ALL bundles within one slice.

        Slices are tried smallest-member-count first (tightest failure
        domain that can hold the gang); within a slice, bundles first-fit
        across members sorted by id (rank i of an N-bundle/N-host gang
        lands on host i — the deterministic rank→host mapping a
        collective mesh wants).  All-or-nothing per slice: a slice with a
        dead member that can't absorb the gang is skipped whole."""
        by_slice: Dict[str, list] = {}
        for n in alive:
            if n.slice_id is not None:
                by_slice.setdefault(n.slice_id, []).append(n)
        for _, members in sorted(by_slice.items(),
                                 key=lambda kv: (len(kv[1]), kv[0])):
            members = sorted(members, key=lambda n: n.node_id)
            avail = {n.node_id: dict(scratch[n.node_id]) for n in members}
            placement = []
            ok = True
            for b in info.bundles:
                for n in members:
                    if _fits(b, avail[n.node_id]):
                        _acquire(b, avail[n.node_id])
                        placement.append((b, n))
                        break
                else:
                    ok = False
                    break
            if ok:
                return placement
        return None

    def remove_placement_group(self, pg_id: bytes) -> None:
        with self.lock:
            rt = self.pgs.pop(pg_id, None)
            if rt is None:
                return
            rt.info.state = "REMOVED"
            for b in rt.bundles:
                b.detached = True
                ns = self.nodes.get(b.node_id)
                if ns is not None:
                    # return unconsumed capacity now; capacity consumed by
                    # still-running tasks flows back to the node when they
                    # finish (the detached flag reroutes their release).
                    _release(b.available, ns.available)
                    b.available = {}
            self._wake_scheduler()

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    def _list_state(self, what: str, limit: int = 1000,
                    filters: Optional[dict] = None) -> List[dict]:
        return self._list_state_page(what, limit, filters)[0]

    def _list_state_page(self, what: str, limit: int = 1000,
                         filters: Optional[dict] = None,
                         ) -> Tuple[List[dict], int]:
        """State API backend (experimental/state/api.py:729-1333 analog),
        returning ``(rows, total)`` so a truncated listing is visibly
        truncated.  ``filters`` (events only: source/severity) apply
        BEFORE the limit truncation — filtering the newest N cluster-wide
        rows client-side would hide a rare WARNING behind thousands of
        sampled DEBUGs."""

        def rows(items):
            out = []
            for it in list(items)[:limit]:
                d = {}
                for k in it.__dataclass_fields__:
                    if k == "creation_spec":  # big blobs; not introspection data
                        continue
                    v = getattr(it, k)
                    d[k] = v.hex() if isinstance(v, bytes) else v
                out.append(d)
            return out


        with self.gcs.lock:
            if what == "actors":
                return rows(self.gcs.actors.values()), len(self.gcs.actors)
            if what == "nodes":
                return rows(self.gcs.nodes.values()), len(self.gcs.nodes)
            if what == "tasks":
                return rows(self.gcs.tasks.values()), len(self.gcs.tasks)
            if what == "placement_groups":
                return (rows(self.gcs.placement_groups.values()),
                        len(self.gcs.placement_groups))
        if what == "slices":
            # failure-domain view: one row per slice, the unit the
            # autoscaler provisions/replaces atomically
            with self.lock:
                by_slice: Dict[str, dict] = {}
                for ns in self.nodes.values():
                    if ns.slice_id is None:
                        continue
                    row = by_slice.setdefault(ns.slice_id, {
                        "slice_id": ns.slice_id, "members": [],
                        "alive_members": 0, "dead_members": 0,
                        "draining": ns.slice_id in self._draining_slices,
                    })
                    row["members"].append(ns.node_id)
                    row["alive_members" if ns.alive else "dead_members"] += 1
                out = []
                for sid in sorted(by_slice):
                    row = by_slice[sid]
                    row["members"].sort()
                    row["degraded"] = (row["dead_members"] > 0
                                       and row["alive_members"] > 0
                                       and not row["draining"])
                    out.append(row)
            return out[:limit], len(out)
        if what == "objects":
            return (self.registry.list_objects(limit),
                    self.registry.stats()["num_objects"])
        if what == "workers":
            with self.lock:
                return [
                    {"worker_id": w.worker_id.hex(), "node_id": w.node_id,
                     "state": w.state, "is_actor_worker": w.is_actor_worker,
                     "pid": w.proc.pid if w.proc else None}
                    for w in list(self.workers.values())[:limit]
                ], len(self.workers)
        if what == "jobs":
            mgr = getattr(self, "job_manager", None)
            jobs = mgr.list_jobs() if mgr else []
            return jobs[:limit], len(jobs)
        if what == "events":
            # worker-shipped table + the head's own ring, one timeline;
            # the table computes its filtered total in the same pass
            src = (filters or {}).get("source")
            sev = (filters or {}).get("severity")
            rows, table_total = self.events.list_with_total(
                limit, source=src, severity=sev)
            local = [
                dict(r, origin="head") for r in events_mod.local_events()
                if (src is None or r.get("source") == src)
                and (sev is None or r.get("severity") == sev)]
            rows.extend(local)
            rows.sort(key=lambda r: r.get("ts", 0.0))
            return rows[-limit:], table_total + len(local)
        if what == "traces":
            self._fold_local_traces()
            return self.traces.list(limit), len(self.traces)
        if what == "tenants":
            # one row per driver job (live + recently dead), with actor
            # counts per namespace — what chaos resolves pids from and
            # what `ray_tpu list tenants` renders
            with self.gcs.lock:
                actor_counts: Dict[str, int] = {}
                for a in self.gcs.actors.values():
                    if a.job_id and a.state != "DEAD":
                        actor_counts[a.job_id] = actor_counts.get(a.job_id, 0) + 1
            with self.lock:
                out = [dict(rec, actors=actor_counts.get(jid, 0))
                       for jid, rec in self._jobs.items()]
            out.sort(key=lambda r: r["job_id"])
            return out[:limit], len(out)
        if what == "logs":
            # one row per captured stream (worker/job/tenant/head files
            # the monitors are tailing, retired death tails included)
            rows = self.log_store.stats()
            return rows[:limit], len(rows)
        if what == "incidents":
            # the watchdog's tracked incident set, open + resolved;
            # the history deque rides along for `incidents --history`
            if self.watchdog is None:
                return [], 0
            rows = self.watchdog.incidents.list(include_resolved=True)
            return rows[:limit], len(rows)
        if what == "slos":
            if self.watchdog is None:
                return [], 0
            rows = self.watchdog.slos()
            return rows[:limit], len(rows)
        raise ValueError(f"unknown state table {what!r}")

    def _doctor_report(self, trend_window_s: float = 1800.0) -> List[dict]:
        """Head-side doctor pass over head-local tables — what the
        ``doctor_report`` RPC serves so `ray_tpu doctor` stops pulling
        100k event/task rows to the client per invocation."""
        from ray_tpu.util import doctor as doctor_mod

        try:
            tasks, _total = self._list_state_page("tasks", 5000)
        except Exception:
            tasks = []
        return doctor_mod.head_report(
            self.events, events_mod.buffer(), self.tsdb, tasks=tasks,
            trend_window_s=trend_window_s)

    # ------------------------------------------------------------------
    # request traces (state_aggregator + tracing backend analog)
    # ------------------------------------------------------------------
    def _fold_local_traces(self) -> None:
        """Fold span events the HEAD process itself emitted (in-process
        drivers, serve routers living here) into the trace table.  Lazy —
        run at query time, cursored so each ring row folds once (the lock
        keeps the cursor single-writer across query + flush threads)."""
        with self._traces_fold_lock:
            rows = events_mod.buffer().since(self._traces_local_seq)
            if rows:
                self._traces_local_seq = rows[-1]["seq"]
                self.traces.add("head", rows)

    def _task_spans(self, trace_id: str) -> Tuple[List[dict], int]:
        """Task-table rows of this trace rendered as spans: the task span
        itself plus scheduler-queue and execution child spans — queue-time
        attribution comes straight from the control plane, no extra
        instrumentation on the dispatch path.

        Bounded like the TraceTable: the join keeps the FIRST N matching
        tasks by submission time (root/ingress work lands early; a traced
        50k-task streaming job must not produce a 150k-span payload) and
        the match runs on a snapshot taken under gcs.lock, not with the
        lock held across rendering."""
        with self.gcs.lock:
            snapshot = list(self.gcs.tasks.values())
        tasks = [t for t in snapshot
                 if t.trace_ctx and t.trace_ctx.get("trace_id") == trace_id]
        dropped = 0
        cap = max(1, events_mod.DEFAULT_TRACE_SPANS // 3)
        if len(tasks) > cap:
            tasks.sort(key=lambda t: t.start_time)
            dropped = len(tasks) - cap
            tasks = tasks[:cap]
        out: List[dict] = []
        now = time.time()
        for t in tasks:
            tc = t.trace_ctx
            sid = tc.get("span_id") or t.task_id.hex()[:16]
            end = t.end_time or now
            out.append({
                "name": t.name, "trace_id": trace_id, "span_id": sid,
                "parent_span_id": tc.get("parent_span_id", ""),
                "phase": "task", "source": "task",
                "origin": t.node_id or "pending",
                "start": t.start_time, "end": end,
                "data": {"task_id": t.task_id.hex(), "state": t.state},
            })
            if t.exec_start:
                out.append({
                    "name": f"{t.name} (queued)", "trace_id": trace_id,
                    "span_id": f"{sid}.q", "parent_span_id": sid,
                    "phase": "scheduler_queue", "source": "task",
                    "origin": t.node_id or "pending",
                    "start": t.start_time, "end": t.exec_start,
                })
                out.append({
                    "name": f"{t.name} (exec)", "trace_id": trace_id,
                    "span_id": f"{sid}.x", "parent_span_id": sid,
                    "phase": "execution", "source": "task",
                    "origin": t.node_id or "pending",
                    "start": t.exec_start, "end": t.exec_end or end,
                })
        return out, dropped

    def _get_trace(self, trace_id: str) -> Optional[dict]:
        """One assembled trace: shipped/local recorder spans + task-table
        spans, sorted by start time.  None for an unknown id."""
        self._fold_local_traces()
        base = self.traces.get(trace_id)
        task_spans, task_dropped = self._task_spans(trace_id)
        if base is None and not task_spans:
            return None
        spans = (base["spans"] if base else []) + task_spans
        spans.sort(key=lambda s: s["start"])
        # the trace's log records (stamped lines whose writer was inside
        # one of these spans) join the tree — prints become evidence on
        # the same timeline as the spans that produced them
        log_rows, _ = self.log_store.query(trace=trace_id, limit=500)
        return {
            "trace_id": trace_id,
            "spans": spans,
            "logs": log_rows,
            "dropped_spans": (base["dropped_spans"] if base else 0)
            + task_dropped,
        }

    # ------------------------------------------------------------------
    # log plane (head side)
    # ------------------------------------------------------------------
    def _ingest_log_report(self, origin: str, records, metas=None) -> None:
        """One shipped batch lands in the store; each job's slice then
        fans out to that job's subscribed drivers over pubsub.  Dict
        materialization (and actor-name resolution) happens only for
        channels someone is actually listening on."""
        by_job = self.log_store.ingest(origin, records, metas)
        for job, recs in by_job.items():
            channel = f"logs:{job}"
            with self.lock:
                if not self.subscribers.get(channel):
                    continue
            out = []
            meta_cache: Dict[str, dict] = {}
            for seq, ts, stream, src, task, actor, trace, line in recs:
                meta = meta_cache.get(stream)
                if meta is None:
                    meta = self.log_store.stream_meta(stream)
                    meta_cache[stream] = meta
                name = None
                if actor:
                    try:
                        with self.gcs.lock:
                            a = self.gcs.actors.get(bytes.fromhex(actor))
                        if a is not None:
                            name = a.name or a.class_name
                    except ValueError:
                        pass
                out.append({"seq": seq, "ts": ts, "stream": stream,
                            "src": src, "task": task, "actor": actor,
                            "trace": trace, "line": line, "name": name,
                            "pid": meta.get("pid"),
                            "node": meta.get("node")})
            self.publish(channel, {"records": out})

    def _retire_worker_log(self, h, reason: str, busy: bool) -> None:
        """A dead worker's capture file gets one final synchronous drain
        (local workers only — agents drain remote files BEFORE reporting
        the death, so the tail is already here), then its ring is
        retired-but-kept: that is what makes a SIGKILL'd worker's last
        stderr retrievable from the head after death.  If the tail ends
        in error output nobody consumed, surface it as the crash
        explanation (the doctor's worker_stderr_at_death rule)."""
        stream = f"worker-{h.worker_id.hex()}"
        if self._log_monitor is not None and h.proc is not None:
            self._log_monitor.unregister(stream)
        err_rows, _ = self.log_store.query(stream=stream, errors=True,
                                           limit=12)
        self.log_store.retire(stream)
        if not err_rows:
            return
        has_tb = any(r["line"].startswith("Traceback (") for r in err_rows)
        if not (has_tb or busy):
            return  # idle reaping with routine stderr chatter is not a crash
        events_mod.emit(
            "log", f"worker died with uncollected stderr: {reason}",
            severity="ERROR" if busy else "WARNING",
            entity_id=h.worker_id.hex(), node=h.node_id,
            tail=[r["line"] for r in err_rows][-8:])

    def _get_log(self, msg: dict) -> dict:
        """Record query for the state API / CLI.  ``job-<id>`` streams
        fall back to the JobManager's complete on-disk file when the
        store has nothing (log plane disabled, or the ring aged out) —
        job driver logs and worker logs stay one surface either way."""
        rows, cursor = self.log_store.query(
            stream=msg.get("stream"), job=msg.get("job"),
            task=msg.get("task"), actor=msg.get("actor"),
            node=msg.get("node"), pid=msg.get("pid"),
            trace=msg.get("trace"), grep=msg.get("grep"),
            errors=bool(msg.get("errors")),
            since_seq=msg.get("since_seq", 0),
            limit=msg.get("limit", 1000))
        stream = msg.get("stream")
        if not rows and stream and stream.startswith("job-") \
                and not msg.get("since_seq"):
            text = self.job_manager.logs(stream[len("job-"):])
            if text:
                rows = [{"seq": 0, "ts": None, "stream": stream, "src": "o",
                         "job": stream[len("job-"):], "task": "",
                         "actor": "", "trace": "", "line": ln,
                         "node": self._head_node_id, "pid": None}
                        for ln in text.splitlines()[-msg.get("limit", 1000):]]
        return {"records": rows, "cursor": cursor}

    def _summarize_state(self, what: str) -> dict:
        """Head-side aggregation for ``summarize_*`` (state_aggregator
        analog): counting happens HERE over the full tables instead of
        shipping up to 100k rows to the client to be counted locally."""
        from collections import Counter

        if what == "events":
            by_source: Dict[str, Counter] = {}
            for e in self._list_state("events", 100_000):
                by_source.setdefault(
                    e["source"], Counter())[e["severity"]] += 1
            return {src: dict(sev) for src, sev in by_source.items()}
        if what == "tasks":
            by_name: Dict[str, Counter] = {}
            with self.gcs.lock:
                for t in self.gcs.tasks.values():
                    by_name.setdefault(t.name, Counter())[t.state] += 1
            return {name: dict(states) for name, states in by_name.items()}
        if what == "actors":
            by_cls: Dict[str, Counter] = {}
            with self.gcs.lock:
                for a in self.gcs.actors.values():
                    by_cls.setdefault(a.class_name, Counter())[a.state] += 1
            return {cls: dict(states) for cls, states in by_cls.items()}
        if what == "traces":
            self._fold_local_traces()
            return self.traces.summarize()
        raise ValueError(f"unknown summary table {what!r}")

    # ------------------------------------------------------------------
    # resource accounting over time (metrics TSDB + top/memory surfaces)
    # ------------------------------------------------------------------
    def _tsdb_loop(self) -> None:
        """Head-side sampler on the shared deadline grid
        (``metrics.grid_ticks``): every push interval, expire origins
        that stopped pushing, refresh the runtime gauges, sample local
        processes' /proc stats, and fold the head's own registry into
        the TSDB.  The ticker's ``stalled`` flag skips expiry on a tick
        right after a head stall (everyone's timestamps lag equally —
        sweeping then would wipe live peers)."""
        from ray_tpu._private.resource_spec import ProcSampler
        from ray_tpu.util import tsdb as tsdb_mod
        from ray_tpu.util.metrics import grid_ticks, push_interval_s
        from ray_tpu.util.metrics import registry as head_registry

        sampler = ProcSampler()
        interval = push_interval_s()
        res = self._resource_sample_s
        if res is None:
            sample_every = 1  # default: /proc sample on every push tick
        elif res <= 0:
            sample_every = 0  # explicitly disabled, like the node agents
        else:
            sample_every = max(1, round(res / interval))
        tick_n = 0
        for stalled in grid_ticks(interval, self._tsdb_stop.wait):
            if self._shutdown:
                continue
            tick_n += 1
            try:
                if not stalled:
                    # the LIVE registry's hygiene is not a TSDB feature:
                    # dead pushers must leave /metrics even with the
                    # history layer switched off
                    expired = self.worker_metrics_registry.expire_origins(
                        self._origin_expiry_s)
                    for origin in expired:
                        events_mod.emit(
                            "node", "metrics origin expired",
                            severity="DEBUG", entity_id=origin)
                if not tsdb_mod.ENABLED:
                    continue
                if sample_every and tick_n % sample_every == 0:
                    self._sample_local_procs(sampler)
                self.refresh_runtime_gauges()
                self.tsdb.ingest("head", head_registry().snapshot())
                if not stalled:
                    self.tsdb.expire_stale(self._tsdb_expiry_s)
                    # profile rings age on the TSDB's clock: staged decay
                    # every tick, whole origins retired on the history
                    # horizon once their pushes stop
                    self.profile_store.prune()
                    for origin in self.profile_store.retire_stale(
                            self._tsdb_expiry_s):
                        events_mod.emit(
                            "profile", "profile origin retired",
                            severity="DEBUG", entity_id=origin)
                    for name in self.log_store.retire_stale(
                            self._tsdb_expiry_s):
                        events_mod.emit(
                            "log", "log stream retired",
                            severity="DEBUG", entity_id=name)
                    self._scan_tenant_logs()
            except Exception:
                logger.debug("tsdb sampler tick failed", exc_info=True)

    def _scan_tenant_logs(self) -> None:
        """Adopt proxied tenant-driver capture files (``tenant-*.log``
        under the session logs dir).  The proxier spawns those drivers
        from its own process, so spawn-time registration can't reach this
        monitor — a narrow glob keeps the registration-based ownership
        rule intact (nothing else ever writes tenant-*.log there)."""
        if self._log_monitor is None:
            return
        import glob as glob_mod

        known = set(self._log_monitor.streams())
        pattern = os.path.join(self.session_dir, "logs", "tenant-*.log")
        for path in glob_mod.glob(pattern):
            stream = os.path.basename(path)[:-len(".log")]
            if stream not in known:
                self._log_monitor.register(stream, path,
                                           node=self._head_node_id)

    def _sample_local_procs(self, sampler) -> None:
        """/proc stats for the head process and every worker whose process
        lives on this host (agent nodes sample their own workers and ship
        over metrics_report).  Lands as tagged gauges in the head registry
        — and therefore in /metrics and the TSDB — with dead workers'
        label series retired via Metric.remove."""
        from ray_tpu._private.resource_spec import (
            PROC_CPU_PCT,
            PROC_OPEN_FDS,
            PROC_RSS_MB,
            _PROC_METRIC_HELP,
            resource_metrics_snapshot,
        )
        from ray_tpu.util.metrics import Gauge

        entities = [({"entity": "head", "worker_id": "head",
                      "node": self._head_node_id}, os.getpid())]
        with self.lock:
            for wid, w in self.workers.items():
                if w.proc is not None and w.state != "dead":
                    entities.append((
                        {"entity": "actor" if w.is_actor_worker else "worker",
                         "worker_id": wid.hex(), "node": w.node_id},
                        w.proc.pid))
        _, raw = resource_metrics_snapshot(sampler, entities)
        gauges = {
            name: Gauge(name, _PROC_METRIC_HELP[name])
            for name in (PROC_RSS_MB, PROC_CPU_PCT, PROC_OPEN_FDS)
        }
        live_keys = set()
        proc_live = {}
        for tags, pid, stats in raw:
            full = {**tags, "pid": str(pid)}
            live_keys.add(tuple(sorted(full.items())))
            gauges[PROC_RSS_MB].set(stats["rss_mb"], tags=full)
            gauges[PROC_CPU_PCT].set(stats["cpu_pct"], tags=full)
            if "open_fds" in stats:
                gauges[PROC_OPEN_FDS].set(stats["open_fds"], tags=full)
            proc_live[tags["worker_id"]] = dict(stats, node=tags["node"],
                                                local=True)
        # retire label series of processes that vanished (Metric.remove —
        # without this the per-worker gauges grow with worker churn)
        for g in gauges.values():
            for labels in g.label_sets():
                if tuple(sorted(labels.items())) not in live_keys:
                    g.remove(labels)
        # local rows replace wholesale; remote rows (shipped by agents)
        # persist until their next report or until they go stale (a dead
        # remote worker stops appearing in its agent's reports — prune by
        # timestamp, or churn accumulates rows forever)
        cutoff = time.time() - self._origin_expiry_s
        with self._proc_lock:
            self._proc_live = {
                **{k: v for k, v in self._proc_live.items()
                   if not v.get("local") and v.get("ts", 0.0) >= cutoff},
                **proc_live,
            }

    def _fold_resource_report(self, origin: str, metrics: Dict[str, dict]) -> None:
        """Keep the live top-view cache current from a node agent's (or
        any remote sampler's) shipped per-process gauges."""
        from ray_tpu._private.resource_spec import (
            PROC_CPU_PCT,
            PROC_OPEN_FDS,
            PROC_RSS_MB,
        )

        names = {PROC_RSS_MB: "rss_mb", PROC_CPU_PCT: "cpu_pct",
                 PROC_OPEN_FDS: "open_fds"}
        now = time.time()
        with self._proc_lock:
            for name, field in names.items():
                m = metrics.get(name)
                if not m:
                    continue
                for key, value in m.get("values", {}).items():
                    tags = dict(key)
                    wid = tags.get("worker_id") or (
                        f"agent:{tags.get('node', origin)}"
                        if tags.get("entity") == "agent" else None)
                    if wid is None:
                        continue
                    row = self._proc_live.setdefault(
                        wid, {"node": tags.get("node", origin)})
                    row[field] = value
                    row["local"] = False
                    row["ts"] = now

    def _profile_ledger(self, window_s: float,
                        tasks: Optional[int] = None) -> dict:
        """The per-task CPU cost ledger over the trailing window: the
        store's duty-cycle class rates joined with the task lane.  Only
        task-path processes enter the sum — the head (which also hosts
        the in-process driver) and the workers; node agents and proxied
        tenant drivers profile too but their cycles are not per-task
        cost.  ``tasks`` defaults to the FINISHED delta the TSDB saw
        over the window (callers that counted exactly — the bench —
        pass their own)."""
        with self.lock:
            worker_origins = {w.worker_id.hex() for w in self.workers.values()}
        roles = {"head": "head"}
        for row in self.profile_store.stats():
            if row["origin"] in worker_origins:
                roles[row["origin"]] = "worker"
        if tasks is None:
            tasks = 0
            try:
                res = self.tsdb.query(
                    "ray_tpu_tasks", window_s=window_s,
                    tags={"state": "FINISHED"}, agg="max")
                points = [v for s in res.get("series", [])
                          for _, v in s.get("points", []) if v is not None]
                if points:
                    tasks = int(max(points) - min(points))
            except Exception:
                pass
            if not tasks:
                with self.gcs.lock:
                    tasks = sum(1 for t in self.gcs.tasks.values()
                                if t.state == "FINISHED")
        return self.profile_store.cost_ledger(window_s, tasks, roles)

    def refresh_runtime_gauges(self) -> None:
        """Refresh the head's runtime gauges (store/arena occupancy, task
        states, queue depth, owner-pinned bytes...) — shared by the
        dashboard's scrape path and the TSDB sample loop, so /metrics and
        the time series always agree (metric_defs.cc analog)."""
        from ray_tpu.util.metrics import Gauge

        g = Gauge("ray_tpu_objects_in_store", "objects tracked by the registry")
        stats = self.registry.stats()
        g.set(stats["num_objects"])
        Gauge("ray_tpu_object_store_bytes", "head-local shm bytes").set(
            stats["bytes_used"])
        Gauge("ray_tpu_objects_spilled", "objects spilled to disk").set(
            stats.get("num_spilled", 0))
        arena = getattr(self, "arena", None)
        if arena is not None:
            try:
                astats = arena.stats()
                Gauge("ray_tpu_arena_bytes_used",
                      "native arena bytes allocated").set(astats["bytes_used"])
                Gauge("ray_tpu_arena_capacity_bytes",
                      "native arena capacity").set(astats["capacity"])
            except Exception:
                pass
        with self.lock:
            n_workers = len([w for w in self.workers.values()
                             if w.state != "dead"])
            n_nodes = len([ns for ns in self.nodes.values() if ns.alive])
            n_pending = (len(self.pending_tasks)
                         + sum(len(q) for q in self._starved.values()))
        Gauge("ray_tpu_num_workers", "live workers").set(n_workers)
        Gauge("ray_tpu_num_nodes", "alive nodes").set(n_nodes)
        Gauge("ray_tpu_sched_queue_depth",
              "tasks pending cluster-wide (not yet staged on a node)").set(
            n_pending)
        # cluster-wide share of busy samples inside serialization frames —
        # the trend behind doctor's serialization_hot rule
        try:
            Gauge("ray_tpu_profile_serialization_frac",
                  "fraction of sampled busy time spent serializing").set(
                round(self.profile_store.serialization_frac(300.0), 4))
        except Exception:
            pass
        # log-plane ship pressure: cumulative records absorbed + source-
        # side suppression markers (grafana rates these for the "are we
        # dropping logs" panel)
        try:
            lc = self.log_store.counters()
            Gauge("ray_tpu_log_records_total",
                  "log records ingested by the head store").set(
                lc["ingested_total"])
            Gauge("ray_tpu_log_suppressed_total",
                  "log records dropped by source-side suppression").set(
                lc["suppressed_total"])
        except Exception:
            pass
        for src, n in self.events.counts().items():
            Gauge("ray_tpu_events_recorded",
                  "flight-recorder events held per source").set(
                n, tags={"source": src})
        with self.gcs.lock:
            for state in ("PENDING", "RUNNING", "FINISHED", "FAILED"):
                n = sum(1 for t in self.gcs.tasks.values() if t.state == state)
                Gauge("ray_tpu_tasks", "tasks by state").set(
                    n, tags={"state": state})
        # object-store bytes pinned per owner, from the ownership table —
        # the "who owns these 6 GiB" trend; stale owners' series retire
        audit = self._memory_audit(limit=0)
        g = Gauge("ray_tpu_owner_pinned_bytes",
                  "sealed object-store bytes attributed per owner")
        live = set()
        for row in audit["by_owner"][:50]:
            tags = {"owner": row["owner"], "kind": row["owner_kind"]}
            live.add(tuple(sorted(tags.items())))
            g.set(row["bytes"], tags=tags)
        for labels in g.label_sets():
            if tuple(sorted(labels.items())) not in live:
                g.remove(labels)

    def _memory_audit(self, limit: int = 200) -> dict:
        """The ``ray memory`` analog: every sealed object's bytes
        attributed to the worker/actor/driver that produced it, with pin
        reasons, ages, and orphan flags (owner process no longer alive).
        ``limit`` caps the per-object rows shipped; ``limit=0`` (the
        every-tick gauge refresh and ``top``) takes the aggregate-only
        registry pass — no per-object row dicts, no sort."""
        with self.lock:
            live_workers = {w.worker_id.hex() for w in self.workers.values()
                            if w.state != "dead"}
            live_actors = {a.info.actor_id.hex() for a in self.actors.values()
                           if a.worker is not None and a.worker.state != "dead"}
        with self.gcs.lock:
            actor_names = {a.actor_id.hex(): a.class_name
                           for a in self.gcs.actors.values()}
            actor_ns = {a.actor_id.hex(): a.namespace
                        for a in self.gcs.actors.values()}
            ns_actors: Dict[str, int] = {}
            for a in self.gcs.actors.values():
                if a.state != "DEAD":
                    ns_actors[a.namespace] = ns_actors.get(a.namespace, 0) + 1
        with self.lock:
            job_ns = {jid: rec["namespace"] for jid, rec in self._jobs.items()}

        def owner_namespace(owner: str, kind: str) -> str:
            """Namespace a sealed owner rolls up under: actors carry
            theirs, driver owners are job ids, pooled workers are shared
            infrastructure (their seals serve whichever tenant's task ran
            last — attributing them to one would lie)."""
            if kind == "actor":
                return actor_ns.get(owner, "default")
            if kind == "driver":
                return job_ns.get(owner, "default")
            return "(shared)"

        def annotate(owner: str, kind: str):
            """(display label, owner process still alive)."""
            if kind == "actor":
                return (f"{actor_names.get(owner, 'actor')}:{owner[:8]}",
                        owner in live_actors)
            if kind == "worker":
                return f"worker:{owner[:8]}", owner in live_workers
            # driver/head seals live exactly as long as the session
            return owner, True

        rows: List[dict] = []
        num_objects = 0
        if limit:
            rows = self.registry.memory_audit()
            num_objects = len(rows)
            owner_aggs: Dict[tuple, dict] = {}
            by_reason: Dict[str, int] = {}
            for r in rows:
                key = (r["owner"], r["owner_kind"])
                agg = owner_aggs.setdefault(key, {"bytes": 0, "objects": 0})
                agg["bytes"] += r["size"] or 0
                agg["objects"] += 1
                by_reason[r["pin_reason"]] = by_reason.get(
                    r["pin_reason"], 0) + (r["size"] or 0)
        else:
            # aggregate-only path: O(owners) read of the incrementally-
            # maintained summary (no table scan under the registry lock
            # on the every-tick gauge refresh); the pin-reason breakdown
            # needs per-object pins and only ships with the rows
            owner_aggs = self.registry.owner_summary()
            by_reason = {}
            num_objects = sum(a["objects"] for a in owner_aggs.values())
        total = attributed = orphan_bytes = 0
        by_owner = []
        for (owner, kind), agg in owner_aggs.items():
            label, alive = annotate(owner, kind)
            total += agg["bytes"]
            if owner != "unknown":
                attributed += agg["bytes"]
            if not alive:
                orphan_bytes += agg["bytes"]
            by_owner.append({
                "owner": owner, "owner_kind": kind, "owner_label": label,
                "bytes": agg["bytes"], "objects": agg["objects"],
                "orphan": not alive,
            })
        by_owner.sort(key=lambda a: -a["bytes"])
        # per-namespace rollup: one row per tenant — pinned bytes, object
        # and actor counts, owning jobs (ISSUE 13 satellite: one tenant's
        # footprint reads off a single row of `ray_tpu top` / `memory`)
        ns_rows: Dict[str, dict] = {}
        for o in by_owner:
            nsn = owner_namespace(o["owner"], o["owner_kind"])
            row = ns_rows.setdefault(nsn, {
                "namespace": nsn, "bytes": 0, "objects": 0,
                "actors": ns_actors.get(nsn, 0), "jobs": 0})
            row["bytes"] += o["bytes"]
            row["objects"] += o["objects"]
            if o["owner_kind"] == "driver":
                row["jobs"] += 1
        for nsn, count in ns_actors.items():
            ns_rows.setdefault(nsn, {
                "namespace": nsn, "bytes": 0, "objects": 0,
                "actors": count, "jobs": 0})
        by_namespace = sorted(ns_rows.values(), key=lambda r: -r["bytes"])
        rows = rows[:limit]  # only shipped rows need per-row annotation
        for r in rows:
            r["owner_label"], alive = annotate(r["owner"], r["owner_kind"])
            r["orphan"] = not alive
        return {
            "ts": time.time(),
            "total_bytes": total,
            "attributed_bytes": attributed,
            "attributed_frac": (attributed / total) if total else 1.0,
            "orphan_bytes": orphan_bytes,
            "num_objects": num_objects,
            "by_owner": by_owner,
            "by_namespace": by_namespace,
            "by_pin_reason": by_reason,
            "rows": rows,
            "store": self.registry.stats(),
        }

    def _top_snapshot(self) -> dict:
        """One frame of ``ray_tpu top``: nodes with live host stats,
        workers/actors with their sampled RSS/CPU/fds and pinned bytes,
        plus store + task-state summaries."""
        from ray_tpu._private.resource_spec import host_stats

        audit = self._memory_audit(limit=0)
        pinned = {a["owner"]: a["bytes"] for a in audit["by_owner"]}
        with self._proc_lock:
            proc_live = dict(self._proc_live)
        with self.lock:
            nodes = [{
                "node_id": ns.node_id, "alive": ns.alive,
                "total": dict(ns.total), "available": dict(ns.available),
                "utilization": round(ns.utilization(), 3),
                "host_stats": ns.host_stats if ns.agent_conn is not None
                else None,
                # only head-local/emulated nodes genuinely share this
                # host: filling a remote node's missing stats (agent yet
                # to pong) with the head's /proc would mislabel them
                "_local_host": ns.agent_conn is None,
            } for ns in self.nodes.values()]
            workers = []
            for wid, w in self.workers.items():
                if w.state == "dead":
                    continue
                hexid = wid.hex()
                stats = proc_live.get(hexid, {})
                workers.append({
                    "worker_id": hexid, "node_id": w.node_id,
                    "pid": w.proc.pid if w.proc else None,
                    "state": w.state,
                    "kind": "actor" if w.is_actor_worker else "worker",
                    "actor_id": w.actor_id.hex() if w.actor_id else None,
                    "rss_mb": stats.get("rss_mb"),
                    "cpu_pct": stats.get("cpu_pct"),
                    "open_fds": stats.get("open_fds"),
                    "pinned_bytes": pinned.get(hexid)
                    or (pinned.get(w.actor_id.hex()) if w.actor_id else 0)
                    or 0,
                })
        with self.gcs.lock:
            actor_names = {a.actor_id.hex(): a.class_name
                           for a in self.gcs.actors.values()}
            task_states: Dict[str, int] = {}
            for t in self.gcs.tasks.values():
                task_states[t.state] = task_states.get(t.state, 0) + 1
        for w in workers:
            if w["actor_id"]:
                w["actor_class"] = actor_names.get(w["actor_id"])
        head_stats = proc_live.get("head", {})
        for n in nodes:
            if n.pop("_local_host") and n["host_stats"] is None \
                    and n["alive"]:
                n["host_stats"] = host_stats()
        return {
            "ts": time.time(),
            "nodes": nodes,
            "workers": workers,
            "head": head_stats,
            "tasks": task_states,
            "store": audit["store"],
            "owners": audit["by_owner"][:20],
            "namespaces": audit["by_namespace"][:20],
            "total_pinned_bytes": audit["total_bytes"],
            "orphan_bytes": audit["orphan_bytes"],
            "tsdb": self.tsdb.stats(),
            # device-memory watermark rows (util/perf.py gauges pushed
            # by train workers / serve engines; host-RSS kind on CPU)
            "hbm": self._hbm_rows(),
        }

    def _merged_metrics_snapshot(self) -> dict:
        """Head registry + worker-pushed registries, one snapshot (the
        dashboard's /metrics merge, reused by perf/top aggregation)."""
        from ray_tpu.util import metrics as metrics_mod

        return metrics_mod.merge_snapshots(
            metrics_mod.registry().snapshot(),
            self.worker_metrics_registry.snapshot())

    def _hbm_rows(self, merged: Optional[dict] = None) -> List[dict]:
        """Device-memory gauge rows from the merged registry: one row
        per (device, kind, origin) with in-use/limit/peak joined."""
        if merged is None:
            merged = self._merged_metrics_snapshot()
        rows: Dict[tuple, dict] = {}
        for name, field in (("ray_tpu_hbm_bytes_in_use", "bytes_in_use"),
                            ("ray_tpu_hbm_bytes_limit", "bytes_limit"),
                            ("ray_tpu_hbm_peak_bytes_in_use",
                             "peak_bytes_in_use")):
            m = merged.get(name)
            if not m:
                continue
            for key, v in m.get("values", {}).items():
                if not isinstance(v, (int, float)):
                    continue
                row = rows.setdefault(tuple(key), {"tags": dict(key)})
                row[field] = v
        return [rows[k] for k in sorted(rows)]

    @staticmethod
    def _merged_histogram_summary(merged: dict, name: str) -> Optional[dict]:
        """Count/mean + bucket-estimated p50/p99 for one merged-registry
        histogram, label series with identical bounds folded together
        (percentiles from cumulative bucket edges — coarse but honest:
        the estimate is an upper bound at bucket resolution, and a
        percentile whose mass lands in the +inf overflow bucket reports
        None rather than clamping to the last bound, which would be a
        FALSE upper bound on exactly the tail this layer explains;
        ``last_bound`` lets renderers say "> last_bound")."""
        m = merged.get(name)
        if not m or m.get("type") != "histogram":
            return None
        bounds: Optional[list] = None
        agg: Optional[list] = None
        total = 0
        total_sum = 0.0
        for v in m.get("values", {}).values():
            if not isinstance(v, dict):
                continue
            b = list(v.get("buckets") or [])
            vb = list(v.get("bounds") or [])
            if bounds is None:
                bounds, agg = vb, [0] * len(b)
            if vb != bounds or len(b) != len(agg):
                continue  # foreign bounds: skip rather than mis-fold
            agg = [a + x for a, x in zip(agg, b)]
            total += int(v.get("count") or 0)
            total_sum += float(v.get("sum") or 0.0)
        if not total or not bounds:
            return None

        def pct(q: float):
            target = q * total
            acc = 0
            for i, c in enumerate(agg):
                acc += c
                if acc >= target:
                    return bounds[i] if i < len(bounds) else None
            return None

        return {"count": total, "mean_s": round(total_sum / total, 6),
                "p50_est_s": pct(0.5), "p99_est_s": pct(0.99),
                "last_bound_s": bounds[-1]}

    def _perf_summary(self, window_s: float = 1800.0) -> dict:
        """Head-side aggregate behind ``ray_tpu perf`` / ``/api/perf``:
        the step-phase breakdown + compile table folded from the
        ``perf`` event source (cluster table + the head's own ring), the
        MFU trend from the TSDB, HBM watermarks and decode TTFT/ITL
        histograms from the merged registry, and each serve engine's
        latest prefill-interference meter state."""
        from ray_tpu.util import tsdb as tsdb_mod

        rows = self._list_state("events", 100_000, {"source": "perf"})
        steps = 0
        wall = 0.0
        tokens = 0
        phase_totals: Dict[str, float] = {}
        last_mfu: Dict[str, float] = {}
        compiles: Dict[tuple, dict] = {}
        interference: Dict[str, dict] = {}
        for r in rows:
            d = r.get("data") or {}
            msg = r.get("message")
            if msg == "step phases":
                steps += 1
                wall += float(d.get("wall_s") or r.get("span_dur") or 0.0)
                tokens += int(d.get("tokens") or 0)
                for k, v in (d.get("phases") or {}).items():
                    phase_totals[k] = phase_totals.get(k, 0.0) + float(v)
                if d.get("mfu") is not None:
                    # origin-qualified: two gangs both have a rank0, and
                    # bare entity ids would show one job's MFU as the
                    # other's
                    who = (f"{r.get('origin') or 'head'}:"
                           f"{r.get('entity_id')}")
                    last_mfu[who] = float(d["mfu"])
            elif msg == "jit compile":
                key = (str(r.get("origin") or "head"), str(d.get("fn", "?")))
                e = compiles.setdefault(key, {
                    "origin": key[0], "fn": key[1], "compiles": 0,
                    "compile_s": 0.0, "n_sigs": 0, "hits": 0, "misses": 0})
                e["compiles"] += 1
                e["compile_s"] += float(r.get("span_dur") or 0.0)
                # hits/misses/n_sigs ride every compile event cumulatively
                e["n_sigs"] = max(e["n_sigs"], int(d.get("n_sigs") or 0))
                e["hits"] = max(e["hits"], int(d.get("hits") or 0))
                e["misses"] = max(e["misses"], int(d.get("misses") or 0))
            elif msg == "prefill interference":
                eid = f"{r.get('origin') or 'head'}:{r.get('entity_id')}"
                prev = interference.get(eid)
                if prev is None or float(r.get("ts") or 0.0) >= float(
                        prev.get("ts") or 0.0):
                    interference[eid] = r
        merged = self._merged_metrics_snapshot()

        def counter_by_origin_fn(name: str) -> Dict[tuple, float]:
            out: Dict[tuple, float] = {}
            for key, v in (merged.get(name) or {}).get("values",
                                                       {}).items():
                if isinstance(v, (int, float)):
                    d = dict(key)
                    out[(d.get("origin", "head"), d.get("fn", "?"))] = v
            return out

        # hit/miss counts ride compile EVENTS only at compile time — a
        # steady-state fn that compiled once then served 100k hits would
        # read hits≈0 forever off events alone.  The live registry
        # counters keep counting, so they win where present.
        live_hits = counter_by_origin_fn("ray_tpu_jit_cache_hits_total")
        live_misses = counter_by_origin_fn("ray_tpu_jit_cache_misses_total")
        for key, e in compiles.items():
            if key in live_hits:
                e["hits"] = int(live_hits[key])
            if key in live_misses:
                e["misses"] = int(live_misses[key])
        mfu_series: List[dict] = []
        if tsdb_mod.ENABLED:
            try:
                mfu_series = self.tsdb.query(
                    "ray_tpu_train_step_mfu",
                    window_s=window_s).get("series", [])
            except Exception:
                mfu_series = []
        phases_out = {
            k: {"s": round(v, 6),
                "frac": round(v / wall, 4) if wall > 0 else 0.0}
            for k, v in sorted(phase_totals.items(), key=lambda kv: -kv[1])}
        for e in compiles.values():
            e["compile_s"] = round(e["compile_s"], 6)
        return {
            "ts": time.time(),
            "window_s": window_s,
            "steps": {"count": steps, "wall_s": round(wall, 6),
                      "tokens": tokens, "phases": phases_out,
                      "last_mfu": last_mfu},
            "mfu_trend": mfu_series,
            "compiles": sorted(compiles.values(),
                               key=lambda e: -e["compile_s"]),
            "hbm": self._hbm_rows(merged),
            "decode": {
                "ttft": self._merged_histogram_summary(
                    merged, "ray_tpu_llm_ttft_s"),
                "itl": self._merged_histogram_summary(
                    merged, "ray_tpu_llm_itl_s"),
                "interference": {eid: dict(r.get("data") or {})
                                 for eid, r in sorted(interference.items())},
            },
        }

    def _state_snapshot(self) -> dict:
        snap = self.gcs.snapshot()
        snap["object_store"] = self.registry.stats()
        snap["dashboard"] = (
            list(self.dashboard.address) if self.dashboard else None)
        with self.lock:
            snap["cluster_resources"] = {
                nid: dict(ns.total) for nid, ns in self.nodes.items() if ns.alive
            }
            snap["available_resources"] = {
                nid: dict(ns.available) for nid, ns in self.nodes.items() if ns.alive
            }
        return snap

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------
    def shutdown(self) -> None:
        self._shutdown = True
        self._tsdb_stop.set()
        if self._head_profiler is not None:
            try:
                self._head_profiler.stop()
            except Exception:
                pass
        if self.watchdog is not None:
            try:
                self.watchdog.stop()
            except Exception:
                pass
        if self._log_monitor is not None:
            try:
                self._log_monitor.stop()  # final drain into the store
            except Exception:
                pass
        if self._head_log_handler is not None:
            import logging as _logging

            try:
                _logging.getLogger("ray_tpu").removeHandler(
                    self._head_log_handler)
                self._head_log_handler.close()
            except Exception:
                pass
        try:
            self._dump_head_events()  # final increment of the crash trail
        except Exception:
            pass
        if self._forkserver is not None:
            self._forkserver.close()
        try:
            self._pub_queue.put(None)  # end the publisher thread
        except Exception:
            pass
        with self.lock:
            workers = list(self.workers.values())
        for w in workers:
            if w.conn is not None:
                try:
                    w.send({"type": "exit"})
                except Exception:
                    pass
        deadline = time.time() + 2.0
        for w in workers:
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=max(0.05, deadline - time.time()))
                except Exception:
                    try:
                        w.proc.kill()
                    except Exception:
                        pass
        with self.lock:
            agents = [ns for ns in self.nodes.values() if ns.agent_conn is not None]
        for ns in agents:
            try:
                ns.agent_send({"type": "shutdown"})
            except Exception:
                pass
        from ray_tpu._private.netutil import (
            force_close_connection,
            unblock_listener,
        )

        # wake the accept loops (close alone leaves accept(2) parked) and
        # every reader thread (their peers also see EOF promptly)
        unblock_listener(self._listener)
        unblock_listener(self._tcp_listener)
        with self.lock:
            conns = list(self._live_conns)
            self._live_conns.clear()
        for conn in conns:
            force_close_connection(conn)
        try:
            if self.dashboard is not None:
                self.dashboard.close()
        except Exception:
            pass
        try:
            self.job_manager.shutdown()
        except Exception:
            pass
        try:
            self.object_server.close()
        except Exception:
            pass
        from ray_tpu._private import object_transfer

        object_transfer.reset()
        if self.gcs_store is not None:
            try:
                self.gcs.flush(self.gcs_store)
                self.gcs_store.close()
            except Exception:
                pass
        self.registry.shutdown()
        if self.arena is not None:
            from ray_tpu._private import object_store as ostore_mod

            ostore_mod.set_owned_arena(None)
            try:
                self.arena.close(unlink=True)
            except Exception:
                pass
        from ray_tpu._private import shm as shm_mod
        from ray_tpu._private import usage

        with self.gcs.lock:
            usage.record_set("tasks_total", len(self.gcs.tasks))
            usage.record_set("actors_total", len(self.gcs.actors))
            usage.record_set("nodes_total", len(self.gcs.nodes))
        # fold in features recorded by worker/driver processes via KV
        for key in self.gcs.kv_keys("usage"):
            usage.record_feature(key.decode(errors="replace"))
        usage.write_report(self.session_dir)
        shm_mod.remove_session_marker(self.session_id)
