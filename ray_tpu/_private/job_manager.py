"""Job submission manager — runs in the head process.

Analog of the reference's ``JobManager``/``JobSupervisor``
(``dashboard/modules/job/job_manager.py:431,133``): an entrypoint shell
command runs as a driver subprocess with the cluster address in its env,
stdout/stderr captured to a per-job log file, and a monitor thread
tracking terminal status.
"""

from __future__ import annotations

import os
import signal
import subprocess
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional


@dataclass
class JobInfo:
    job_id: str
    entrypoint: str
    status: str = "PENDING"  # PENDING/RUNNING/SUCCEEDED/FAILED/STOPPED
    start_time: float = field(default_factory=time.time)
    end_time: Optional[float] = None
    returncode: Optional[int] = None
    metadata: Dict[str, str] = field(default_factory=dict)
    log_path: str = ""


class JobManager:
    def __init__(self, node):
        self.node = node
        self.jobs: Dict[str, JobInfo] = {}
        self.procs: Dict[str, subprocess.Popen] = {}
        self.lock = threading.Lock()
        self.log_dir = os.path.join(node.session_dir, "jobs")
        os.makedirs(self.log_dir, exist_ok=True)

    def _fail_pre_launch(self, job_id: str, entrypoint: str, log_path: str,
                         message: str) -> str:
        """Record a job that failed before its process launched."""
        info = JobInfo(job_id=job_id, entrypoint=entrypoint,
                       log_path=log_path, status="FAILED",
                       end_time=time.time())
        with self.lock:
            self.jobs[job_id] = info
        try:
            with open(log_path, "w") as f:
                f.write(message + "\n")
        except OSError:
            pass
        return job_id

    def submit(self, entrypoint: str, runtime_env: Optional[dict] = None,
               job_id: Optional[str] = None,
               metadata: Optional[Dict[str, str]] = None) -> str:
        job_id = job_id or f"job-{os.urandom(4).hex()}"
        log_path = os.path.join(self.log_dir, f"{job_id}.log")
        # reserve the id under the lock so two racing submits with the same
        # explicit job_id can't both launch
        placeholder = JobInfo(job_id=job_id, entrypoint=entrypoint, log_path=log_path)
        with self.lock:
            if job_id in self.jobs:
                raise ValueError(f"job {job_id} already exists")
            self.jobs[job_id] = placeholder
        env = dict(os.environ)
        cwd = None
        module_paths: list = []
        materialized: list = []  # package dirs pinned by THIS process
        if runtime_env:
            from ray_tpu._private.runtime_env_packaging import (
                PKG_KV_NAMESPACE, ensure_package_local, is_package_uri,
            )

            def materialize(uri: str) -> str:
                # a remote submitter uploaded local code as content-
                # addressed packages; extract from the head's own KV
                d = ensure_package_local(
                    lambda u: self.node.gcs.kv_get(
                        PKG_KV_NAMESPACE, u.encode()), uri,
                    pin_suffix=job_id)
                materialized.append(d)
                return d

            try:
                env.update(runtime_env.get("env_vars") or {})
                cwd = runtime_env.get("working_dir")
                if is_package_uri(cwd):
                    cwd = materialize(cwd)
                # py_modules go on the DRIVER's PYTHONPATH (the reference
                # installs them through the agent before the driver starts)
                for m in runtime_env.get("py_modules") or []:
                    module_paths.append(materialize(m) if is_package_uri(m)
                                        else m)
            except Exception as e:  # noqa: BLE001 — a bad/missing package
                # fails THIS job with a readable log, never the reader
                # loop (that would close the submitter's connection and
                # leak the reserved job id)
                from ray_tpu._private.runtime_env_packaging import unpin

                for d in materialized:
                    unpin(d, suffix=job_id)
                return self._fail_pre_launch(
                    job_id, entrypoint, log_path,
                    f"runtime_env package setup failed: {e}")
        host, port = self.node.tcp_address
        env["RAY_TPU_ADDRESS"] = f"tcp://{host}:{port}"
        env["RAY_TPU_AUTHKEY"] = self.node.authkey.hex()
        env["RAY_TPU_JOB_ID"] = job_id
        # the entrypoint driver must resolve this framework regardless of
        # its cwd (the reference ships the working dir via runtime_env)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        parts = module_paths + [pkg_root]
        if env.get("PYTHONPATH"):
            parts.append(env["PYTHONPATH"])
        env["PYTHONPATH"] = os.pathsep.join(parts)
        info = JobInfo(job_id=job_id, entrypoint=entrypoint,
                       metadata=dict(metadata or {}), log_path=log_path)
        log_f = open(log_path, "wb")
        try:
            proc = subprocess.Popen(
                entrypoint, shell=True, env=env, cwd=cwd,
                stdout=log_f, stderr=subprocess.STDOUT,
                start_new_session=True,  # stop_job kills the whole group
            )
        except OSError as e:
            log_f.close()
            from ray_tpu._private.runtime_env_packaging import unpin

            for d in materialized:
                unpin(d, suffix=job_id)
            return self._fail_pre_launch(job_id, entrypoint, log_path,
                                         f"failed to launch: {e}")
        finally:
            if not log_f.closed:
                log_f.close()
        # the packages now belong to the job process: transfer the head's
        # pins so the cache can evict them once the job exits (a
        # long-lived head must not pin every job's code forever)
        if materialized:
            from ray_tpu._private.runtime_env_packaging import repin

            for d in materialized:
                repin(d, proc.pid, suffix=job_id)
        info.status = "RUNNING"
        with self.lock:
            self.jobs[job_id] = info
            self.procs[job_id] = proc
        threading.Thread(target=self._monitor, args=(job_id, proc),
                         daemon=True, name=f"job-monitor-{job_id}").start()
        return job_id

    def _monitor(self, job_id: str, proc: subprocess.Popen) -> None:
        rc = proc.wait()
        with self.lock:
            info = self.jobs.get(job_id)
            self.procs.pop(job_id, None)
            if info is None or info.status == "STOPPED":
                return
            info.returncode = rc
            info.end_time = time.time()
            info.status = "SUCCEEDED" if rc == 0 else "FAILED"

    def stop(self, job_id: str) -> bool:
        with self.lock:
            info = self.jobs.get(job_id)
            proc = self.procs.get(job_id)
        if info is None:
            return False
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            except OSError:
                proc.kill()
            with self.lock:
                info.status = "STOPPED"
                info.end_time = time.time()
            return True
        return False

    def info(self, job_id: str) -> Optional[dict]:
        with self.lock:
            info = self.jobs.get(job_id)
        return asdict(info) if info else None

    def logs(self, job_id: str) -> str:
        with self.lock:
            info = self.jobs.get(job_id)
        if info is None or not os.path.exists(info.log_path):
            return ""
        with open(info.log_path, "r", errors="replace") as f:
            return f.read()

    def list_jobs(self) -> List[dict]:
        with self.lock:
            return [asdict(i) for i in self.jobs.values()]

    def shutdown(self) -> None:
        with self.lock:
            procs = list(self.procs.items())
        for _, proc in procs:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except OSError:
                pass
