"""Logging setup for ``ray_tpu.*`` loggers.

Workers do NOT inherit the driver's stdout/stderr: every worker dup2s
fds 1/2 into its per-process capture file at boot
(``worker.py:_redirect_output_to_log``), and the log plane — a per-node
:class:`~ray_tpu._private.log_plane.LogMonitor` tailing those files into
the head's :class:`~ray_tpu.util.log_store.LogStore` — is what carries
output to the driver and ``ray_tpu logs`` (the reference's
``python/ray/_private/log_monitor.py:100`` + GCS pubsub path).

The handler here resolves ``sys.stderr`` at emit time (never captures it
at setup — redirection may install the stamping stream later) and, when
stderr IS a capture stream, writes the record through
``write_record(level, ...)`` so logger output carries the same
job/task/actor/trace stamp as plain ``print()``.  On a plain tty it
stays human-readable with no stamp bytes.
"""

from __future__ import annotations

import logging
import os
import sys


class _ContextStreamHandler(logging.StreamHandler):
    """Emit-time stderr resolution + context stamping.

    ``logging.StreamHandler(sys.stderr)`` freezes whichever object
    ``sys.stderr`` was at import; a worker that redirects afterwards
    would keep logging to the dead inherited fd and its records would
    never reach the capture file."""

    # logging level -> one-char record src (log_plane protocol)
    _LEVEL_SRC = {"DEBUG": "D", "INFO": "I", "WARNING": "W",
                  "ERROR": "E", "CRITICAL": "C"}

    @property
    def stream(self):
        return sys.stderr

    @stream.setter
    def stream(self, value):  # base __init__ assigns; always re-resolve
        pass

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = self.format(record)
            out = sys.stderr
            writer = getattr(out, "write_record", None)
            if writer is not None and getattr(out, "_rt_log_plane", False):
                writer(self._LEVEL_SRC.get(record.levelname, "I"), msg)
            else:
                out.write(msg + "\n")
        except Exception:
            self.handleError(record)


def get_logger(name: str) -> logging.Logger:
    logger = logging.getLogger(name)
    root = logging.getLogger("ray_tpu")
    if not root.handlers:
        h = _ContextStreamHandler()
        h.setFormatter(logging.Formatter(
            "[ray_tpu %(levelname)s %(name)s] %(message)s"))
        root.addHandler(h)
        root.setLevel(os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"))
    return logger
