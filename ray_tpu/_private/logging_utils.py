"""Logging setup (RAY_LOG / log_monitor analog, kept minimal).

Workers inherit the driver's stdout/stderr, which gives the reference's
"actor prints appear on the driver" behavior for free on a single machine
(the reference needs a log monitor + GCS pubsub for this across nodes,
``python/ray/_private/log_monitor.py:100``).
"""

from __future__ import annotations

import logging
import os
import sys


def get_logger(name: str) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logging.getLogger("ray_tpu").handlers:
        root = logging.getLogger("ray_tpu")
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter("[ray_tpu %(levelname)s %(name)s] %(message)s"))
        root.addHandler(h)
        root.setLevel(os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"))
    return logger

