"""Worker forkserver: sub-100ms worker spawns on slow hosts.

The reference amortizes worker startup with a prestarted pool
(``src/ray/raylet/worker_pool.cc`` StartWorkerProcess + prestart); that
still pays a full CPython boot (~2s on a small host: interpreter + site +
imports) per worker.  This forkserver pays it ONCE: a template process
imports the worker module, then forks on demand — each worker is a fork
of a warm interpreter (~10-20ms), which is what makes hundreds of actors
per node feasible on one core.

Protocol (unix socket, one JSON line per spawn):
    request:  {"env": {full environ}, "cwd": path-or-null}
    response: {"pid": <worker pid>}

Each spawn double-forks so the worker is orphaned toward the nearest
subreaper (the head process sets PR_SET_CHILD_SUBREAPER and reaps —
node.py), and the forkserver itself reaps only the short-lived middle
child.  The template stays single-threaded, so forks are always safe.

Workers with a pip runtime_env use a different interpreter (the venv's);
those take the classic Popen path instead — see node.py.
"""

from __future__ import annotations

import json
import os
import socket
import sys


def serve(sock_path: str) -> None:
    # die with the head: we inherit its stdio, so outliving it would hold
    # its output pipes open (and leak a warm interpreter) after a crash
    ppid = os.getppid()
    try:
        import ctypes

        ctypes.CDLL(None).prctl(1, 9)  # PR_SET_PDEATHSIG, SIGKILL
    except Exception:
        pass
    if os.getppid() != ppid:  # parent died in the window before prctl
        os._exit(0)
    # preload: the expensive part of a worker cold boot.  Everything a
    # worker touches before its first task — the worker module chain,
    # the protobuf wire codec (google.protobuf is ~0.3s cold), pickle
    # machinery — is imported ONCE here; forks inherit the warm modules.
    import ray_tpu._private.worker as worker_mod
    import ray_tpu._private.wire  # noqa: F401  (pulls google.protobuf)
    import cloudpickle  # noqa: F401

    try:
        os.unlink(sock_path)
    except OSError:
        pass
    srv = socket.socket(socket.AF_UNIX)
    srv.bind(sock_path)
    srv.listen(128)
    print("FORKSERVER_READY", flush=True)
    while True:
        conn, _ = srv.accept()
        try:
            data = b""
            while not data.endswith(b"\n"):
                chunk = conn.recv(1 << 16)
                if not chunk:
                    break
                data += chunk
            if not data.strip():
                continue
            req = json.loads(data)
            pid = os.fork()
            if pid == 0:
                # middle child: fork the real worker and exit, orphaning
                # it to the subreaper so we never accumulate zombies.
                # EVERY path out of this branch must _exit — falling
                # through would leave a rogue twin racing accepts.
                try:
                    gpid = os.fork()
                except OSError:
                    try:
                        conn.sendall(b'{"error": "fork failed"}\n')
                    except OSError:
                        pass
                    os._exit(1)
                if gpid == 0:
                    srv.close()
                    conn.close()
                    os.environ.clear()
                    os.environ.update(req["env"])
                    # sys.path was computed from the TEMPLATE's env at its
                    # boot; honor this worker's PYTHONPATH + working_dir
                    # the way a fresh interpreter would
                    for p in reversed(
                            (req["env"].get("PYTHONPATH") or "").split(os.pathsep)):
                        if p and p not in sys.path:
                            sys.path.insert(0, p)
                    if req.get("cwd"):
                        try:
                            os.chdir(req["cwd"])
                        except OSError:
                            os._exit(1)
                        if req["cwd"] not in sys.path:
                            sys.path.insert(0, req["cwd"])
                    try:
                        worker_mod.main()
                    finally:
                        os._exit(0)
                try:
                    conn.sendall((json.dumps({"pid": gpid}) + "\n").encode())
                except OSError:
                    pass  # client gone; the worker registers on its own
                os._exit(0)
            os.waitpid(pid, 0)  # the middle child exits immediately
        except (OSError, ValueError, KeyError):
            pass  # bad/truncated request or client death must not kill us
        finally:
            try:
                conn.close()
            except OSError:
                pass


if __name__ == "__main__":
    serve(sys.argv[1])
