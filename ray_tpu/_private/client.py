"""CoreClient — every process's connection to the head runtime.

Plays the role of the reference's ``CoreWorker`` RPC surface
(``src/ray/core_worker/core_worker.h:249``): task submission, object
get/put/wait, actor creation/calls, KV access for function shipping.  Both
the driver and each worker hold one; replies are routed to blocked callers
by request id (the client-call manager pattern of ``src/ray/rpc/client_call.h``).
"""

from __future__ import annotations

import itertools
import pickle
import queue
import threading
import time
from multiprocessing.connection import Client as MPClient
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.object_store import ObjectLocation


def connect_control(address: str, authkey: bytes):
    """Open a wire-wrapped control-plane connection.

    Address is a unix-socket path or ``tcp://host:port`` (remote workers
    joining the head's TCP control plane).  The handshake occasionally
    loses a challenge race when several processes connect at once —
    retry, it is not a credentials problem.  Shared by CoreClient and
    the tenant driver relay (``util/client/driver.py``)."""
    from multiprocessing import AuthenticationError

    from ray_tpu._private import wire

    if isinstance(address, str) and address.startswith("tcp://"):
        host, _, port = address[len("tcp://"):].rpartition(":")
        target, family = (host, int(port)), "AF_INET"
    else:
        target, family = address, "AF_UNIX"
    for attempt in range(5):
        try:
            return wire.wrap(
                MPClient(target, family=family, authkey=authkey))
        except (AuthenticationError, OSError, EOFError):
            if attempt == 4:
                raise
            time.sleep(0.05 * (attempt + 1))


class CoreClient:
    def __init__(self, address: str, authkey: bytes, worker_id: Optional[bytes] = None, node_id: str = "",
                 proxy_namespace: Optional[str] = None, proxy: bool = False):
        self.conn = connect_control(address, authkey)
        if proxy:
            # multi-tenant proxy mode (ray_tpu://): ask the proxy to spawn
            # this connection's isolated driver subprocess, then the conn
            # becomes a transparent pipe to the head.  Done BEFORE the
            # recv loop starts — the handshake owns the socket.
            self.conn.send({"type": "proxy_hello",
                            "namespace": proxy_namespace})
            reply = self.conn.recv()
            mtype = reply.get("type")
            if mtype == "proxy_ready":
                pass  # this conn is now a pipe to our isolated driver
            elif mtype == "proxy_error":
                raise ConnectionError(
                    f"proxy refused connection: {reply.get('error')}")
            else:
                raise ConnectionError(
                    f"unexpected proxy handshake reply: {reply!r}")
        self.send_lock = threading.Lock()
        self._req_ids = itertools.count(1)
        self._pending: Dict[int, dict] = {}
        self._pending_lock = threading.Lock()
        # Submit coalescing: task/actor-task submissions buffer here and
        # ride one "submit_batch" message (the pipelined-pushes idea of the
        # reference's direct submitters, direct_actor_task_submitter.h:67,
        # applied to the wire).  Every other send flushes first, so
        # cross-message ordering on this connection is preserved; a 1 ms
        # side flusher bounds the latency of fire-and-forget submits.
        self._submit_buf: List[tuple] = []
        # Borrow-announcement coalescing: handle-reason add_refs buffer
        # here and ride ONE add_ref frame per flush tick (a get() wave
        # over a list of refs was one frame per ref).  Ordering stays
        # safe because every other send flushes this buffer FIRST — an
        # add_ref can arrive early (a transient extra pin, harmless) but
        # never after a remove_ref sent on this connection.
        self._ref_add_buf: List[bytes] = []
        self._submit_lock = threading.Lock()
        self._flush_event = threading.Event()
        self._flush_thread: Optional[threading.Thread] = None
        self._exec_queue: "queue.Queue[dict]" = None  # set by worker loop
        # worker-side cancellation hook: runs ON the recv thread so a
        # cancel can interrupt the main thread mid-task (the exec queue
        # would only deliver it after the task finished)
        self._cancel_handler = None
        # worker-side pipeline-reclaim hook: runs ON the recv thread for
        # the same reason — the main thread is blocked inside the current
        # task, so only this thread can drain the local queue
        self._reclaim_handler = None
        # worker-side profiling hook (dashboard on-demand profiling): runs
        # on its own thread — sampling blocks for the requested duration
        self._profile_handler = None
        self._subscriptions: Dict[str, list] = {}  # channel -> callbacks
        self._pubsub_queue = None  # created on first subscribe
        self._pubsub_lock = threading.Lock()
        self.worker_id = worker_id
        self.node_id = node_id
        self.closed = False
        self._recv_thread = threading.Thread(target=self._recv_loop, daemon=True, name="core-client-recv")
        self._recv_thread.start()

    # -- plumbing ----------------------------------------------------------
    def send(self, msg: dict) -> None:
        with self.send_lock:
            if self._ref_add_buf or self._submit_buf:
                self._flush_submits_locked()
            self.conn.send(msg)

    _SUBMIT_FLUSH_THRESHOLD = 32

    def _buffer_submit(self, kind: str, spec: dict) -> None:
        with self._submit_lock:
            self._submit_buf.append((kind, spec))
            n = len(self._submit_buf)
        if n >= self._SUBMIT_FLUSH_THRESHOLD:
            self.flush_submits()
        elif n == 1:
            # arm the deferred flush only on the empty->nonempty transition;
            # re-setting per submit made the flusher spin at 1 kHz
            self._arm_flusher()

    def flush_submits(self) -> None:
        with self.send_lock:
            if self._ref_add_buf or self._submit_buf:
                self._flush_submits_locked()

    def _flush_submits_locked(self) -> None:
        """send_lock held.  Lock order is always send_lock -> _submit_lock.
        Refs flush BEFORE submits: a buffered borrow announcement must
        precede any task spec that could reference the borrowed object."""
        with self._submit_lock:
            refs, self._ref_add_buf = self._ref_add_buf, []
            batch, self._submit_buf = self._submit_buf, []
        try:
            if refs:
                self.conn.send({"type": "add_ref", "oids": refs,
                                "reason": "handle"})
            if batch:
                self.conn.send({"type": "submit_batch", "batch": batch})
        except (OSError, ValueError):
            pass  # connection gone; recv loop surfaces it

    def _flush_loop(self) -> None:
        while not self.closed:
            self._flush_event.wait()
            time.sleep(0.001)
            self._flush_event.clear()
            if not self._submit_buf and not self._ref_add_buf:
                continue  # threshold flush already drained it
            try:
                self.flush_submits()
            except Exception:
                pass

    def _arm_flusher(self) -> None:
        """Start/poke the deferred flusher (empty->nonempty transitions)."""
        if self._flush_thread is None:
            with self._submit_lock:  # two transitions racing must not
                if self._flush_thread is None:  # start two flushers
                    self._flush_thread = threading.Thread(
                        target=self._flush_loop, daemon=True,
                        name="submit-flush")
                    self._flush_thread.start()
        self._flush_event.set()

    def _recv_loop(self) -> None:
        while not self.closed:
            try:
                msg = self.conn.recv()
            except (EOFError, OSError, pickle.UnpicklingError):
                # UnpicklingError covers wire.WireDecodeError: a corrupt
                # or version-mismatched frame is a broken connection, not
                # a reason to leave request() waiters hanging
                self.closed = True
                # wake all waiters with a connection error
                with self._pending_lock:
                    for slot in self._pending.values():
                        slot["reply"] = {"type": "reply", "error": "connection closed"}
                        slot["event"].set()
                if self._exec_queue is not None:
                    self._exec_queue.put({"type": "exit"})
                return
            if msg.get("type") == "reply":
                with self._pending_lock:
                    slot = self._pending.pop(msg["req_id"], None)
                if slot is not None:
                    slot["reply"] = msg
                    slot["event"].set()
            elif msg.get("type") == "pubsub":
                # dispatch on a side thread: a callback that itself issues
                # a request must not block the only thread that can ever
                # deliver that request's reply
                self._pubsub_dispatch(msg)
            elif msg.get("type") == "cancel" and self._cancel_handler is not None:
                try:
                    self._cancel_handler(msg)
                except Exception:
                    pass
            elif (msg.get("type") == "reclaim_pipeline"
                    and self._reclaim_handler is not None):
                try:
                    self._reclaim_handler(msg)
                except Exception:
                    pass
            elif msg.get("type") == "profile" and self._profile_handler is not None:
                threading.Thread(
                    target=self._profile_handler, args=(msg,), daemon=True,
                    name="profile-request",
                ).start()
            elif self._exec_queue is not None:
                self._exec_queue.put(msg)

    def request(self, msg: dict, timeout: Optional[float] = None) -> dict:
        req_id = next(self._req_ids)
        msg["req_id"] = req_id
        slot = {"event": threading.Event(), "reply": None}
        with self._pending_lock:
            self._pending[req_id] = slot
        self.send(msg)
        if not slot["event"].wait(timeout):
            with self._pending_lock:
                self._pending.pop(req_id, None)
            raise TimeoutError("head did not reply")
        reply = slot["reply"]
        if reply.get("error"):
            raise ConnectionError(reply["error"])
        return reply

    # -- API ---------------------------------------------------------------
    def register_client(self, namespace: Optional[str] = None,
                        job_name: Optional[str] = None) -> dict:
        """Register this driver and learn its identity: the head assigns a
        job id and resolves the namespace (multi-tenancy attribution —
        everything this connection creates is owned by that job).  In
        proxy mode the per-connection driver subprocess enriches this
        frame in flight with its own pid/namespace."""
        import os as _os

        reply = self.request({
            "type": "register_client",
            "namespace": namespace,
            "job_name": job_name,
            "pid": _os.getpid(),
        }, timeout=60)
        return reply["value"]

    def _pubsub_dispatch(self, msg: dict) -> None:
        q = self._pubsub_queue
        if q is not None:
            q.put(msg)

    def _pubsub_loop(self) -> None:
        import logging

        log = logging.getLogger(__name__)
        while not self.closed:
            msg = self._pubsub_queue.get()
            if msg is None:
                return
            for cb in list(self._subscriptions.get(msg["channel"], [])):
                try:
                    cb(msg["data"])
                except Exception:
                    log.exception("pubsub callback for channel %r failed",
                                  msg["channel"])

    def subscribe(self, channel: str, callback) -> None:
        """Register a callback for a pubsub channel (Subscriber analog).
        Callbacks run on a dedicated dispatcher thread and may use the
        full client API."""
        with self._pubsub_lock:
            if self._pubsub_queue is None:
                self._pubsub_queue = queue.Queue()
                threading.Thread(target=self._pubsub_loop, daemon=True,
                                 name="pubsub-dispatch").start()
            first = channel not in self._subscriptions
            self._subscriptions.setdefault(channel, []).append(callback)
        if first:
            self.send({"type": "subscribe", "channel": channel})

    def unsubscribe(self, channel: str, callback=None) -> None:
        cbs = self._subscriptions.get(channel, [])
        if callback is None:
            cbs.clear()
        elif callback in cbs:
            cbs.remove(callback)
        if not cbs:
            self._subscriptions.pop(channel, None)
            self.send({"type": "unsubscribe", "channel": channel})

    def publish(self, channel: str, data) -> None:
        self.send({"type": "publish", "channel": channel, "data": data})

    def register_worker(self) -> None:
        self.send({
            "type": "register_worker",
            "worker_id": self.worker_id.hex(),
            "node_id": self.node_id,
        })

    def submit_task(self, spec: dict) -> None:
        self._buffer_submit("task", spec)

    def create_actor(self, spec: dict) -> None:
        self.send({"type": "create_actor", "spec": spec})

    def submit_actor_task(self, spec: dict) -> None:
        self._buffer_submit("actor_task", spec)

    def kill_actor(self, actor_id: bytes, no_restart: bool = True) -> None:
        self.send({"type": "kill_actor", "actor_id": actor_id, "no_restart": no_restart})

    def cancel_task(self, oid: bytes, force: bool = False,
                    recursive: bool = True) -> None:
        reply = self.request({"type": "cancel_task", "oid": oid,
                              "force": force, "recursive": recursive})
        err = reply.get("value")
        if err:
            raise ValueError(err)

    def seal(self, oid: bytes, loc: ObjectLocation, contained: List[bytes]) -> None:
        self.send({"type": "seal", "oid": oid, "loc": loc, "contained": contained})

    def get_locations(
        self, oids: List[bytes], timeout: Optional[float] = None
    ) -> Optional[Dict[bytes, ObjectLocation]]:
        """Blocks until all oids are sealed (or timeout -> None)."""
        reply = self.request({"type": "get_locations", "oids": oids, "timeout": timeout})
        if reply.get("timeout"):
            return None
        return reply["locations"]

    def wait(
        self, oids: List[bytes], num_returns: int, timeout: Optional[float]
    ) -> Tuple[List[bytes], Dict[bytes, ObjectLocation]]:
        reply = self.request({
            "type": "wait", "oids": oids, "num_returns": num_returns, "timeout": timeout,
        })
        return reply["ready"], reply["locations"]

    def kv_put(self, ns: str, key: bytes, value: bytes) -> None:
        self.send({"type": "kv_put", "ns": ns, "key": key, "value": value})

    def kv_get(self, ns: str, key: bytes, timeout: float = 30.0) -> Optional[bytes]:
        return self.request({"type": "kv_get", "ns": ns, "key": key}, timeout=timeout)["value"]

    def notify_blocked(self) -> None:
        self.send({"type": "blocked"})

    def notify_unblocked(self) -> None:
        self.send({"type": "unblocked"})

    _REF_FLUSH_THRESHOLD = 256

    def add_refs(self, oids: List[bytes], reason: str = "handle") -> None:
        """``reason`` labels the pin in the head's ownership audit
        ("handle" for live ObjectRefs, "task_arg" for spec-build arg
        pins); lifetime accounting is reason-agnostic.  Handle-reason
        announcements coalesce per flush tick (see _ref_add_buf); other
        reasons ship inline — their senders already batch per task."""
        if reason == "handle":
            with self._submit_lock:
                self._ref_add_buf.extend(oids)
                n = len(self._ref_add_buf)
            if n >= self._REF_FLUSH_THRESHOLD:
                self.flush_submits()
            elif n == len(oids):  # empty -> nonempty transition
                self._arm_flusher()
            return
        self.send({"type": "add_ref", "oids": oids, "reason": reason})

    def remove_refs(self, oids: List[bytes], reason: str = "handle") -> None:
        self.send({"type": "remove_ref", "oids": oids, "reason": reason})

    def broadcast(self, oid: bytes, timeout: float = 120.0) -> dict:
        return self.request({"type": "broadcast", "oid": oid,
                             "timeout": timeout}, timeout=timeout + 60)["value"]

    def create_pg(self, spec: dict) -> None:
        self.send({"type": "create_pg", "spec": spec})

    def remove_pg(self, pg_id: bytes) -> None:
        self.send({"type": "remove_pg", "pg_id": pg_id})

    def get_actor_by_name(self, name: str, namespace: Optional[str] = None):
        return self.request({"type": "get_actor_by_name", "name": name,
                             "namespace": namespace})["value"]

    def state_snapshot(self) -> dict:
        return self.request({"type": "state_snapshot"})["value"]

    def close(self) -> None:
        try:
            self.flush_submits()
        except Exception:
            pass
        self.closed = True
        self._flush_event.set()  # let the flusher thread exit
        from ray_tpu._private.netutil import force_close_connection

        # shutdown(2) wakes the recv thread; close alone would leave it
        # parked forever (the per-session thread leak)
        force_close_connection(self.conn)
        if self._pubsub_queue is not None:
            self._pubsub_queue.put(None)  # end the dispatcher thread
        try:
            self.conn.close()
        except Exception:
            pass
