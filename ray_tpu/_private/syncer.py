"""P2P resource/health sync mesh (RaySyncer analog).

Analog of the reference's ``src/ray/common/ray_syncer/ray_syncer.h:88``:
each node keeps a **versioned snapshot** of its own resource view +
liveness and gossips it to a few peers per tick; received snapshots merge
**version-gated** (only a strictly newer version of a node's state is
applied, and only the node itself ever authors its own snapshot).  The
head then consumes a *converged mesh view* — every agent's periodic
``syncer_report`` carries the whole map it has converged on, so the head
is no longer the sole fan-in for every heartbeat: any one agent's report
refreshes the head's liveness/utilization picture of ALL nodes it has
gossiped with, and a broken agent→head link no longer makes that agent
invisible.

Failure detection rides the same exchanges, with two distinct signals:

- **connection refused** while dialing a peer: the peer's listener socket
  is gone, i.e. the process is dead (a SIGKILL closes the socket).  After
  ``REFUSED_DEATH_COUNT`` consecutive refusals the observer records a
  *death* — an objective fact that gossips to everyone and reaches the
  head on the next report, far faster than the head's missed-pong
  timeout.
- **exchange timeout**: the peer accepted TCP (kernel backlog) but never
  answered — a hung/paused (SIGSTOP) process.  After
  ``TIMEOUT_SUSPECT_COUNT`` consecutive timeouts the observer records a
  *suspicion* tagged with its own id; suspicions union as they gossip, so
  the head sees how many distinct peers agree before acting (quorum).

Transport: one-shot TCP exchanges with HMAC-SHA256-signed pickle frames
(the cluster authkey signs every frame; an unauthenticated or torn frame
is treated as a failed exchange, never a crash).  ``multiprocessing``'s
``Client`` is deliberately not used here — its handshake has no timeout,
and a timeout IS the suspect signal.
"""

from __future__ import annotations

import hmac
import logging
import os
import pickle
import random
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu._private import events as events_mod
from ray_tpu._private.events import _float_env, _int_env
from ray_tpu._private.locks import make_lock

logger = logging.getLogger(__name__)

# Kill switch: the mesh is ON by default for every agent-joined (emulated
# multi-node) cluster; single-node sessions never construct a syncer.
ENABLED = os.environ.get("RAY_TPU_SYNCER", "1") not in ("0", "false", "no")

DEFAULT_TICK_S = _float_env("RAY_TPU_SYNCER_TICK_S", 0.5)
DEFAULT_FANOUT = _int_env("RAY_TPU_SYNCER_FANOUT", 2)
# dial/exchange deadline; also the longest one accept-handler can stall
DEFAULT_TIMEOUT_S = _float_env("RAY_TPU_SYNCER_TIMEOUT_S", 1.0)
# consecutive ECONNREFUSED dials before an observer declares a peer dead
REFUSED_DEATH_COUNT = 2
# consecutive exchange timeouts before an observer suspects a peer hung
TIMEOUT_SUSPECT_COUNT = 3
# head-side: distinct observers that must agree before a suspect is acted on
SUSPECT_QUORUM = 2

_SIG_LEN = 32  # sha256 digest
_MAX_FRAME = 8 << 20


# ---------------------------------------------------------------------------
# framed transport (authkey-signed pickle over a plain socket)
# ---------------------------------------------------------------------------

def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise EOFError("syncer peer closed mid-frame")
        buf += chunk
    return buf


def send_frame(sock: socket.socket, authkey: bytes, obj: dict) -> None:
    body = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sig = hmac.new(authkey, body, "sha256").digest()
    sock.sendall(struct.pack("!I", len(body)) + sig + body)


def recv_frame(sock: socket.socket, authkey: bytes) -> dict:
    header = _recv_exact(sock, 4 + _SIG_LEN)
    (n,) = struct.unpack("!I", header[:4])
    if n > _MAX_FRAME:
        raise OSError(f"oversized syncer frame ({n} bytes)")
    body = _recv_exact(sock, n)
    want = hmac.new(authkey, body, "sha256").digest()
    if not hmac.compare_digest(want, header[4:]):
        raise OSError("syncer frame failed authentication")
    return pickle.loads(body)


# ---------------------------------------------------------------------------
# the versioned store
# ---------------------------------------------------------------------------

class SyncerStore:
    """Per-node map of versioned snapshots + death/suspect rumors.

    Merge rules (the RaySyncer invariants):

    - a node's snapshot only ever advances to a strictly NEWER version,
      and only the node itself bumps its own version (``local_update``);
    - a death rumor keeps the EARLIEST observation (first observer wins —
      that timestamp is the detection-latency measurement) and is erased
      by any snapshot authored after it (resurrection-proof);
    - suspicions union per-observer with the freshest timestamp, and are
      erased when the suspect's snapshot advances (it answered someone).
    """

    def __init__(self, node_id: str):
        self.node_id = node_id
        self._lock = make_lock("syncer.store")
        self._snaps: Dict[str, dict] = {}
        self._deaths: Dict[str, dict] = {}       # node -> {"ts", "by"}
        self._suspects: Dict[str, Dict[str, float]] = {}  # node -> {observer: ts}
        self._version = 0

    def local_update(self, payload: Optional[dict] = None) -> dict:
        with self._lock:
            self._version += 1
            snap = dict(payload or {})
            snap.update(node_id=self.node_id, version=self._version,
                        ts=time.time())
            self._snaps[self.node_id] = snap
            # our own liveness trumps any stale rumor about us
            self._deaths.pop(self.node_id, None)
            self._suspects.pop(self.node_id, None)
            return snap

    def get(self, node_id: str) -> Optional[dict]:
        with self._lock:
            snap = self._snaps.get(node_id)
            return dict(snap) if snap else None

    def mark_dead(self, node_id: str, by: str,
                  ts: Optional[float] = None) -> bool:
        """Record a refused-connection death observation; returns True if
        this is news (first observation or earlier than the known one)."""
        if node_id == self.node_id:
            return False
        if ts is None:
            ts = time.time()
        with self._lock:
            cur = self._deaths.get(node_id)
            if cur is not None and cur["ts"] <= ts:
                return False
            self._deaths[node_id] = {"ts": ts, "by": by}
            return True

    def mark_suspect(self, node_id: str, by: str,
                     ts: Optional[float] = None) -> None:
        if node_id == self.node_id:
            return
        if ts is None:
            ts = time.time()
        with self._lock:
            obs = self._suspects.setdefault(node_id, {})
            obs[by] = max(obs.get(by, 0.0), ts)

    def merge(self, snaps: Optional[dict], deaths: Optional[dict] = None,
              suspects: Optional[dict] = None) -> int:
        """Fold a peer's view in; returns how many snapshots advanced."""
        applied = 0
        with self._lock:
            for nid, snap in (snaps or {}).items():
                if nid == self.node_id:
                    continue  # only we author our own state
                cur = self._snaps.get(nid)
                if cur is not None and snap.get("version", 0) <= cur.get("version", 0):
                    continue
                self._snaps[nid] = snap
                applied += 1
                d = self._deaths.get(nid)
                if d is not None and snap.get("ts", 0.0) > d["ts"]:
                    del self._deaths[nid]  # authored after the rumor
                    self._suspects.pop(nid, None)
                elif d is None and nid in self._suspects:
                    self._suspects.pop(nid, None)
            for nid, d in (deaths or {}).items():
                if nid == self.node_id:
                    continue
                snap = self._snaps.get(nid)
                if snap is not None and snap.get("ts", 0.0) > d.get("ts", 0.0):
                    continue  # seen alive after the rumor
                cur = self._deaths.get(nid)
                if cur is None or d["ts"] < cur["ts"]:
                    self._deaths[nid] = dict(d)
            for nid, obs in (suspects or {}).items():
                if nid == self.node_id:
                    continue
                mine = self._suspects.setdefault(nid, {})
                for by, ts in obs.items():
                    mine[by] = max(mine.get(by, 0.0), ts)
        return applied

    def snapshot(self) -> Tuple[dict, dict, dict]:
        """(snaps, deaths, suspects) copies — what gossip/report ships."""
        with self._lock:
            return (
                {k: dict(v) for k, v in self._snaps.items()},
                {k: dict(v) for k, v in self._deaths.items()},
                {k: dict(v) for k, v in self._suspects.items()},
            )

    def prune(self, keep: set) -> None:
        """Drop entries for nodes no longer in the peer directory — the
        head's membership view bounds the store (no unbounded rumor
        accumulation as nodes churn)."""
        keep = set(keep) | {self.node_id}
        with self._lock:
            for table in (self._snaps, self._deaths, self._suspects):
                for nid in [n for n in table if n not in keep]:
                    del table[nid]


# ---------------------------------------------------------------------------
# the per-node syncer
# ---------------------------------------------------------------------------

class ResourceSyncer:
    """One node's corner of the mesh: a listener serving push-pull gossip
    exchanges, a gossip loop dialing ``fanout`` random peers per tick,
    and (optionally) a per-tick ``report_fn`` shipping the converged view
    to the head.

    ``state_fn`` builds this node's own snapshot payload each tick
    (resources + host stats); it must be cheap — it runs at tick cadence.
    """

    def __init__(
        self,
        node_id: str,
        authkey: bytes,
        state_fn: Callable[[], dict],
        report_fn: Optional[Callable[[dict], None]] = None,
        host: str = "127.0.0.1",
        tick_s: Optional[float] = None,
        fanout: Optional[int] = None,
        timeout_s: Optional[float] = None,
        seed: Optional[int] = None,
    ):
        self.node_id = node_id
        self.authkey = authkey
        self.store = SyncerStore(node_id)
        self._state_fn = state_fn
        self._report_fn = report_fn
        self._tick = tick_s if tick_s is not None else DEFAULT_TICK_S
        self._fanout = fanout if fanout is not None else DEFAULT_FANOUT
        self._timeout = timeout_s if timeout_s is not None else DEFAULT_TIMEOUT_S
        # seeded per-instance: gossip partner choice must be reproducible
        # under a chaos schedule's seed (and never touches urandom per tick)
        self._rng = random.Random(seed if seed is not None
                                  else sum(node_id.encode()))
        self._peers_lock = make_lock("syncer.peers")
        self._peers: Dict[str, Tuple[str, int]] = {}
        self._fail: Dict[str, Dict[str, int]] = {}
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(16)
        self.addr: Tuple[str, int] = self._sock.getsockname()
        self._threads: List[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "ResourceSyncer":
        self.store.local_update(self._safe_state())
        for name, target in (("syncer-accept", self._accept_loop),
                             ("syncer-gossip", self._gossip_loop)):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    # -- membership ----------------------------------------------------
    def set_peers(self, peers: Dict[str, Tuple[str, int]]) -> None:
        """Replace the peer directory (the head broadcasts it on every
        membership change); the store prunes to the new membership."""
        peers = {nid: tuple(addr) for nid, addr in peers.items()
                 if nid != self.node_id}
        with self._peers_lock:
            self._peers = peers
            for nid in [n for n in self._fail if n not in peers]:
                del self._fail[nid]
        self.store.prune(set(peers))

    def peers(self) -> Dict[str, Tuple[str, int]]:
        with self._peers_lock:
            return dict(self._peers)

    # -- serving side --------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed (stop)
            t = threading.Thread(target=self._serve_exchange, args=(conn,),
                                 daemon=True, name="syncer-exchange")
            t.start()

    def _serve_exchange(self, conn: socket.socket) -> None:
        try:
            with conn:
                conn.settimeout(self._timeout)
                msg = recv_frame(conn, self.authkey)
                mtype = msg.get("type")
                if mtype == "syncer_sync":
                    self.store.merge(msg.get("snaps"), msg.get("deaths"),
                                     msg.get("suspects"))
                    snaps, deaths, suspects = self.store.snapshot()
                    send_frame(conn, self.authkey, {
                        "type": "syncer_sync_reply", "from": self.node_id,
                        "snaps": snaps, "deaths": deaths,
                        "suspects": suspects,
                    })
                else:
                    logger.warning("syncer: unknown exchange type %s", mtype)
        except (OSError, EOFError, pickle.UnpicklingError):
            pass  # torn/unauthenticated exchange: the dialer's problem

    # -- dialing side --------------------------------------------------
    def _gossip_loop(self) -> None:
        while not self._stop.wait(self._tick):
            try:
                self.store.local_update(self._safe_state())
                for nid, addr in self._pick_partners():
                    self._gossip_once(nid, addr)
                if self._report_fn is not None:
                    snaps, deaths, suspects = self.store.snapshot()
                    self._report_fn({"snaps": snaps, "deaths": deaths,
                                     "suspects": suspects})
            except Exception:
                logger.exception("syncer gossip tick failed")

    def _safe_state(self) -> dict:
        try:
            return dict(self._state_fn() or {})
        except Exception:
            return {}

    def _pick_partners(self) -> List[Tuple[str, Tuple[str, int]]]:
        with self._peers_lock:
            items = list(self._peers.items())
        if len(items) <= self._fanout:
            return items
        return self._rng.sample(items, self._fanout)

    def _gossip_once(self, nid: str, addr: Tuple[str, int]) -> None:
        try:
            sock = socket.create_connection(addr, timeout=self._timeout)
        except ConnectionRefusedError:
            self._on_refused(nid)
            return
        except OSError:
            self._on_timeout(nid)
            return
        try:
            with sock:
                sock.settimeout(self._timeout)
                snaps, deaths, suspects = self.store.snapshot()
                send_frame(sock, self.authkey, {
                    "type": "syncer_sync", "from": self.node_id,
                    "snaps": snaps, "deaths": deaths, "suspects": suspects,
                })
                reply = recv_frame(sock, self.authkey)
                self.store.merge(reply.get("snaps"), reply.get("deaths"),
                                 reply.get("suspects"))
        except (OSError, EOFError, pickle.UnpicklingError):
            self._on_timeout(nid)
            return
        with self._peers_lock:
            self._fail.pop(nid, None)

    def _fail_slot(self, nid: str) -> Dict[str, int]:
        with self._peers_lock:
            return self._fail.setdefault(nid, {"refused": 0, "timeout": 0})

    def _on_refused(self, nid: str) -> None:
        # >= not ==: counters only reset on a successful exchange, and a
        # flappy peer can erase the rumor (one authored snapshot) without
        # ever answering THIS observer's dial — at == the counter sails
        # past the threshold once and the observer can never re-detect
        slot = self._fail_slot(nid)
        slot["refused"] += 1
        if slot["refused"] >= REFUSED_DEATH_COUNT:
            if self.store.mark_dead(nid, by=self.node_id):
                events_mod.emit(
                    "syncer", "peer connection refused; marking dead",
                    severity="WARNING", entity_id=nid,
                    observer=self.node_id, refusals=slot["refused"])

    def _on_timeout(self, nid: str) -> None:
        slot = self._fail_slot(nid)
        slot["timeout"] += 1
        if slot["timeout"] >= TIMEOUT_SUSPECT_COUNT:
            # mark every tick past the threshold (re-establishes a
            # suspicion the suspect's own gossip erased); emit only on
            # the first crossing so the recorder isn't spammed per tick
            self.store.mark_suspect(nid, by=self.node_id)
            if slot["timeout"] == TIMEOUT_SUSPECT_COUNT:
                events_mod.emit(
                    "syncer", "peer unresponsive; marking suspect",
                    severity="WARNING", entity_id=nid,
                    observer=self.node_id, timeouts=slot["timeout"])
