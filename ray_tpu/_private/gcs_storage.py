"""Pluggable GCS metadata storage — the StoreClient layer.

Analog of ``src/ray/gcs/store_client/``: the GCS keeps its tables behind a
``StoreClient`` interface with an in-memory default
(``in_memory_store_client.h:31``) and a persistent backend for fault
tolerance (``redis_store_client.h:28``; flags in
``gcs_server_main.cc:26-33``).  Here the persistent backend is sqlite —
single-file, crash-safe, stdlib — enabled with
``RAY_TPU_GCS_PERSISTENCE=<path>`` or ``init(_gcs_persistence_path=...)``.
On restart the head replays the store (``GcsInitData`` analog,
``gcs_init_data.h:29``): the internal KV (function/class blobs survive),
job history, and prior actor records (marked DEAD — their processes died
with the old head).
"""

from __future__ import annotations

import pickle
import sqlite3
import threading
from typing import Dict, Iterable, List, Optional, Tuple


class StoreClient:
    """table -> key -> bytes.  Implementations must be thread-safe."""

    def put(self, table: str, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def replace_table(self, table: str, items: Iterable[Tuple[bytes, bytes]]) -> None:
        """Atomically replace a table's full contents (one transaction —
        deletions propagate and per-key commit cost is avoided)."""
        for k in self.keys(table):
            self.delete(table, k)
        for k, v in items:
            self.put(table, k, v)

    def get(self, table: str, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def delete(self, table: str, key: bytes) -> None:
        raise NotImplementedError

    def keys(self, table: str) -> List[bytes]:
        raise NotImplementedError

    def items(self, table: str) -> List[Tuple[bytes, bytes]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryStoreClient(StoreClient):
    def __init__(self):
        self._lock = threading.Lock()
        self._tables: Dict[str, Dict[bytes, bytes]] = {}

    def put(self, table, key, value):
        with self._lock:
            self._tables.setdefault(table, {})[key] = value

    def get(self, table, key):
        with self._lock:
            return self._tables.get(table, {}).get(key)

    def delete(self, table, key):
        with self._lock:
            self._tables.get(table, {}).pop(key, None)

    def keys(self, table):
        with self._lock:
            return list(self._tables.get(table, {}).keys())

    def items(self, table):
        with self._lock:
            return list(self._tables.get(table, {}).items())


class SqliteStoreClient(StoreClient):
    """Durable store; one connection guarded by a lock (writes are rare —
    control-plane metadata, not the data plane)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS kv ("
            " tbl TEXT NOT NULL, key BLOB NOT NULL, value BLOB NOT NULL,"
            " PRIMARY KEY (tbl, key))"
        )
        self._db.commit()

    def put(self, table, key, value):
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO kv (tbl, key, value) VALUES (?, ?, ?)",
                (table, key, value),
            )
            self._db.commit()

    def replace_table(self, table, items):
        with self._lock:
            self._db.execute("DELETE FROM kv WHERE tbl = ?", (table,))
            self._db.executemany(
                "INSERT INTO kv (tbl, key, value) VALUES (?, ?, ?)",
                [(table, k, v) for k, v in items],
            )
            self._db.commit()  # one fsync for the whole flush pass

    def get(self, table, key):
        with self._lock:
            row = self._db.execute(
                "SELECT value FROM kv WHERE tbl = ? AND key = ?", (table, key)
            ).fetchone()
        return row[0] if row else None

    def delete(self, table, key):
        with self._lock:
            self._db.execute("DELETE FROM kv WHERE tbl = ? AND key = ?", (table, key))
            self._db.commit()

    def keys(self, table):
        with self._lock:
            rows = self._db.execute(
                "SELECT key FROM kv WHERE tbl = ?", (table,)
            ).fetchall()
        return [r[0] for r in rows]

    def items(self, table):
        with self._lock:
            rows = self._db.execute(
                "SELECT key, value FROM kv WHERE tbl = ?", (table,)
            ).fetchall()
        return list(rows)

    def close(self):
        with self._lock:
            self._db.close()


def dumps(obj) -> bytes:
    return pickle.dumps(obj, protocol=5)


def loads(blob: bytes):
    return pickle.loads(blob)
