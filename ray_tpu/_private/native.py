"""ctypes bindings for the native store core (src/store_core/).

The native layer of the framework (SURVEY §2.1 expects C++ equivalents of
the plasma/runtime components).  The library builds on demand with the
baked-in toolchain (g++); everything degrades to the pure-Python
per-object-file path when a compiler is unavailable, so the native layer
is an accelerator, never a dependency.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

logger = logging.getLogger(__name__)

_SRC_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "src", "store_core",
)
_LIB_NAME = "libray_tpu_store.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _tsan_enabled() -> bool:
    """RAY_TPU_STORE_TSAN=1 builds the store core under ThreadSanitizer
    (+ clang thread-safety warnings when the compiler is clang): the
    sanitizer wiring the reference carries in its C++ tree (SURVEY §7).
    The instrumented .so caches under its own name so a sanitizer run
    never poisons the production build cache (or vice versa)."""
    return os.environ.get("RAY_TPU_STORE_TSAN", "") == "1"


def _compiler_is_clang(cxx: str) -> bool:
    try:
        probe = subprocess.run([cxx, "--version"], capture_output=True,
                               timeout=10, text=True)
        return "clang" in probe.stdout.lower()
    except (OSError, subprocess.SubprocessError):
        return False


def _build() -> Optional[str]:
    """Compile the .so next to its source (cached across sessions)."""
    tsan = _tsan_enabled()
    lib_name = _LIB_NAME.replace(".so", "_tsan.so") if tsan else _LIB_NAME
    out = os.path.join(_SRC_DIR, lib_name)
    src = os.path.join(_SRC_DIR, "store_core.cc")
    if not os.path.exists(src):
        return None
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    cxx = os.environ.get("CXX", "g++")
    cmd = [cxx, "-O2", "-fPIC", "-std=c++17"]
    if tsan:
        cmd = [cxx, "-g", "-O1", "-fPIC", "-std=c++17",
               "-fsanitize=thread", "-fno-omit-frame-pointer"]
        if _compiler_is_clang(cxx):
            cmd.append("-Wthread-safety")  # g++ has no such warning
    try:
        subprocess.run(cmd + ["-shared", "-o", out, src],
                       check=True, capture_output=True, timeout=120)
        return out
    except (OSError, subprocess.SubprocessError) as e:
        if tsan:
            # the operator explicitly asked for a sanitized store: a
            # silent fall-through to the Python path would read as "no
            # races found" while running uninstrumented code
            logger.warning(
                "RAY_TPU_STORE_TSAN=1 but the TSan build failed (%s) — "
                "the store is NOT sanitizer-instrumented", e)
        else:
            logger.info("native store core unavailable (build failed: %s)", e)
        return None


def load() -> Optional[ctypes.CDLL]:
    """The shared library, building it on first use; None when impossible."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        path = _build()
        if path is None:
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError as e:
            if _tsan_enabled():
                # a TSan .so usually can't dlopen into an uninstrumented
                # interpreter ("cannot allocate memory in static TLS
                # block"): the process must be started with libtsan
                # preloaded or the coverage silently doesn't exist
                logger.warning(
                    "RAY_TPU_STORE_TSAN=1 but the instrumented store "
                    "failed to load (%s) — run python under "
                    "LD_PRELOAD=libtsan.so.0 (path via `%s -print-file-"
                    "name=libtsan.so.0`); falling back to the "
                    "UNINSTRUMENTED Python store",
                    e, os.environ.get("CXX", "g++"))
            else:
                logger.info("native store core failed to load: %s", e)
            _build_failed = True
            return None
        lib.rtpu_store_create.restype = ctypes.c_void_p
        lib.rtpu_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.rtpu_store_put.restype = ctypes.c_int
        lib.rtpu_store_put.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.rtpu_store_seal.restype = ctypes.c_int
        lib.rtpu_store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_store_get.restype = ctypes.c_int
        lib.rtpu_store_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.rtpu_store_delete.restype = ctypes.c_int
        lib.rtpu_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        for fn in ("rtpu_store_bytes_used", "rtpu_store_capacity",
                   "rtpu_store_num_objects", "rtpu_store_num_free_blocks"):
            getattr(lib, fn).restype = ctypes.c_uint64
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.rtpu_store_close.restype = None
        lib.rtpu_store_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        # RefIndex (head registry hot maps; see store_core.cc)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.rtpu_refs_create.restype = ctypes.c_void_p
        lib.rtpu_refs_create.argtypes = []
        lib.rtpu_refs_ensure.restype = None
        lib.rtpu_refs_ensure.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32]
        lib.rtpu_refs_contains.restype = ctypes.c_int
        lib.rtpu_refs_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_refs_add.restype = None
        lib.rtpu_refs_add.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int64]
        lib.rtpu_refs_remove.restype = ctypes.c_int64
        lib.rtpu_refs_remove.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int64, u8p]
        for fn in ("rtpu_refs_seal", "rtpu_refs_unseal", "rtpu_refs_erase"):
            getattr(lib, fn).restype = ctypes.c_int
            getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_refs_get.restype = ctypes.c_int
        lib.rtpu_refs_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32)]
        lib.rtpu_refs_get_batch.restype = None
        lib.rtpu_refs_get_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32)]
        lib.rtpu_refs_size.restype = ctypes.c_uint64
        lib.rtpu_refs_size.argtypes = [ctypes.c_void_p]
        for fn in ("rtpu_refs_set_origin", "rtpu_refs_add_replica"):
            getattr(lib, fn).restype = ctypes.c_int
            getattr(lib, fn).argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32]
        for fn in ("rtpu_refs_pop_replica", "rtpu_refs_num_replicas",
                   "rtpu_refs_clear_replicas"):
            getattr(lib, fn).restype = ctypes.c_int
            getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_refs_replica_mask.restype = ctypes.c_uint64
        lib.rtpu_refs_replica_mask.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_refs_drop_slot.restype = None
        lib.rtpu_refs_drop_slot.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.rtpu_refs_locate.restype = None
        lib.rtpu_refs_locate.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int32)]
        lib.rtpu_refs_clear.restype = None
        lib.rtpu_refs_clear.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


class NativeArena:
    """Owner-side handle over one arena file (single-writer: the head).

    Consumers never need this class — they mmap the arena file directly
    and slice at the offsets the control plane hands them."""

    def __init__(self, path: str, capacity: int):
        lib = load()
        if lib is None:
            raise RuntimeError("native store core unavailable")
        self._lib = lib
        self.path = path
        self.capacity = capacity
        self._h = lib.rtpu_store_create(path.encode(), capacity)
        if not self._h:
            raise OSError(f"could not create arena at {path}")
        import mmap as mmap_mod

        # the fd stays open for the session: big-object puts write through
        # it (pwrite — the single-pass path that skips the mmap fault+zero
        # loop on fresh pages) while small puts memcpy into the mapping
        self.fd = os.open(path, os.O_RDWR)
        try:
            self._mm = mmap_mod.mmap(self.fd, capacity)
        except BaseException:
            os.close(self.fd)
            raise
        self.buf = memoryview(self._mm)
        self._closed = False

    def put(self, oid: bytes, size: int) -> Optional[int]:
        """Allocate+index; returns the offset or None when full."""
        off = ctypes.c_uint64()
        rc = self._lib.rtpu_store_put(self._h, oid, size, ctypes.byref(off))
        if rc != 0:
            return None
        return off.value

    def seal(self, oid: bytes) -> None:
        self._lib.rtpu_store_seal(self._h, oid)

    def get(self, oid: bytes):
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        sealed = ctypes.c_int()
        rc = self._lib.rtpu_store_get(self._h, oid, ctypes.byref(off),
                                      ctypes.byref(size), ctypes.byref(sealed))
        if rc != 0:
            return None
        return off.value, size.value, bool(sealed.value)

    def delete(self, oid: bytes) -> bool:
        return self._lib.rtpu_store_delete(self._h, oid) == 0

    def stats(self) -> dict:
        return {
            "bytes_used": self._lib.rtpu_store_bytes_used(self._h),
            "capacity": self._lib.rtpu_store_capacity(self._h),
            "num_objects": self._lib.rtpu_store_num_objects(self._h),
            "free_blocks": self._lib.rtpu_store_num_free_blocks(self._h),
        }

    def close(self, unlink: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self.buf.release()
            self._mm.close()
        except (BufferError, ValueError):
            pass  # exported zero-copy views still alive
        try:
            os.close(self.fd)
        except OSError:
            pass
        self._lib.rtpu_store_close(self._h, 1 if unlink else 0)


class RefIndex:
    """Thin handle over the C RefIndex (head registry hot maps).

    All batch calls take a single packed ``bytes`` of concatenated
    16-byte oids and run with the GIL released — one mutex hop per
    MESSAGE instead of one Python-lock hop per oid.  Callers own the
    16-byte-oid invariant (``object_store`` routes rare odd-size ids to
    the pure-Python twin)."""

    OID = 16
    NUM_REASONS = 8
    MAX_SLOTS = 64

    def __init__(self):
        lib = load()
        if lib is None:
            raise RuntimeError("native store core unavailable")
        self._lib = lib
        self._h = lib.rtpu_refs_create()
        if not self._h:
            raise OSError("could not create native ref index")

    def ensure(self, packed: bytes, n: int, reason: int) -> None:
        self._lib.rtpu_refs_ensure(self._h, packed, n, reason)

    def contains(self, oid: bytes) -> bool:
        return self._lib.rtpu_refs_contains(self._h, oid) == 1

    def add(self, packed: bytes, n: int, reason: int, delta: int) -> None:
        self._lib.rtpu_refs_add(self._h, packed, n, reason, delta)

    def remove(self, packed: bytes, n: int, reason: int,
               delta: int) -> list:
        """Returns the oids erased by this decrement (count<=0 while
        sealed) — the caller reaps exactly those."""
        buf = (ctypes.c_uint8 * (n * self.OID))()
        dead = self._lib.rtpu_refs_remove(
            self._h, packed, n, reason, delta, buf)
        raw = bytes(buf)
        return [raw[i * self.OID:(i + 1) * self.OID] for i in range(dead)]

    def seal(self, oid: bytes) -> int:
        return self._lib.rtpu_refs_seal(self._h, oid)

    def unseal(self, oid: bytes) -> int:
        return self._lib.rtpu_refs_unseal(self._h, oid)

    def erase(self, oid: bytes) -> int:
        return self._lib.rtpu_refs_erase(self._h, oid)

    def get(self, oid: bytes):
        """(count, sealed, pins[8]) or None."""
        count = ctypes.c_int64()
        sealed = ctypes.c_int32()
        pins = (ctypes.c_int32 * self.NUM_REASONS)()
        rc = self._lib.rtpu_refs_get(self._h, oid, ctypes.byref(count),
                                     ctypes.byref(sealed), pins)
        if rc != 0:
            return None
        return count.value, bool(sealed.value), list(pins)

    def get_batch(self, packed: bytes, n: int):
        """Parallel (counts, pins-rows); missing oids have count None."""
        counts = (ctypes.c_int64 * n)()
        pins = (ctypes.c_int32 * (n * self.NUM_REASONS))()
        self._lib.rtpu_refs_get_batch(self._h, packed, n, counts, pins)
        missing = -(1 << 63)
        out_counts = [None if c == missing else c for c in counts]
        out_pins = [pins[i * self.NUM_REASONS:(i + 1) * self.NUM_REASONS]
                    for i in range(n)]
        return out_counts, out_pins

    def size(self) -> int:
        return self._lib.rtpu_refs_size(self._h)

    def set_origin(self, oid: bytes, slot: int) -> int:
        return self._lib.rtpu_refs_set_origin(self._h, oid, slot)

    def add_replica(self, oid: bytes, slot: int) -> int:
        return self._lib.rtpu_refs_add_replica(self._h, oid, slot)

    def pop_replica(self, oid: bytes) -> int:
        return self._lib.rtpu_refs_pop_replica(self._h, oid)

    def num_replicas(self, oid: bytes) -> int:
        return self._lib.rtpu_refs_num_replicas(self._h, oid)

    def replica_mask(self, oid: bytes) -> int:
        return self._lib.rtpu_refs_replica_mask(self._h, oid)

    def clear_replicas(self, oid: bytes) -> int:
        return self._lib.rtpu_refs_clear_replicas(self._h, oid)

    def drop_slot(self, slot: int) -> None:
        self._lib.rtpu_refs_drop_slot(self._h, slot)

    def locate(self, packed: bytes, n: int, prefer_slot: int) -> list:
        out = (ctypes.c_int32 * n)()
        self._lib.rtpu_refs_locate(self._h, packed, n, prefer_slot, out)
        return list(out)

    def clear(self) -> None:
        self._lib.rtpu_refs_clear(self._h)


def available() -> bool:
    return load() is not None
