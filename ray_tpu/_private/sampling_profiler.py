"""In-process sampling profilers — the py-spy analog of the reference's
``dashboard/modules/reporter/profile_manager.py``, in two duty cycles:

- :class:`SamplingProfiler`: dense on-demand sampling (the dashboard's
  ``/api/profile`` endpoint and ``RAY_TPU_SAMPLE_PROFILE`` ad-hoc worker
  profiling).  ~1-2% overhead at the default 2 ms period — fine for a
  bounded window.
- :class:`ContinuousProfiler`: the always-on mode.  Short sample bursts
  (~50 ms) every couple of seconds, with the inter-burst interval backing
  off while the process's stacks stay static, keep the duty cycle (and
  therefore the overhead) in the 0.1% range.  Folded stacks are
  time-bucketed and batch-shipped over the control connection to the
  head's :class:`~ray_tpu.util.profile_store.ProfileStore`, so every
  process in the cluster has a queryable flamegraph history by default.

Both sample ``sys._current_frames()`` on a timer thread, aggregating
``file:function`` call stacks across all threads of the process.  Pure
Python and dependency-free (py-spy is not in the image).
"""

from __future__ import annotations

import collections
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

# absolute frame-walk ceiling: stacks deeper than this are pathological
# (runaway recursion) and sampling them whole would make the sampler the
# hot spot the profile reports
_HARD_DEPTH = 128

# mid-stack truncation marker: deep stacks keep their root-most AND
# leaf-most frames around it, so collapsed output still merges at the
# real roots (main/_loop) instead of at fabricated mid-call roots
TRUNCATION_MARKER = "..."


def fold_frame(frame, max_depth: int) -> str:
    """One thread's stack as a ``|``-joined root→leaf frame string.

    ``max_depth`` bounds the OUTPUT, not the walk: the walk always
    reaches the root (up to ``_HARD_DEPTH``), and an over-deep stack is
    truncated in the MIDDLE — root-most frames kept (they name the call
    tree), leaf-most frames kept (they name the hot spot), a ``...``
    marker between.  Truncating leaf→root (the old behaviour) dropped
    the roots of deep stacks, merging unrelated call trees at whatever
    mid-call frame happened to land at the cut."""
    stack: List[str] = []
    f = frame
    while f is not None and len(stack) < _HARD_DEPTH:
        code = f.f_code
        stack.append(f"{code.co_filename.rsplit('/', 1)[-1]}:{code.co_name}")
        f = f.f_back
    stack.reverse()  # walked leaf→root; folded form reads root→leaf
    if len(stack) > max_depth:
        head = max(1, max_depth // 2)
        tail = max(1, max_depth - head - 1)
        stack = stack[:head] + [TRUNCATION_MARKER] + stack[-tail:]
    return "|".join(stack)


def is_idle_leaf(frame) -> bool:
    """True when the frame is parked in a blocking wait (consuming no
    core) — the sampler-side twin of the store's idle classification."""
    from ray_tpu.util.profile_store import _IDLE_LEAF_FILES, _IDLE_LEAF_FUNCS

    code = frame.f_code
    return (code.co_name in _IDLE_LEAF_FUNCS
            or code.co_filename.rsplit("/", 1)[-1] in _IDLE_LEAF_FILES)


def sample_stacks(exclude: frozenset, max_depth: int,
                  counter: "collections.Counter[str]") -> int:
    """One sampling tick: fold every thread's current stack (except the
    excluded sampler threads) into ``counter``.  Returns the number of
    threads caught OFF a blocking wait — the per-tick core-occupancy
    signal behind the duty-cycle ledger's utilization estimate."""
    busy = 0
    for tid, frame in sys._current_frames().items():
        if tid in exclude:
            continue
        if not is_idle_leaf(frame):
            busy += 1
        counter[fold_frame(frame, max_depth)] += 1
    return busy


class SamplingProfiler:
    def __init__(self, period_s: float = 0.002, max_depth: int = 16):
        self.period_s = period_s
        self.max_depth = max_depth
        self.samples: "collections.Counter[str]" = collections.Counter()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="sampling-profiler"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None

    def _loop(self) -> None:
        me = frozenset((threading.get_ident(),))
        while not self._stop.wait(self.period_s):
            sample_stacks(me, self.max_depth, self.samples)

    def report(self, top: int = 40) -> List[Dict]:
        total = sum(self.samples.values()) or 1
        return [
            {"stack": stack, "samples": n, "pct": round(100.0 * n / total, 2)}
            for stack, n in self.samples.most_common(top)
        ]

    def report_text(self, top: int = 40) -> str:
        lines = [f"{r['samples']:6d} {r['pct']:5.1f}%  {r['stack']}"
                 for r in self.report(top)]
        return "\n".join(lines)

    def report_collapsed(self) -> str:
        """Folded-stack lines (``frame;frame;frame N``) — the format
        speedscope and Brendan Gregg's flamegraph.pl consume directly."""
        return collapsed_from_report(
            [{"stack": stack, "samples": n}
             for stack, n in self.samples.most_common()])


def collapsed_from_report(report: List[Dict]) -> str:
    """Convert ``report()``-shaped rows (``{stack, samples, ...}`` —
    what workers ship back over the control connection) into
    folded-stack lines.  The single formatting site for the collapsed
    format."""
    return "\n".join(
        f"{r['stack'].replace('|', ';')} {r['samples']}" for r in report)


def profile_for(duration_s: float, period_s: float = 0.002,
                top: int = 40) -> List[Dict]:
    """Blocking one-shot profile of this process (dashboard endpoint body)."""
    p = SamplingProfiler(period_s=period_s).start()
    time.sleep(duration_s)
    p.stop()
    return p.report(top)


# ---------------------------------------------------------------------------
# always-on continuous mode
# ---------------------------------------------------------------------------

def _env_float(name: str, default: float) -> float:
    import os

    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


def continuous_enabled() -> bool:
    """Continuous profiling is ON by default; RAY_TPU_CONT_PROFILE=0
    disables it cluster-wide (the env is inherited by spawned workers)."""
    import os

    return os.environ.get("RAY_TPU_CONT_PROFILE", "1") not in (
        "0", "false", "no")


class ContinuousProfiler:
    """Low-duty-cycle burst sampler with adaptive backoff.

    Every ``interval_s`` it samples for ``burst_s`` at ``period_s``
    (default duty cycle 50ms / 2s = 2.5%, at a 5 ms period — ~0.05% CPU
    given the per-tick cost is ~20-40 us).  When consecutive bursts see
    an identical stack fingerprint (an idle process parked on the same
    waits), the interval doubles up to ``max_interval_s``; any change
    snaps it back — a process that starts working is re-sampled at full
    cadence within one backed-off interval.

    Samples fold into per-``bucket_s`` time buckets; ``ship()`` (called
    from the burst loop every ``ship_every_s``) drains finished buckets
    to ``send_fn`` as a ``profile_report`` control frame, or hands them
    to ``ingest_fn`` directly (the head profiles itself without a
    loopback connection).

    The sampler doubles as the process's GIL-pressure probe: each burst
    compares the wall time its ticks actually took against the schedule
    they asked for.  Tick lateness beyond the timer period means this
    thread sat runnable-but-unscheduled — on a CPython process that is
    GIL wait, and the published ``ray_tpu_gil_lateness_frac`` gauge is
    the "core-bound" number the doctor's ``gil_saturation`` rule reads.
    """

    def __init__(self, origin: str,
                 send_fn: Optional[Callable[[dict], None]] = None,
                 ingest_fn: Optional[Callable] = None, *,
                 burst_s: float = 0.05, interval_s: float = 2.0,
                 period_s: float = 0.005, max_depth: int = 24,
                 bucket_s: float = 60.0, ship_every_s: Optional[float] = None,
                 max_interval_s: Optional[float] = None,
                 closed_fn: Optional[Callable[[], bool]] = None):
        self.origin = origin
        self._send = send_fn
        self._ingest = ingest_fn
        self.burst_s = _env_float("RAY_TPU_CONT_PROFILE_BURST_S", burst_s)
        self.interval_s = _env_float("RAY_TPU_CONT_PROFILE_INTERVAL_S",
                                     interval_s)
        self.period_s = _env_float("RAY_TPU_CONT_PROFILE_PERIOD_S", period_s)
        self.max_depth = max_depth
        self.bucket_s = bucket_s
        if ship_every_s is None:
            from ray_tpu.util.metrics import push_interval_s

            ship_every_s = push_interval_s()
        self.ship_every_s = ship_every_s
        self.max_interval_s = (max_interval_s if max_interval_s is not None
                               else 8 * self.interval_s)
        self._closed = closed_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # bucket start ts -> Counter[folded stack]
        self._buckets: Dict[float, "collections.Counter[str]"] = {}
        # bucket start ts -> [ticks, busy_ticks]: the per-bucket duty
        # denominators the ledger divides by (a tick is "busy" when at
        # least one thread was caught off a blocking wait — process
        # core-occupancy, immune to GIL-inflated thread counts)
        self._bucket_ticks: Dict[float, List[float]] = {}
        self._ticks = 0          # sampling ticks taken (duty accounting)
        self._cur_interval = self.interval_s
        self._last_fingerprint: Optional[frozenset] = None
        self._static_bursts = 0
        self._last_ship = 0.0
        self._ship_failures = 0
        self.lateness_frac = 0.0  # last burst's GIL-pressure estimate

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ContinuousProfiler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="cont-profiler")
        self._thread.start()
        from ray_tpu._private import events

        events.emit("profile", "continuous profiler started",
                    severity="DEBUG", entity_id=self.origin,
                    burst_s=self.burst_s, interval_s=self.interval_s,
                    period_s=self.period_s)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self.ship(final=True)
        from ray_tpu._private import events

        events.emit("profile", "continuous profiler stopped",
                    severity="DEBUG", entity_id=self.origin)

    # -- sampling ----------------------------------------------------------
    def _burst(self) -> None:
        """One sampling burst; also refreshes the GIL-lateness estimate."""
        exclude = frozenset((threading.get_ident(),))
        counter: "collections.Counter[str]" = collections.Counter()
        t0 = time.perf_counter()
        deadline = t0 + self.burst_s
        ticks = 0
        busy_ticks = 0
        while time.perf_counter() < deadline and not self._stop.is_set():
            if sample_stacks(exclude, self.max_depth, counter):
                busy_ticks += 1
            ticks += 1
            self._stop.wait(self.period_s)
        elapsed = time.perf_counter() - t0
        if ticks:
            # expected wall for the burst is ticks * period (+ sample
            # bodies, already inside elapsed); the excess is time this
            # thread waited for the GIL / the scheduler
            expected = ticks * self.period_s
            self.lateness_frac = max(
                0.0, min(1.0, (elapsed - expected) / max(elapsed, 1e-9)))
        if not counter:
            return
        bucket = (time.time() // self.bucket_s) * self.bucket_s
        with self._lock:
            cur = self._buckets.setdefault(bucket, collections.Counter())
            cur.update(counter)
            bt = self._bucket_ticks.setdefault(bucket, [0.0, 0.0])
            bt[0] += ticks
            bt[1] += busy_ticks
            self._ticks += ticks
        self._adapt(counter)
        self._publish_gauges()

    def _adapt(self, counter) -> None:
        """Interval backoff: static stacks across bursts double the
        interval (idle process); any change resets it."""
        fp = frozenset(counter)
        if fp == self._last_fingerprint:
            self._static_bursts += 1
            if (self._static_bursts >= 2
                    and self._cur_interval < self.max_interval_s):
                self._cur_interval = min(self.max_interval_s,
                                         self._cur_interval * 2)
                from ray_tpu._private import events

                events.emit("profile", "profiler backoff",
                            severity="DEBUG", entity_id=self.origin,
                            interval_s=self._cur_interval)
        else:
            if self._cur_interval != self.interval_s:
                from ray_tpu._private import events

                events.emit("profile", "profiler backoff reset",
                            severity="DEBUG", entity_id=self.origin)
            self._cur_interval = self.interval_s
            self._static_bursts = 0
        self._last_fingerprint = fp

    def _publish_gauges(self) -> None:
        from ray_tpu.util.metrics import Gauge

        Gauge("ray_tpu_gil_lateness_frac",
              "fraction of the profiler burst wall spent waiting for the "
              "GIL/scheduler (off-GIL pressure estimate)").set(
            round(self.lateness_frac, 4))
        # duty cycle: what share of wall the profiler spends sampling at
        # its CURRENT (backed-off) interval — the overhead meter the
        # grafana profiling row charts
        Gauge("ray_tpu_profiler_duty_frac",
              "profiler sampling duty cycle (burst wall / interval)").set(
            round(self.burst_s / max(self._cur_interval, 1e-9), 5))
        # named-lock wait/hold gauges ride the same publish tick so the
        # lock-timing plane needs no thread of its own
        from ray_tpu._private import locks

        locks.publish_lock_metrics()

    def _loop(self) -> None:
        while not self._stop.wait(self._cur_interval):
            if self._closed is not None and self._closed():
                return
            try:
                self._burst()
                now = time.monotonic()
                if now - self._last_ship >= self.ship_every_s:
                    self._last_ship = now
                    self.ship()
            except Exception:
                # the profiler must never take its host process down
                pass

    # -- shipping ----------------------------------------------------------
    def drain(self) -> tuple:
        """Take the accumulated buckets + duty meta (resets the rings)."""
        with self._lock:
            buckets_map, self._buckets = self._buckets, {}
            ticks_map, self._bucket_ticks = self._bucket_ticks, {}
            ticks, self._ticks = self._ticks, 0
        buckets = [
            {"ts": ts, "folded": dict(c),
             "ticks": ticks_map.get(ts, [0.0, 0.0])[0],
             "busy_ticks": ticks_map.get(ts, [0.0, 0.0])[1]}
            for ts, c in sorted(buckets_map.items())]
        meta = {"period_s": self.period_s, "burst_s": self.burst_s,
                "interval_s": self._cur_interval, "ticks": ticks,
                "lateness_frac": round(self.lateness_frac, 4)}
        return buckets, meta

    def ship(self, final: bool = False) -> None:
        """Drain buckets to the head (send_fn) or straight into a local
        ProfileStore (ingest_fn).  A failed send drops this batch — the
        next burst re-fills; profiles are advisory, never worth a
        backlog on the control connection."""
        buckets, meta = self.drain()
        if not buckets:
            return
        try:
            if self._ingest is not None:
                self._ingest(self.origin, buckets, meta)
            elif self._send is not None:
                self._send({"type": "profile_report", "origin": self.origin,
                            "buckets": buckets, "meta": meta})
        except Exception:
            self._ship_failures += 1
            if not final:
                from ray_tpu._private import events

                events.emit("profile", "profile ship failed",
                            severity="DEBUG", entity_id=self.origin,
                            failures=self._ship_failures)
