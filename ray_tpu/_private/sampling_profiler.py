"""In-process sampling profiler — the py-spy-analog used by the dashboard's
on-demand profiling endpoint (reference
``dashboard/modules/reporter/profile_manager.py``) and, via
``RAY_TPU_SAMPLE_PROFILE``, for ad-hoc worker profiling.

Samples ``sys._current_frames()`` on a timer thread, aggregating
``file:function`` call stacks across all threads of the process.  Pure
Python and dependency-free (py-spy is not in the image), so the overhead is
~1-2% at the default 2 ms period — fine for on-demand use, not meant to be
always-on.
"""

from __future__ import annotations

import collections
import sys
import threading
import time
from typing import Dict, List, Optional


class SamplingProfiler:
    def __init__(self, period_s: float = 0.002, max_depth: int = 8):
        self.period_s = period_s
        self.max_depth = max_depth
        self.samples: "collections.Counter[str]" = collections.Counter()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="sampling-profiler"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None

    def _loop(self) -> None:
        me = threading.get_ident()
        while not self._stop.wait(self.period_s):
            for tid, frame in sys._current_frames().items():
                if tid == me:
                    continue
                stack: List[str] = []
                f = frame
                while f is not None and len(stack) < self.max_depth:
                    code = f.f_code
                    stack.append(
                        f"{code.co_filename.rsplit('/', 1)[-1]}:{code.co_name}"
                    )
                    f = f.f_back
                self.samples["|".join(reversed(stack))] += 1

    def report(self, top: int = 40) -> List[Dict]:
        total = sum(self.samples.values()) or 1
        return [
            {"stack": stack, "samples": n, "pct": round(100.0 * n / total, 2)}
            for stack, n in self.samples.most_common(top)
        ]

    def report_text(self, top: int = 40) -> str:
        lines = [f"{r['samples']:6d} {r['pct']:5.1f}%  {r['stack']}"
                 for r in self.report(top)]
        return "\n".join(lines)

    def report_collapsed(self) -> str:
        """Folded-stack lines (``frame;frame;frame N``) — the format
        speedscope and Brendan Gregg's flamegraph.pl consume directly."""
        return collapsed_from_report(
            [{"stack": stack, "samples": n}
             for stack, n in self.samples.most_common()])


def collapsed_from_report(report: List[Dict]) -> str:
    """Convert ``report()``-shaped rows (``{stack, samples, ...}`` —
    what workers ship back over the control connection) into
    folded-stack lines.  The single formatting site for the collapsed
    format."""
    return "\n".join(
        f"{r['stack'].replace('|', ';')} {r['samples']}" for r in report)


def profile_for(duration_s: float, period_s: float = 0.002,
                top: int = 40) -> List[Dict]:
    """Blocking one-shot profile of this process (dashboard endpoint body)."""
    p = SamplingProfiler(period_s=period_s).start()
    time.sleep(duration_s)
    p.stop()
    return p.report(top)
