"""Cross-node object transfer — the ObjectManager analog.

The reference moves objects between nodes with chunked gRPC pushes between
per-node ObjectManagers, located through an ownership-based directory
(``src/ray/object_manager/object_manager.h:117``, ``pull_manager.h:48``,
``ownership_based_object_directory.h:37``).  Here every node (head and
agents) runs an :class:`ObjectServer` over its local shm directory, the
head's registry is the location directory, and consumers pull with
:func:`pull_object`: chunked transfer straight into a segment in the
consumer's local shm namespace, attached zero-copy afterwards.

Connections to remote servers are cached per address (the reference pools
its gRPC channels the same way).
"""

from __future__ import annotations

import logging
import mmap
import os
import socket as socket_mod
import threading
from multiprocessing.connection import Client as MPClient, Connection, Listener
from typing import Dict, Optional, Tuple

from ray_tpu._private.shm import ShmSegment, shm_dir

logger = logging.getLogger(__name__)

CHUNK = 32 << 20  # 32 MiB sendfile spans (object_manager chunk analog)

Addr = Tuple[str, int]


class ObjectServer:
    """Serves local shm segments to remote pullers (PushManager analog)."""

    def __init__(self, host: str, authkey: bytes):
        self._listener = Listener((host, 0), family="AF_INET", authkey=authkey, backlog=16)
        self.addr: Addr = self._listener.address
        self._shutdown = False
        self._thread = threading.Thread(target=self._accept_loop, daemon=True,
                                        name="object-server")
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._shutdown:
            try:
                conn = self._listener.accept()
            except Exception:
                if self._shutdown:
                    return
                continue
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: Connection) -> None:
        try:
            while not self._shutdown:
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    return
                if msg.get("arena"):
                    # a slice of the local arena file (native store path)
                    path = os.path.join(shm_dir(), os.path.basename(msg["arena"]))
                    base, size = int(msg["off"]), int(msg["size"])
                else:
                    # names are flat session-scoped identifiers; never
                    # serve a path outside the local shm dir
                    path = os.path.join(shm_dir(), os.path.basename(msg.get("name", "")))
                    base, size = 0, -1
                try:
                    fd = os.open(path, os.O_RDONLY)
                except OSError:
                    conn.send({"ok": False, "error": f"no such segment {path}"})
                    continue
                try:
                    file_size = os.fstat(fd).st_size
                    if size < 0:
                        size = file_size
                    if base < 0 or base + size > file_size:
                        conn.send({"ok": False,
                                   "error": f"range [{base}, {base + size}) "
                                            f"outside file of {file_size}"})
                        continue
                    if msg.get("raw"):
                        # kernel-side file->socket copy: no userspace pread
                        # buffer, no mp framing — on a CPU-starved host the
                        # copy count IS the bandwidth ceiling
                        conn.send({"ok": True, "size": size, "raw": True})
                        cfd = conn.fileno()
                        off = 0
                        while off < size:
                            sent = os.sendfile(
                                cfd, fd, base + off, min(CHUNK, size - off))
                            if sent == 0:  # peer gone / truncation race
                                conn.close()
                                return
                            off += sent
                        continue
                    conn.send({"ok": True, "size": size})
                    off = 0
                    while off < size:
                        data = os.pread(fd, min(CHUNK, size - off), base + off)
                        if not data:  # hole/truncation race: fail the stream
                            conn.close()
                            return
                        conn.send_bytes(data)
                        off += len(data)
                finally:
                    os.close(fd)
        finally:
            try:
                conn.close()
            except Exception:
                pass

    def close(self) -> None:
        self._shutdown = True
        from ray_tpu._private.netutil import unblock_listener

        unblock_listener(self._listener)  # wakes the parked accept loop


# -- pull client -----------------------------------------------------------

# addr -> [connection-or-None, per-connection lock].  The per-connection
# lock serializes the dial and request/response pairs on one wire; pulls
# from different nodes proceed concurrently.
_conns: Dict[Addr, list] = {}
_conns_lock = threading.Lock()
_authkey: Optional[bytes] = None


def configure(authkey: bytes) -> None:
    """Set the cluster authkey used when dialing remote object servers."""
    global _authkey
    _authkey = authkey


def _connection(addr: Addr) -> Tuple[Connection, threading.Lock]:
    import time
    from multiprocessing import AuthenticationError

    # the global lock only guards the dict; the (possibly slow) TCP dial
    # happens under the per-address lock so an unreachable node can't
    # stall pulls from healthy nodes
    with _conns_lock:
        entry = _conns.get(addr)
        if entry is None:
            entry = [None, threading.Lock()]
            _conns[addr] = entry
    conn, lock = entry
    if conn is not None:
        return conn, lock
    with lock:
        if entry[0] is None:
            # the mp handshake occasionally loses a challenge race when
            # several processes dial one listener at once — retry, it is
            # not a credentials problem (same guard as CoreClient)
            for attempt in range(5):
                try:
                    entry[0] = MPClient(tuple(addr), family="AF_INET", authkey=_authkey)
                    break
                except (AuthenticationError, OSError, EOFError):
                    if attempt == 4:
                        with _conns_lock:
                            _conns.pop(addr, None)  # next pull redials
                        raise
                    # redial backoff: waiters need this conn live anyway
                    time.sleep(0.05 * (attempt + 1))  # raylint: disable=R4
        return entry[0], lock


def _evict(addr: Addr, conn: Connection) -> None:
    """Drop a connection whose request/response stream may be desynced (a
    failed mid-transfer pull leaves undrained chunks on the wire)."""
    with _conns_lock:
        entry = _conns.get(addr)
        if entry is not None and entry[0] is conn:
            del _conns[addr]
    try:
        conn.close()
    except Exception:
        pass


def _arena_local_copy(dst_path: str, arena: tuple, size: int) -> bool:
    """Same-HOST fast path: the origin's arena file is visible in this
    host's tmpfs (emulated multi-node, or co-located nodes), so the slice
    copies kernel-side with copy_file_range — no sockets, one copy.  The
    reference gets the same effect from its per-node shared plasma store.
    Returns False (caller takes the socket path) if the arena isn't local
    or the copy fails.  ``RAY_TPU_FORCE_REMOTE_PULL=1`` disables it
    (benchmarks that specifically measure the network plane)."""
    if size < 0 or os.environ.get("RAY_TPU_FORCE_REMOTE_PULL"):
        return False
    # the origin's arena path is host-absolute; when it exists HERE the
    # origin shares this host (namespaced shm dirs notwithstanding —
    # arena names are session+node scoped, so a hit can't be a stranger)
    src = arena[0] if os.path.isabs(arena[0]) and os.path.exists(arena[0]) \
        else os.path.join(shm_dir(), os.path.basename(arena[0]))
    base = int(arena[1])
    try:
        sfd = os.open(src, os.O_RDONLY)
    except OSError:
        return False
    dfd = -1
    tmp = f"{dst_path}.lcopy.{os.getpid()}.{os.urandom(2).hex()}"
    try:
        if base + size > os.fstat(sfd).st_size:
            return False
        dfd = os.open(tmp, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o600)
        off_in, off_out = base, 0
        while off_out < size:
            n = os.copy_file_range(sfd, dfd, size - off_out,
                                   offset_src=off_in, offset_dst=off_out)
            if n == 0:
                raise OSError("copy_file_range returned 0")
            off_in += n
            off_out += n
        os.rename(tmp, dst_path)
        return True
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    finally:
        os.close(sfd)
        if dfd >= 0:
            os.close(dfd)


def pull_object(name: str, addr: Addr, expected_size: int = -1,
                arena: Optional[tuple] = None) -> None:
    """Fetch segment ``name`` from the object server at ``addr`` into the
    local shm dir (PullManager analog: chunked transfer into local plasma).
    With ``arena=(path, offset)`` the origin payload is an arena slice
    rather than a standalone file; the local copy is still a file named
    ``name``.

    Idempotent: if the local copy already exists, returns immediately.
    """
    addr = tuple(addr)
    path = os.path.join(shm_dir(), name)
    if os.path.exists(path):
        return
    if arena is not None and _arena_local_copy(path, arena, expected_size):
        return
    tmp = f"{path}.pull.{os.getpid()}.{threading.get_ident()}.{os.urandom(2).hex()}"
    conn, req_lock = _connection(addr)
    fd = -1
    try:
        with req_lock:
            if arena is not None:
                conn.send({"arena": arena[0], "off": arena[1],
                           "size": expected_size, "raw": True})
            else:
                conn.send({"name": name, "raw": True})
            # req_lock IS the pull-protocol serializer for this conn —
            # interleaved requests would desync the chunk stream
            hdr = conn.recv()  # raylint: disable=R4
            if not hdr.get("ok"):
                # clean protocol state — no chunks follow an error header
                raise FileNotFoundError(hdr.get("error", f"pull of {name} failed"))
            size = hdr["size"]
            if expected_size >= 0 and size != expected_size:
                _evict(addr, conn)  # chunks are in flight; wire is dirty
                raise IOError(f"pull of {name}: size {size} != expected {expected_size}")
            fd = os.open(tmp, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
            if hdr.get("raw"):
                # raw payload stream straight into the mmapped destination:
                # one kernel->user copy total (the server side is sendfile)
                if size > 0:
                    os.ftruncate(fd, size)
                    sock = socket_mod.socket(fileno=os.dup(conn.fileno()))
                    try:
                        with mmap.mmap(fd, size) as mm:
                            view = memoryview(mm)
                            try:
                                off = 0
                                while off < size:
                                    n = sock.recv_into(  # raylint: disable=R4
                                        view[off:], min(CHUNK, size - off))
                                    if n == 0:
                                        raise EOFError(
                                            f"pull of {name}: stream ended "
                                            f"at {off}/{size}")
                                    off += n
                            finally:
                                view.release()  # else mmap.close() raises
                    finally:
                        sock.close()  # closes only the dup'd fd
            else:
                off = 0
                while off < size:
                    data = conn.recv_bytes()
                    os.write(fd, data)
                    off += len(data)
    except (OSError, EOFError) as e:
        if not isinstance(e, FileNotFoundError):
            _evict(addr, conn)
        if fd >= 0:
            os.close(fd)
            fd = -1
            try:
                os.unlink(tmp)
            except OSError:
                pass
        raise
    finally:
        if fd >= 0:
            os.close(fd)
    try:
        os.rename(tmp, path)  # atomic publish; concurrent pullers race benignly
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def reset() -> None:
    """Drop cached connections (tests / shutdown)."""
    with _conns_lock:
        for conn, _ in _conns.values():
            try:
                if conn is not None:
                    conn.close()
            except Exception:
                pass
        _conns.clear()
