"""Usage stats: opt-out, local-only session feature report.

Analog of the reference's usage-stats subsystem
(``python/ray/_private/usage/usage_lib.py`` — opt-out telemetry of which
libraries/features a session used).  This environment has zero egress, so
the report is written to the session directory (``usage_report.json``)
instead of posted; the schema mirrors the reference's payload so an
operator can aggregate reports themselves.

Disable with ``RAY_TPU_USAGE_STATS_ENABLED=0``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Set

_lock = threading.Lock()
_features: Set[str] = set()
_counters: Dict[str, int] = {}


def reset() -> None:
    """Start a fresh session scope (called at head start)."""
    with _lock:
        _features.clear()
        _counters.clear()


def enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") not in ("0", "false")


def record_feature(name: str) -> None:
    """Mark a library/feature as used this session (cheap, idempotent).

    Works from any process: worker/driver processes also publish the flag
    to the head's KV (namespace ``usage``) so features exercised inside
    actors — e.g. a Tune trial importing rllib — reach the head's report."""
    if not enabled():
        return
    with _lock:
        if name in _features:
            return
        _features.add(name)
    try:
        from ray_tpu._private.worker import global_worker

        if global_worker.connected and global_worker.client is not None:
            global_worker.client.kv_put("usage", name.encode(), b"1")
    except Exception:
        pass  # never let telemetry break the caller


def record_set(name: str, n: int) -> None:
    """Set a counter to an absolute value (session totals at shutdown)."""
    if not enabled():
        return
    with _lock:
        _counters[name] = n


def record_count(name: str, n: int = 1) -> None:
    if not enabled():
        return
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def write_report(session_dir: str, extra: Dict = None) -> str:
    """Write the session's usage report (called at head shutdown)."""
    if not enabled():
        return ""
    with _lock:
        payload = {
            "schema_version": "0.1",
            "timestamp": time.time(),
            "features_used": sorted(_features),
            "counters": dict(_counters),
            **(extra or {}),
        }
    path = os.path.join(session_dir, "usage_report.json")
    try:
        with open(path, "w") as f:
            json.dump(payload, f, indent=2)
    except OSError:
        return ""
    return path
