"""runtime_env pip plugin: hash-keyed cached virtualenvs at worker spawn.

Reference counterpart: ``python/ray/_private/runtime_env/pip.py`` (venv
per requirements hash, installed by the per-node agent before the worker
starts).  Here the slow work runs in the WORKER's own bootstrap process —
``python -m ray_tpu._private.runtime_env_setup --pip-spec ... `` creates or
reuses the venv, then ``exec``s the venv's interpreter into the normal
worker entrypoint — so the head's scheduler thread never blocks on an
install.  A boot-looping pip spec trips the existing 3-strikes
runtime_env circuit breaker (``node.py`` spawn_failures) and fails the
task with an actionable error.

Venvs are created with ``--system-site-packages`` so the base image's
jax/numpy remain importable, keyed by the sha1 of the canonicalized spec,
and marked ready atomically; concurrent creators serialize on an
``fcntl`` lock.
"""

from __future__ import annotations

import fcntl
import hashlib
import json
import os
import subprocess
import sys
import venv
from typing import Any, Dict, List, Tuple, Union

DEFAULT_BASE_DIR = "/tmp/ray_tpu/runtime_envs"

PipSpec = Union[List[str], Dict[str, Any]]


def parse_pip_spec(pip: PipSpec) -> Tuple[List[str], List[str]]:
    if isinstance(pip, dict):
        return list(pip.get("packages") or []), list(
            pip.get("pip_install_options") or [])
    return list(pip), []


def pip_env_key(pip: PipSpec) -> str:
    packages, options = parse_pip_spec(pip)
    blob = json.dumps({"packages": sorted(packages), "options": options},
                      sort_keys=True).encode()
    return hashlib.sha1(blob).hexdigest()[:16]


def ensure_pip_env(pip: PipSpec, base_dir: str = DEFAULT_BASE_DIR) -> Tuple[str, bool]:
    """Create (or reuse) the venv for ``pip``; returns ``(python_exe,
    created)``.  Raises on install failure."""
    packages, options = parse_pip_spec(pip)
    key = pip_env_key(pip)
    env_dir = os.path.join(base_dir, f"pip-{key}")
    python = os.path.join(env_dir, "bin", "python")
    ready = os.path.join(env_dir, ".ready")
    if os.path.exists(ready):
        return python, False
    os.makedirs(base_dir, exist_ok=True)
    lock_path = os.path.join(base_dir, f"pip-{key}.lock")
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if os.path.exists(ready):  # another process won the race
                return python, False
            venv.EnvBuilder(
                system_site_packages=True, with_pip=True, clear=True
            ).create(env_dir)
            if packages:
                proc = subprocess.run(
                    [python, "-m", "pip", "install", "--no-input",
                     *options, *packages],
                    capture_output=True, text=True, timeout=600,
                )
                if proc.returncode != 0:
                    raise RuntimeError(
                        f"pip install {packages} failed:\n"
                        f"{proc.stderr[-2000:]}")
            with open(ready, "w") as f:
                f.write(json.dumps({"packages": packages, "options": options}))
            return python, True
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def worker_argv(pip: Union[PipSpec, None]) -> List[str]:
    """Worker process argv — shared by the head and node agents so local
    and remote spawns can never drift.  A pip spec boots through this
    module's shim (venv build in the worker process), which then execs the
    venv's python into the normal entrypoint."""
    if pip:
        return [sys.executable, "-m", "ray_tpu._private.runtime_env_setup",
                "--pip-spec", json.dumps(pip)]
    return [sys.executable, "-m", "ray_tpu._private.worker"]


def main() -> None:
    """Worker bootstrap: materialize the env, then exec the venv's python
    into the worker entrypoint (argv after ``--``)."""
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--pip-spec", required=True, help="JSON pip spec")
    p.add_argument("--base-dir", default=DEFAULT_BASE_DIR)
    args = p.parse_args()
    try:
        python, _created = ensure_pip_env(
            json.loads(args.pip_spec), base_dir=args.base_dir)
    except Exception as e:  # noqa: BLE001 — the exit code IS the signal
        print(f"runtime_env pip setup failed: {e}", file=sys.stderr)
        raise SystemExit(77)
    os.execv(python, [python, "-m", "ray_tpu._private.worker"])


if __name__ == "__main__":
    main()
