"""runtime_env pip plugin: hash-keyed cached virtualenvs at worker spawn.

Reference counterpart: ``python/ray/_private/runtime_env/pip.py`` (venv
per requirements hash, installed by the per-node agent before the worker
starts).  Here the slow work runs in the WORKER's own bootstrap process —
``python -m ray_tpu._private.runtime_env_setup --pip-spec ... `` creates or
reuses the venv, then ``exec``s the venv's interpreter into the normal
worker entrypoint — so the head's scheduler thread never blocks on an
install.  A boot-looping pip spec trips the existing 3-strikes
runtime_env circuit breaker (``node.py`` spawn_failures) and fails the
task with an actionable error.

Venvs are created with ``--system-site-packages`` so the base image's
jax/numpy remain importable, keyed by the sha1 of the canonicalized spec,
and marked ready atomically; concurrent creators serialize on an
``fcntl`` lock.
"""

from __future__ import annotations

import fcntl
import hashlib
import json
import os
import subprocess
import sys
import venv
from typing import Any, Dict, List, Tuple, Union

DEFAULT_BASE_DIR = "/tmp/ray_tpu/runtime_envs"

PipSpec = Union[List[str], Dict[str, Any]]


def parse_pip_spec(pip: PipSpec) -> Tuple[List[str], List[str]]:
    if isinstance(pip, dict):
        return list(pip.get("packages") or []), list(
            pip.get("pip_install_options") or [])
    return list(pip), []


def pip_env_key(pip: PipSpec) -> str:
    packages, options = parse_pip_spec(pip)
    blob = json.dumps({"packages": sorted(packages), "options": options},
                      sort_keys=True).encode()
    return hashlib.sha1(blob).hexdigest()[:16]


def ensure_pip_env(pip: PipSpec, base_dir: str = DEFAULT_BASE_DIR) -> Tuple[str, bool]:
    """Create (or reuse) the venv for ``pip``; returns ``(python_exe,
    created)``.  Raises on install failure."""
    packages, options = parse_pip_spec(pip)
    key = pip_env_key(pip)
    env_dir = os.path.join(base_dir, f"pip-{key}")
    python = os.path.join(env_dir, "bin", "python")
    ready = os.path.join(env_dir, ".ready")
    if os.path.exists(ready):
        return python, False
    os.makedirs(base_dir, exist_ok=True)
    lock_path = os.path.join(base_dir, f"pip-{key}.lock")
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if os.path.exists(ready):  # another process won the race
                return python, False
            venv.EnvBuilder(
                system_site_packages=True, with_pip=True, clear=True
            ).create(env_dir)
            if packages:
                proc = subprocess.run(
                    [python, "-m", "pip", "install", "--no-input",
                     *options, *packages],
                    capture_output=True, text=True, timeout=600,
                )
                if proc.returncode != 0:
                    raise RuntimeError(
                        f"pip install {packages} failed:\n"
                        f"{proc.stderr[-2000:]}")
            with open(ready, "w") as f:
                f.write(json.dumps({"packages": packages, "options": options}))
            return python, True
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


# ---------------------------------------------------------------------------
# conda (reference python/ray/_private/runtime_env/conda.py)

CondaSpec = Union[str, Dict[str, Any]]


def conda_env_key(conda: CondaSpec) -> str:
    blob = json.dumps(conda, sort_keys=True).encode()
    return hashlib.sha1(blob).hexdigest()[:16]


def _conda_exe() -> str:
    import shutil as _shutil

    exe = os.environ.get("RAY_TPU_CONDA_EXE") or _shutil.which("conda")
    if not exe:
        raise RuntimeError(
            "runtime_env['conda'] requires a conda binary on this node's "
            "PATH (or RAY_TPU_CONDA_EXE); none found. Use "
            "runtime_env['pip'] for venv-based isolation instead.")
    return exe


def ensure_conda_env(conda: CondaSpec,
                     base_dir: str = DEFAULT_BASE_DIR) -> Tuple[str, bool]:
    """Resolve (or create) the conda env for ``conda``; returns
    ``(python_exe, created)``.

    - str: the NAME of a pre-existing conda env — resolved, never built
      (the reference's named-env path).
    - dict: an environment.yml body — materialized under a hash-keyed
      prefix exactly once per node, flock-serialized like the pip cache.
    """
    exe = _conda_exe()
    if isinstance(conda, str):
        proc = subprocess.run(
            [exe, "run", "-n", conda, "python", "-c",
             "import sys; print(sys.executable)"],
            capture_output=True, text=True, timeout=120,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"conda env {conda!r} not usable:\n{proc.stderr[-2000:]}")
        return proc.stdout.strip().splitlines()[-1], False
    key = conda_env_key(conda)
    prefix = os.path.join(base_dir, f"conda-{key}")
    python = os.path.join(prefix, "bin", "python")
    ready = os.path.join(base_dir, f"conda-{key}.ready")
    if os.path.exists(ready):
        return python, False
    os.makedirs(base_dir, exist_ok=True)
    with open(os.path.join(base_dir, f"conda-{key}.lock"), "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if os.path.exists(ready):
                return python, False
            spec_path = os.path.join(base_dir, f"conda-{key}.yml")
            with open(spec_path, "w") as f:
                json.dump(conda, f)  # YAML is a JSON superset
            # a prior failed create leaves a partial prefix that conda
            # refuses to reuse — clear it (EnvBuilder(clear=True) analog)
            import shutil

            shutil.rmtree(prefix, ignore_errors=True)
            proc = subprocess.run(
                [exe, "env", "create", "--prefix", prefix, "--file",
                 spec_path, "--yes"],
                capture_output=True, text=True, timeout=1800,
            )
            if proc.returncode != 0:
                raise RuntimeError(
                    f"conda env create failed:\n{proc.stderr[-2000:]}")
            open(ready, "w").close()
            return python, True
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def worker_argv(pip: Union[PipSpec, None],
                conda: Union[CondaSpec, None] = None) -> List[str]:
    """Worker process argv — shared by the head and node agents so local
    and remote spawns can never drift.  A pip/conda spec boots through
    this module's shim (env build in the worker process), which then
    execs that env's python into the normal entrypoint."""
    if pip:
        return [sys.executable, "-m", "ray_tpu._private.runtime_env_setup",
                "--pip-spec", json.dumps(pip)]
    if conda:
        return [sys.executable, "-m", "ray_tpu._private.runtime_env_setup",
                "--conda-spec", json.dumps(conda)]
    return [sys.executable, "-m", "ray_tpu._private.worker"]


def main() -> None:
    """Worker bootstrap: materialize the env, then exec the env's python
    into the worker entrypoint."""
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--pip-spec", help="JSON pip spec")
    p.add_argument("--conda-spec", help="JSON conda spec (name or env dict)")
    p.add_argument("--base-dir", default=DEFAULT_BASE_DIR)
    args = p.parse_args()
    try:
        if args.pip_spec:
            python, _ = ensure_pip_env(
                json.loads(args.pip_spec), base_dir=args.base_dir)
        elif args.conda_spec:
            python, _ = ensure_conda_env(
                json.loads(args.conda_spec), base_dir=args.base_dir)
        else:
            raise ValueError("one of --pip-spec/--conda-spec is required")
    except Exception as e:  # noqa: BLE001 — the exit code IS the signal
        print(f"runtime_env setup failed: {e}", file=sys.stderr)
        raise SystemExit(77)
    os.execv(python, [python, "-m", "ray_tpu._private.worker"])


if __name__ == "__main__":
    main()
