"""Node resource detection, with TPU chips first-class.

Analog of ``python/ray/_private/resource_spec.py`` — its
``_autodetect_num_gpus`` (``resource_spec.py:268``) counts GPUs; here we
autodetect **TPU chips** instead, per SURVEY §2.1's TPU-port note: probe
``/dev/accel*`` (TPU VM PCI devices) and ``/dev/vfio``, honor the
``TPU_VISIBLE_CHIPS`` restriction the way the reference honors
``CUDA_VISIBLE_DEVICES``, and allow an explicit override via
``RAY_TPU_NUM_TPUS`` (tunneled/remote-attached chips are invisible in /dev).
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional, Tuple


def autodetect_num_tpus() -> int:
    if "RAY_TPU_NUM_TPUS" in os.environ:
        return int(os.environ["RAY_TPU_NUM_TPUS"])
    visible = os.environ.get("TPU_VISIBLE_CHIPS")
    if visible:
        return len([c for c in visible.split(",") if c.strip()])
    accel = glob.glob("/dev/accel*")
    if accel:
        return len(accel)
    vfio = glob.glob("/dev/vfio/[0-9]*")
    if vfio:
        return len(vfio)
    return 0


def autodetect_resources(
    num_cpus: Optional[int],
    num_tpus: Optional[int],
    resources: Optional[Dict[str, float]],
) -> Tuple[Dict[str, float], List[int]]:
    """Returns (resource totals, tpu chip ids)."""
    total: Dict[str, float] = dict(resources or {})
    total["CPU"] = float(num_cpus if num_cpus is not None else os.cpu_count() or 1)
    n_tpus = num_tpus if num_tpus is not None else autodetect_num_tpus()
    total["TPU"] = float(n_tpus)
    try:
        import psutil  # type: ignore

        total.setdefault("memory", float(psutil.virtual_memory().available))
    except Exception:
        total.setdefault("memory", 8.0 * 1024**3)
    # Use the real chip ids this process can see, not synthetic ones —
    # workers are later isolated via TPU_VISIBLE_CHIPS=<these ids>.
    visible = os.environ.get("TPU_VISIBLE_CHIPS")
    if num_tpus is None and visible:
        ids = [int(c) for c in visible.split(",") if c.strip()]
    else:
        ids = list(range(int(n_tpus)))
    return total, ids
