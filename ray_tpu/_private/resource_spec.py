"""Node resource detection, with TPU chips first-class.

Analog of ``python/ray/_private/resource_spec.py`` — its
``_autodetect_num_gpus`` (``resource_spec.py:268``) counts GPUs; here we
autodetect **TPU chips** instead, per SURVEY §2.1's TPU-port note: probe
``/dev/accel*`` (TPU VM PCI devices) and ``/dev/vfio``, honor the
``TPU_VISIBLE_CHIPS`` restriction the way the reference honors
``CUDA_VISIBLE_DEVICES``, and allow an explicit override via
``RAY_TPU_NUM_TPUS`` (tunneled/remote-attached chips are invisible in /dev).
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional, Tuple


def autodetect_tpus() -> Tuple[int, List[int]]:
    """(chip count, chip ids) from one consistent source — the count and the
    id list must never disagree (the ids become TPU_VISIBLE_CHIPS grants)."""
    if "RAY_TPU_NUM_TPUS" in os.environ:
        n = int(os.environ["RAY_TPU_NUM_TPUS"])
        return n, list(range(n))
    visible = os.environ.get("TPU_VISIBLE_CHIPS")
    if visible:
        ids = [int(c) for c in visible.split(",") if c.strip()]
        return len(ids), ids
    accel = glob.glob("/dev/accel*")
    if accel:
        return len(accel), list(range(len(accel)))
    vfio = glob.glob("/dev/vfio/[0-9]*")
    if vfio:
        return len(vfio), list(range(len(vfio)))
    return 0, []


def autodetect_num_tpus() -> int:
    return autodetect_tpus()[0]


def autodetect_resources(
    num_cpus: Optional[int],
    num_tpus: Optional[int],
    resources: Optional[Dict[str, float]],
) -> Tuple[Dict[str, float], List[int]]:
    """Returns (resource totals, tpu chip ids)."""
    total: Dict[str, float] = dict(resources or {})
    total["CPU"] = float(num_cpus if num_cpus is not None else os.cpu_count() or 1)
    if num_tpus is not None:
        n_tpus, ids = num_tpus, list(range(num_tpus))
    else:
        n_tpus, ids = autodetect_tpus()
    total["TPU"] = float(n_tpus)
    try:
        import psutil  # type: ignore

        total.setdefault("memory", float(psutil.virtual_memory().available))
    except Exception:
        total.setdefault("memory", 8.0 * 1024**3)
    return total, ids


def host_stats() -> Dict[str, float]:
    """Live host utilization for node heartbeats (the per-node metrics
    the reference's dashboard agent reports,
    ``dashboard/modules/reporter/reporter_agent.py:253``).  /proc reads
    only — no psutil dependency on the hot heartbeat path."""
    stats: Dict[str, float] = {"cpu_count": float(os.cpu_count() or 1)}
    try:
        with open("/proc/loadavg") as f:
            stats["load_1m"] = float(f.read().split()[0])
    except (OSError, ValueError, IndexError):
        pass
    try:
        mem: Dict[str, int] = {}
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, rest = line.partition(":")
                if k in ("MemTotal", "MemAvailable"):
                    mem[k] = int(rest.split()[0])  # kB
        if mem:
            stats["mem_total_mb"] = round(mem.get("MemTotal", 0) / 1024, 1)
            stats["mem_available_mb"] = round(
                mem.get("MemAvailable", 0) / 1024, 1)
    except (OSError, ValueError):
        pass
    return stats
