"""Node resource detection, with TPU chips first-class.

Analog of ``python/ray/_private/resource_spec.py`` — its
``_autodetect_num_gpus`` (``resource_spec.py:268``) counts GPUs; here we
autodetect **TPU chips** instead, per SURVEY §2.1's TPU-port note: probe
``/dev/accel*`` (TPU VM PCI devices) and ``/dev/vfio``, honor the
``TPU_VISIBLE_CHIPS`` restriction the way the reference honors
``CUDA_VISIBLE_DEVICES``, and allow an explicit override via
``RAY_TPU_NUM_TPUS`` (tunneled/remote-attached chips are invisible in /dev).
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional, Tuple


def autodetect_tpus() -> Tuple[int, List[int]]:
    """(chip count, chip ids) from one consistent source — the count and the
    id list must never disagree (the ids become TPU_VISIBLE_CHIPS grants)."""
    if "RAY_TPU_NUM_TPUS" in os.environ:
        n = int(os.environ["RAY_TPU_NUM_TPUS"])
        return n, list(range(n))
    visible = os.environ.get("TPU_VISIBLE_CHIPS")
    if visible:
        ids = [int(c) for c in visible.split(",") if c.strip()]
        return len(ids), ids
    accel = glob.glob("/dev/accel*")
    if accel:
        return len(accel), list(range(len(accel)))
    vfio = glob.glob("/dev/vfio/[0-9]*")
    if vfio:
        return len(vfio), list(range(len(vfio)))
    return 0, []


def autodetect_num_tpus() -> int:
    return autodetect_tpus()[0]


def autodetect_resources(
    num_cpus: Optional[int],
    num_tpus: Optional[int],
    resources: Optional[Dict[str, float]],
) -> Tuple[Dict[str, float], List[int]]:
    """Returns (resource totals, tpu chip ids)."""
    total: Dict[str, float] = dict(resources or {})
    total["CPU"] = float(num_cpus if num_cpus is not None else os.cpu_count() or 1)
    if num_tpus is not None:
        n_tpus, ids = num_tpus, list(range(num_tpus))
    else:
        n_tpus, ids = autodetect_tpus()
    total["TPU"] = float(n_tpus)
    try:
        import psutil  # type: ignore

        total.setdefault("memory", float(psutil.virtual_memory().available))
    except Exception:
        total.setdefault("memory", 8.0 * 1024**3)
    return total, ids


def host_stats() -> Dict[str, float]:
    """Live host utilization for node heartbeats (the per-node metrics
    the reference's dashboard agent reports,
    ``dashboard/modules/reporter/reporter_agent.py:253``).  /proc reads
    only — no psutil dependency on the hot heartbeat path."""
    stats: Dict[str, float] = {"cpu_count": float(os.cpu_count() or 1)}
    try:
        with open("/proc/loadavg") as f:
            stats["load_1m"] = float(f.read().split()[0])
    except (OSError, ValueError, IndexError):
        pass
    try:
        mem: Dict[str, int] = {}
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, rest = line.partition(":")
                if k in ("MemTotal", "MemAvailable"):
                    mem[k] = int(rest.split()[0])  # kB
        if mem:
            stats["mem_total_mb"] = round(mem.get("MemTotal", 0) / 1024, 1)
            stats["mem_available_mb"] = round(
                mem.get("MemAvailable", 0) / 1024, 1)
    except (OSError, ValueError):
        pass
    return stats


# ---------------------------------------------------------------------------
# per-process resource sampling (reporter_agent's per-worker stats analog)
# ---------------------------------------------------------------------------

# gauge names the sampler emits; the head's top view, the TSDB trend rules
# (doctor RSS-growth), and the Grafana factory all key off these
PROC_RSS_MB = "ray_tpu_proc_rss_mb"
PROC_CPU_PCT = "ray_tpu_proc_cpu_percent"
PROC_OPEN_FDS = "ray_tpu_proc_open_fds"

_PROC_METRIC_HELP = {
    PROC_RSS_MB: "resident set size per tracked process (MB)",
    PROC_CPU_PCT: "CPU utilization per tracked process (%)",
    PROC_OPEN_FDS: "open file descriptors per tracked process",
}


class ProcSampler:
    """Reads RSS, CPU%, and open-fd counts for a set of pids from /proc.

    CPU% needs a delta between consecutive samples (utime+stime ticks over
    wall time), so one sampler instance persists across a sampling loop's
    lifetime; pids that vanish between samples simply drop out.  /proc
    only — no psutil on a 5 s always-on path."""

    def __init__(self):
        self._prev: Dict[int, Tuple[float, float]] = {}  # pid -> (ticks_s, t)
        try:
            self._hz = float(os.sysconf("SC_CLK_TCK")) or 100.0
        except (ValueError, OSError, AttributeError):
            self._hz = 100.0
        self._page_kb = (os.sysconf("SC_PAGE_SIZE") // 1024
                         if hasattr(os, "sysconf") else 4)

    def sample(self, pid: int) -> Optional[Dict[str, float]]:
        """One process's stats, or None when the pid is gone."""
        import time as _time

        try:
            with open(f"/proc/{pid}/stat") as f:
                stat = f.read()
        except OSError:
            self._prev.pop(pid, None)
            return None
        # comm may contain spaces/parens: fields start after the LAST ')'
        fields = stat[stat.rfind(")") + 2:].split()
        # fields[11]=utime, fields[12]=stime (0-based after comm/state),
        # fields[21]=rss pages
        try:
            cpu_s = (float(fields[11]) + float(fields[12])) / self._hz
            rss_mb = float(fields[21]) * self._page_kb / 1024.0
        except (IndexError, ValueError):
            return None
        now = _time.monotonic()
        cpu_pct = 0.0
        prev = self._prev.get(pid)
        if prev is not None and now > prev[1]:
            cpu_pct = max(0.0, (cpu_s - prev[0]) / (now - prev[1]) * 100.0)
        self._prev[pid] = (cpu_s, now)
        out = {"rss_mb": round(rss_mb, 2), "cpu_pct": round(cpu_pct, 2)}
        try:
            out["open_fds"] = float(len(os.listdir(f"/proc/{pid}/fd")))
        except OSError:
            pass
        return out

    def forget_missing(self, live_pids) -> None:
        """Drop CPU baselines for pids no longer tracked."""
        live = set(live_pids)
        for pid in [p for p in self._prev if p not in live]:
            del self._prev[p]


def resource_metrics_snapshot(sampler: ProcSampler,
                              entities: List[Tuple[Dict[str, str], int]],
                              ) -> Tuple[Dict[str, dict], List[tuple]]:
    """Sample ``entities`` ((tags, pid) pairs) into a registry-snapshot-
    shaped dict, so the result rides the existing ``metrics_report`` path
    and folds into the head's merged registry AND its TSDB unchanged.
    Also returns the per-entity raw stats as (tags, pid, stats) for
    callers that keep a live cache (the head's top view)."""
    values_by_metric: Dict[str, Dict[tuple, float]] = {
        PROC_RSS_MB: {}, PROC_CPU_PCT: {}, PROC_OPEN_FDS: {}}
    raw: List[Tuple[Dict[str, str], int, Dict[str, float]]] = []
    seen_pids = []
    for tags, pid in entities:
        stats = sampler.sample(pid)
        if stats is None:
            continue
        seen_pids.append(pid)
        key = tuple(sorted({**tags, "pid": str(pid)}.items()))
        values_by_metric[PROC_RSS_MB][key] = stats["rss_mb"]
        values_by_metric[PROC_CPU_PCT][key] = stats["cpu_pct"]
        if "open_fds" in stats:
            values_by_metric[PROC_OPEN_FDS][key] = stats["open_fds"]
        raw.append((tags, pid, stats))
    sampler.forget_missing(seen_pids)
    snap = {
        name: {"type": "gauge", "help": _PROC_METRIC_HELP[name],
               "values": values}
        for name, values in values_by_metric.items() if values
    }
    return snap, raw
