"""GCS — the cluster control-plane tables.

In-process analog of the reference's GCS server
(``src/ray/gcs/gcs_server/gcs_server.h:75``): actor directory + restart FSM
state (``gcs_actor_manager.h:270``), node table (``gcs_node_manager.h:39``),
job/worker bookkeeping, the internal KV used for function shipping
(``gcs_kv_manager.h:139`` — the reference's FunctionActorManager stores
pickled functions there, ``python/ray/_private/function_manager.py:56``),
and placement-group records (``gcs_placement_group_manager.h:221``).

Storage is the ``InMemoryStoreClient`` analog
(``src/ray/gcs/store_client/in_memory_store_client.h:31``); a pluggable
persistent backend is the round-2+ path to GCS fault tolerance.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class ActorInfo:
    actor_id: bytes
    name: Optional[str]
    class_name: str
    state: str = "PENDING_CREATION"  # PENDING_CREATION/ALIVE/RESTARTING/DEAD
    node_id: Optional[str] = None
    worker_id: Optional[bytes] = None
    max_restarts: int = 0
    num_restarts: int = 0
    creation_spec: Optional[dict] = None  # kept for restart (lineage)
    death_cause: Optional[str] = None


@dataclass
class NodeInfo:
    node_id: str
    resources: Dict[str, float]
    alive: bool = True
    start_time: float = field(default_factory=time.time)


@dataclass
class TaskInfo:
    task_id: bytes
    name: str
    state: str = "PENDING"  # PENDING/RUNNING/FINISHED/FAILED
    node_id: Optional[str] = None
    start_time: float = field(default_factory=time.time)
    end_time: Optional[float] = None


@dataclass
class PlacementGroupInfo:
    pg_id: bytes
    bundles: List[Dict[str, float]]
    strategy: str
    state: str = "PENDING"  # PENDING/CREATED/REMOVED
    bundle_nodes: List[Optional[str]] = field(default_factory=list)
    name: Optional[str] = None


class GcsTables:
    """All control-plane tables behind one lock (single head process)."""

    def __init__(self):
        self.lock = threading.RLock()
        self.kv: Dict[str, Dict[bytes, bytes]] = {}  # namespace -> key -> val
        self.actors: Dict[bytes, ActorInfo] = {}
        self.named_actors: Dict[str, bytes] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.tasks: Dict[bytes, TaskInfo] = {}
        self.placement_groups: Dict[bytes, PlacementGroupInfo] = {}

    # ---- internal KV (GcsInternalKVManager analog) ----
    def kv_put(self, ns: str, key: bytes, value: bytes) -> None:
        with self.lock:
            self.kv.setdefault(ns, {})[key] = value

    def kv_get(self, ns: str, key: bytes) -> Optional[bytes]:
        with self.lock:
            return self.kv.get(ns, {}).get(key)

    def kv_keys(self, ns: str) -> List[bytes]:
        with self.lock:
            return list(self.kv.get(ns, {}).keys())

    def kv_del(self, ns: str, key: bytes) -> None:
        with self.lock:
            self.kv.get(ns, {}).pop(key, None)

    # ---- snapshots for the state API (dashboard/state_aggregator analog) ----
    def snapshot(self) -> Dict[str, Any]:
        with self.lock:
            return {
                "actors": list(self.actors.values()),
                "nodes": list(self.nodes.values()),
                "tasks": list(self.tasks.values()),
                "placement_groups": list(self.placement_groups.values()),
            }
