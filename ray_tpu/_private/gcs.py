"""GCS — the cluster control-plane tables.

In-process analog of the reference's GCS server
(``src/ray/gcs/gcs_server/gcs_server.h:75``): actor directory + restart FSM
state (``gcs_actor_manager.h:270``), node table (``gcs_node_manager.h:39``),
job/worker bookkeeping, the internal KV used for function shipping
(``gcs_kv_manager.h:139`` — the reference's FunctionActorManager stores
pickled functions there, ``python/ray/_private/function_manager.py:56``),
and placement-group records (``gcs_placement_group_manager.h:221``).

Storage is the ``InMemoryStoreClient`` analog
(``src/ray/gcs/store_client/in_memory_store_client.h:31``); a pluggable
persistent backend is the round-2+ path to GCS fault tolerance.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class ActorInfo:
    actor_id: bytes
    name: Optional[str]
    class_name: str
    state: str = "PENDING_CREATION"  # PENDING_CREATION/ALIVE/RESTARTING/DEAD
    node_id: Optional[str] = None
    worker_id: Optional[bytes] = None
    max_restarts: int = 0
    num_restarts: int = 0
    # in-flight method retries across a restart (at-most-once by default)
    max_task_retries: int = 0
    creation_spec: Optional[dict] = None  # kept for restart (lineage)
    death_cause: Optional[str] = None
    # multi-tenancy: names are scoped per namespace; the owning job is the
    # driver connection that created the actor, and non-detached actors are
    # reaped when it disconnects (GcsActorManager OnJobFinished analog)
    namespace: str = "default"
    job_id: Optional[str] = None
    lifetime: Optional[str] = None  # None | "detached"


@dataclass
class NodeInfo:
    node_id: str
    resources: Dict[str, float]
    alive: bool = True
    start_time: float = field(default_factory=time.time)
    # failure-domain id: hosts of one TPU slice share it and are
    # provisioned/terminated/replaced as one unit (`ray_tpu slices`)
    slice_id: Optional[str] = None


@dataclass
class TaskInfo:
    task_id: bytes
    name: str
    state: str = "PENDING"  # PENDING/RUNNING/FINISHED/FAILED
    node_id: Optional[str] = None
    start_time: float = field(default_factory=time.time)  # submission
    end_time: Optional[float] = None
    # worker-reported execution window + pid (profile events)
    exec_start: Optional[float] = None
    exec_end: Optional[float] = None
    worker_pid: Optional[int] = None
    # distributed trace context (util.tracing): set when the submitter was
    # inside a trace() block; the timeline draws flow arrows from it
    trace_ctx: Optional[dict] = None
    # submitting tenant (stamped from the spec): per-job attribution in
    # the state API and `ray_tpu list tasks`
    job_id: Optional[str] = None


@dataclass
class PlacementGroupInfo:
    pg_id: bytes
    bundles: List[Dict[str, float]]
    strategy: str
    state: str = "PENDING"  # PENDING/CREATED/REMOVED
    bundle_nodes: List[Optional[str]] = field(default_factory=list)
    name: Optional[str] = None


class GcsTables:
    """All control-plane tables behind one lock (single head process)."""

    def __init__(self):
        self.lock = threading.RLock()
        self.kv: Dict[str, Dict[bytes, bytes]] = {}  # namespace -> key -> val
        self.actors: Dict[bytes, ActorInfo] = {}
        # (namespace, name) -> actor_id: two tenants can both own "svc"
        # without colliding; lookups are namespace-scoped (reference
        # GcsActorManager named_actors_ keyed the same way)
        self.named_actors: Dict[tuple, bytes] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.tasks: Dict[bytes, TaskInfo] = {}
        self.placement_groups: Dict[bytes, PlacementGroupInfo] = {}

    # ---- internal KV (GcsInternalKVManager analog) ----
    def kv_put(self, ns: str, key: bytes, value: bytes) -> None:
        with self.lock:
            self.kv.setdefault(ns, {})[key] = value

    def kv_get(self, ns: str, key: bytes) -> Optional[bytes]:
        with self.lock:
            return self.kv.get(ns, {}).get(key)

    def kv_keys(self, ns: str) -> List[bytes]:
        with self.lock:
            return list(self.kv.get(ns, {}).keys())

    def kv_del(self, ns: str, key: bytes) -> None:
        with self.lock:
            self.kv.get(ns, {}).pop(key, None)

    # ---- snapshots for the state API (dashboard/state_aggregator analog) ----
    def snapshot(self) -> Dict[str, Any]:
        with self.lock:
            return {
                "actors": list(self.actors.values()),
                "nodes": list(self.nodes.values()),
                "tasks": list(self.tasks.values()),
                "placement_groups": list(self.placement_groups.values()),
            }

    # ---- persistence (GcsTableStorage over a StoreClient) ----
    def flush(self, store) -> None:
        """Write the control-plane tables through to the store.  Called
        periodically + at shutdown; metadata rates are low, so wholesale
        dumps are simpler than per-mutation write-through and equally
        durable at the flush period granularity."""
        from ray_tpu._private import gcs_storage as gs

        with self.lock:
            kv = {ns: dict(t) for ns, t in self.kv.items()}
            actors = [self._actor_record(a) for a in self.actors.values()]
            tasks = list(self.tasks.values())
            pgs = list(self.placement_groups.values())
        # whole-table replacement so kv_del'd entries don't resurrect on
        # replay, in one transaction per table (one fsync, not per key)
        store.replace_table("kv", [
            (ns.encode() + b"\x00" + k, v)
            for ns, t in kv.items() for k, v in t.items()
        ])
        store.replace_table("tables", [
            (b"actors", gs.dumps(actors)),
            (b"tasks", gs.dumps(tasks)),
            (b"placement_groups", gs.dumps(pgs)),
        ])

    @staticmethod
    def _actor_record(a: "ActorInfo") -> "ActorInfo":
        """Copy without the creation spec (arg blobs aren't replayable —
        their object refs died with the session)."""
        import dataclasses

        return dataclasses.replace(a, creation_spec=None)

    def replay(self, store) -> None:
        """GcsInitData analog: restore KV + historical records from a prior
        head's store.  Prior actors/tasks are history, not live entities —
        their processes died with the old head."""
        from ray_tpu._private import gcs_storage as gs

        with self.lock:
            for key, value in store.items("kv"):
                ns, _, k = key.partition(b"\x00")
                self.kv.setdefault(ns.decode(), {})[k] = value
            blob = store.get("tables", b"actors")
            for a in gs.loads(blob) if blob else []:
                if a.state != "DEAD":
                    a.state = "DEAD"
                    a.death_cause = "head restarted"
                self.actors[a.actor_id] = a
            blob = store.get("tables", b"tasks")
            for t in gs.loads(blob) if blob else []:
                if t.state in ("PENDING", "RUNNING"):
                    t.state = "FAILED"
                self.tasks[t.task_id] = t
            blob = store.get("tables", b"placement_groups")
            for pg in gs.loads(blob) if blob else []:
                pg.state = "REMOVED"
                self.placement_groups[pg.pg_id] = pg
