"""Trend-driven autoscaling: scale on slopes, not on incidents.

``StandardAutoscaler`` reacts to *unmet demand* — work that already can't
be placed.  This module reads the head's metrics TSDB (PR 5) and acts on
*trends* so capacity arrives BEFORE the cluster degrades into something
``ray_tpu doctor`` would flag:

- a scheduler queue whose depth keeps climbing (sustained positive slope,
  growth past ``queue_ratio``) scales worker nodes up — thresholds sit
  deliberately BELOW doctor's ``queue_depth_climb`` trend rule (ratio 2.0
  + never-drained), so the scale-up fires first and the incident never
  forms;
- a serve deployment whose router queue stays backed up scales replicas
  up ahead of doctor's ``router_saturation`` (which needs observed
  stalls);
- per-process RSS growing steadily scales nodes before doctor's
  ``rss_growth`` leak rule (64 MB floor) would fire, spreading the
  working set while the leak is found.

Every decision is emitted to the flight recorder (source ``autoscaler``)
with its evidence — ``ray_tpu events --source autoscaler`` IS the audit
log of why the fleet changed size.

:class:`TrendAutoscaler` folds the policy into the reconcile loop and
adds **slice repair**: a slice with a dead member and no replacement in
flight is swapped atomically through ``provider.replace_slice``
(create-before-terminate), closing the loop doctor's ``slice_degraded``
rule watches.
"""

from __future__ import annotations

import logging
import time
from dataclasses import asdict, dataclass, field
from typing import Callable, Dict, List, Optional

from ray_tpu._private import events as _events
from ray_tpu.autoscaler.autoscaler import AutoscalingConfig, StandardAutoscaler
from ray_tpu.util.doctor import _monotone_frac, _slope_per_min

logger = logging.getLogger(__name__)

# TSDB metrics the policy queries each pass (the PR 5 names)
POLICY_METRICS = (
    "ray_tpu_sched_queue_depth",
    "ray_tpu_serve_router_queue_len",
    "ray_tpu_proc_rss_mb",
)


@dataclass
class TrendPolicyConfig:
    window_s: float = 300.0
    min_points: int = 6           # samples before any slope is trusted
    # queue trend → scale_up_nodes.  Doctor's queue_depth_climb needs the
    # queue to NEVER drain below 1 AND to double; the policy fires on
    # sustained growth alone — earlier by construction.
    queue_depth_min: float = 1.0
    queue_slope_per_min: float = 1.0
    queue_ratio: float = 1.5
    # router backlog → scale_up_replicas (doctor's router_saturation
    # needs a stall event; a standing queue is the precursor)
    router_queue_mean: float = 1.0
    # RSS trend → scale_up_nodes (doctor's rss_growth flags at 64 MB
    # growth; act at half that)
    rss_slope_mb_per_min: float = 5.0
    rss_growth_min_mb: float = 32.0
    rss_monotone_frac: float = 0.8
    cooldown_s: float = 60.0      # per action+entity
    max_step: int = 2             # nodes/replicas added per decision


@dataclass
class Decision:
    action: str                   # scale_up_nodes | scale_up_replicas
    reason: str                   # which trend fired
    amount: int = 1
    deployment: Optional[str] = None
    evidence: Dict = field(default_factory=dict)


class TrendPolicy:
    """Pure series→decisions function plus per-action cooldowns.

    ``series_map`` has the ``query_metric`` shape —
    ``{name: [{"tags": {...}, "points": [[ts, v], ...]}, ...]}`` — so the
    policy runs identically over a live TSDB and synthetic fixtures."""

    def __init__(self, cfg: Optional[TrendPolicyConfig] = None):
        self.cfg = cfg or TrendPolicyConfig()
        self._last_fired: Dict[str, float] = {}

    def _cooled(self, key: str, now: float) -> bool:
        last = self._last_fired.get(key, 0.0)
        if now - last < self.cfg.cooldown_s:
            return False
        self._last_fired[key] = now
        return True

    def decide(self, series_map: Dict[str, list],
               now: Optional[float] = None) -> List[Decision]:
        if now is None:
            now = time.time()
        out: List[Decision] = []
        d = self._queue_trend(series_map)
        if d is not None and self._cooled("nodes/queue", now):
            out.append(d)
        for d in self._router_trend(series_map):
            if self._cooled(f"replicas/{d.deployment}", now):
                out.append(d)
        d = self._rss_trend(series_map)
        if d is not None and self._cooled("nodes/rss", now):
            out.append(d)
        return out

    # -- trends --------------------------------------------------------
    def _queue_trend(self, series_map) -> Optional[Decision]:
        cfg = self.cfg
        for s in series_map.get("ray_tpu_sched_queue_depth", ()):
            pts = s.get("points") or []
            if len(pts) < cfg.min_points:
                continue
            slope = _slope_per_min(pts)
            first = max(pts[0][1], cfg.queue_depth_min)
            last = pts[-1][1]
            if (last >= cfg.queue_depth_min
                    and slope >= cfg.queue_slope_per_min
                    and last >= first * cfg.queue_ratio):
                return Decision(
                    "scale_up_nodes", "queue_depth_slope",
                    amount=min(cfg.max_step,
                               max(1, int(slope // cfg.queue_slope_per_min))),
                    evidence={"slope_per_min": round(slope, 2),
                              "start_depth": pts[0][1], "end_depth": last,
                              "tags": s.get("tags", {})})
        return None

    def _router_trend(self, series_map) -> List[Decision]:
        cfg = self.cfg
        out: List[Decision] = []
        for s in series_map.get("ray_tpu_serve_router_queue_len", ()):
            pts = s.get("points") or []
            if len(pts) < cfg.min_points:
                continue
            mean = sum(p[1] for p in pts) / len(pts)
            if mean >= cfg.router_queue_mean and _slope_per_min(pts) >= 0.0:
                dep = (s.get("tags") or {}).get("deployment", "?")
                out.append(Decision(
                    "scale_up_replicas", "router_backlog",
                    amount=min(cfg.max_step, max(1, int(mean))),
                    deployment=dep,
                    evidence={"mean_queue": round(mean, 2),
                              "window_points": len(pts)}))
        return out

    def _rss_trend(self, series_map) -> Optional[Decision]:
        cfg = self.cfg
        worst = None
        for s in series_map.get("ray_tpu_proc_rss_mb", ()):
            pts = s.get("points") or []
            if len(pts) < cfg.min_points:
                continue
            slope = _slope_per_min(pts)
            growth = pts[-1][1] - pts[0][1]
            if (slope >= cfg.rss_slope_mb_per_min
                    and growth >= cfg.rss_growth_min_mb
                    and _monotone_frac(pts) >= cfg.rss_monotone_frac):
                row = {"slope_mb_per_min": round(slope, 2),
                       "growth_mb": round(growth, 1),
                       "tags": s.get("tags", {})}
                if worst is None or slope > worst["slope_mb_per_min"]:
                    worst = row
        if worst is None:
            return None
        return Decision("scale_up_nodes", "rss_trend", amount=1,
                        evidence=worst)


def serve_replica_scaler(controller=None) -> Callable[[str, int], None]:
    """A ``replica_scaler`` bound to the serve controller's
    ``scale_deployment`` RPC — the glue that lets a TrendAutoscaler act
    on router-backlog slope (``scale_up_replicas`` decisions) by growing
    the deployment's replica goal.  Clamping to autoscaling bounds
    happens controller-side, so this scaler and the controller's own
    demand autoscaler can coexist without fighting."""
    import ray_tpu

    def scale(deployment: str, delta: int) -> None:
        nonlocal controller
        if controller is None:
            from ray_tpu.serve._private.controller import (
                CONTROLLER_NAME, SERVE_NAMESPACE)

            controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                           namespace=SERVE_NAMESPACE)
        ray_tpu.get(
            controller.scale_deployment.remote(deployment, delta=delta),
            timeout=30)

    return scale


class TrendAutoscaler(StandardAutoscaler):
    """StandardAutoscaler + TSDB-trend decisions + slice repair.

    ``replica_scaler(deployment, delta)`` applies serve scale-ups; when
    None, replica decisions are still emitted (audit trail) but only
    logged — the serve controller's own autoscaler may also be active.
    """

    def __init__(self, head_node, provider,
                 config: Optional[AutoscalingConfig] = None,
                 policy: Optional[TrendPolicy] = None,
                 replica_scaler: Optional[Callable[[str, int], None]] = None):
        super().__init__(head_node, provider, config)
        self.policy = policy or TrendPolicy()
        self.replica_scaler = replica_scaler

    # -- TSDB plumbing -------------------------------------------------
    def query_series(self) -> Dict[str, list]:
        tsdb = getattr(self.head, "tsdb", None)
        if tsdb is None:
            return {}
        out: Dict[str, list] = {}
        for name in POLICY_METRICS:
            try:
                out[name] = tsdb.query(
                    name, window_s=self.policy.cfg.window_s).get("series", [])
            except (ValueError, KeyError):
                out[name] = []
        return out

    # -- reconcile -----------------------------------------------------
    def update(self) -> None:
        self.repair_slices()
        try:
            decisions = self.policy.decide(self.query_series())
        except Exception:
            logger.exception("trend policy pass failed")
            decisions = []
        for d in decisions:
            self.apply(d)
        super().update()

    def apply(self, decision: Decision) -> None:
        d = asdict(decision)
        _events.emit("autoscaler", f"scale decision: {decision.action}",
                     severity="WARNING", entity_id=decision.deployment,
                     **d)
        logger.info("autoscaler trend decision: %s", d)
        if decision.action == "scale_up_nodes":
            cfg = self.config
            room = cfg.max_workers - len(self.provider.non_terminated_nodes())
            n = min(decision.amount, max(room, 0))
            if n > 0:
                self.provider.create_node(dict(cfg.worker_node), n)
        elif decision.action == "scale_up_replicas":
            if self.replica_scaler is not None and decision.deployment:
                try:
                    self.replica_scaler(decision.deployment, decision.amount)
                except Exception:
                    logger.exception("replica scale-up failed")

    # -- slice repair ----------------------------------------------------
    def repair_slices(self) -> List[tuple]:
        """Replace every slice with a dead member, atomically.

        A slice is one failure domain: one dead host wedges any gang on
        it, and per-host replacement cannot restore the lease (the
        paper's slice-atomic claim).  Ordering per slice: emit
        'slice replacement started' (doctor's in-flight marker), mark the
        old slice draining at the head (its surviving members' deaths are
        deliberate), create-then-terminate through
        ``provider.replace_slice``, emit 'slice replaced'.  A failed
        creation emits 'slice replacement failed' and leaves the old
        slice as it was (doctor re-opens the degraded finding).  Runs
        serially from the Monitor thread; replace_slice is synchronous,
        so one pass never sees its own replacement target again."""
        members_of = getattr(self.provider, "slice_members", None)
        if members_of is None:
            return []
        replaced: List[tuple] = []
        for sid in list(self.provider.non_terminated_nodes()):
            try:
                members = list(members_of(sid))
            except Exception:
                continue
            if len(members) <= 1:
                continue
            with self.head.lock:
                states = {m: self.head.nodes.get(m) for m in members}
            dead = [m for m, ns in states.items()
                    if ns is not None and not ns.alive]
            if not dead:
                continue
            _events.emit(
                "autoscaler", "slice replacement started",
                severity="WARNING", entity_id=sid, dead_members=dead,
                gang_size=len(members))
            if hasattr(self.head, "mark_slice_draining"):
                self.head.mark_slice_draining(sid)
            cfg = dict(self.config.worker_node)
            cfg.setdefault("slice_hosts", len(members))
            try:
                new_sid = self.provider.replace_slice(sid, cfg)
            except Exception as e:  # noqa: BLE001 — surfaced as event
                if hasattr(self.head, "mark_slice_draining"):
                    # the old slice lives on; future member deaths are
                    # real degradations again
                    self.head.mark_slice_draining(sid, draining=False)
                _events.emit(
                    "autoscaler", "slice replacement failed",
                    severity="ERROR", entity_id=sid,
                    error=f"{type(e).__name__}: {e}"[:200])
                continue
            _events.emit(
                "autoscaler", "slice replaced", severity="WARNING",
                entity_id=sid, replacement=new_sid,
                gang_size=len(members))
            replaced.append((sid, new_sid))
        return replaced
