"""GCP TPU-VM node provider — the cloud path.

Mirrors the reference's first-class TPU support in the GCP provider
(``autoscaler/_private/gcp/node.py:187`` ``GCPTPUNode``, resource class
``GCPTPU`` ``:547``, TPU roles/version in ``gcp/config.py:21-71``): worker
nodes are TPU VMs created/deleted through ``gcloud compute tpus tpu-vm``.
A pod slice is one provider node (the hosts of a slice live and die
together — SURVEY §7's gang/failure-domain note).

Requires the ``gcloud`` CLI and credentials on the head; constructing the
provider without them raises immediately rather than failing mid-scale.
"""

from __future__ import annotations

import json
import shutil
import subprocess
from typing import Dict, List

from ray_tpu.autoscaler.node_provider import NodeProvider


class GCPTPUNodeProvider(NodeProvider):
    """provider_config: {project, zone, accelerator_type (e.g. "v5e-8"),
    runtime_version, startup_script}."""

    def __init__(self, provider_config: dict, cluster_name: str = "default"):
        super().__init__(provider_config, cluster_name)
        if shutil.which("gcloud") is None:
            raise RuntimeError(
                "GCPTPUNodeProvider needs the gcloud CLI with TPU API access; "
                "use LocalNodeProvider for single-host clusters"
            )
        for key in ("project", "zone", "accelerator_type", "runtime_version"):
            if key not in provider_config:
                raise ValueError(f"provider_config missing {key!r}")
        self._counter = 0

    def _gcloud(self, *args: str) -> str:
        cmd = [
            "gcloud", "compute", "tpus", "tpu-vm", *args,
            "--project", self.provider_config["project"],
            "--zone", self.provider_config["zone"],
            "--format", "json",
        ]
        return subprocess.check_output(cmd, text=True)

    def non_terminated_nodes(self) -> List[str]:
        out = json.loads(self._gcloud("list"))
        prefix = f"ray-tpu-{self.cluster_name}-"
        return [
            n["name"].rsplit("/", 1)[-1]
            for n in out
            if n["name"].rsplit("/", 1)[-1].startswith(prefix)
            and n.get("state") in ("CREATING", "READY")
        ]

    def is_running(self, node_id: str) -> bool:
        try:
            n = json.loads(self._gcloud("describe", node_id))
        except subprocess.CalledProcessError:
            return False
        return n.get("state") == "READY"

    def create_node(self, node_config: Dict, count: int = 1) -> List[str]:
        """All-or-nothing batch: if the i-th slice creation fails (quota,
        capacity), the i−1 already-created slices of THIS batch are
        deleted and the error propagates — a partial provision would
        read as fleet capacity that can't actually hold the demand that
        triggered the launch.  The failed name itself is also deleted
        best-effort (the TPU API can leave a half-created node behind)."""
        created: List[str] = []
        for _ in range(count):
            self._counter += 1
            name = f"ray-tpu-{self.cluster_name}-{self._counter}"
            args = [
                "create", name,
                "--accelerator-type", self.provider_config["accelerator_type"],
                "--version", self.provider_config["runtime_version"],
            ]
            script = self.provider_config.get("startup_script")
            if script:
                # member hosts join the head tagged with this provider
                # node as their slice_id — the autoscaler's head-side
                # slice index (idle reasoning, repair) keys on it
                script = f"export RAY_TPU_SLICE_ID={name}\n{script}"
                args += ["--metadata", f"startup-script={script}"]
            try:
                self._gcloud(*args)
            except subprocess.CalledProcessError:
                for partial in (*created, name):  # rollback, newest last
                    self.terminate_node(partial)
                raise
            created.append(name)
        return created

    def terminate_node(self, node_id: str) -> None:
        try:
            self._gcloud("delete", node_id, "--quiet")
        except subprocess.CalledProcessError:
            pass
