"""Cluster launcher: ``ray_tpu up / down`` from a YAML spec.

Analog of the reference's ``ray up`` (``python/ray/autoscaler/_private/
commands.py`` + ``command_runner.py``): a cluster YAML names the head and
worker hosts; ``up`` starts the head there, reads its ``tcp://`` address,
and joins every worker host as a node agent; ``down`` tears everything
back down.  Command execution goes through a pluggable runner:

- ``SSHCommandRunner`` — real multi-host clusters over ``ssh`` (the
  reference's path),
- ``LocalCommandRunner`` — runs the same commands through a local shell
  (single-host bring-up and the hermetic test double, the
  ``fake_multi_node`` role).

YAML shape::

    cluster_name: demo
    provider: {type: local}          # or ssh
    auth: {ssh_user: ubuntu, ssh_private_key: ~/.ssh/key.pem}
    head_node: {address: 10.0.0.1, num_cpus: 8, num_tpus: 4}
    worker_nodes:
      - {address: 10.0.0.2, num_cpus: 8, num_tpus: 4}
    head_start_extra: "--dashboard-port 8265"
"""

from __future__ import annotations

import json
import shlex
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional


class CommandRunner:
    """Run a shell command 'on' a host; subclasses decide transport."""

    def run(self, address: str, cmd: str, timeout: float = 300.0) -> str:
        raise NotImplementedError


class LocalCommandRunner(CommandRunner):
    """Execute on this machine (single-host clusters + hermetic tests)."""

    def run(self, address: str, cmd: str, timeout: float = 300.0) -> str:
        proc = subprocess.run(
            ["bash", "-lc", cmd], capture_output=True, text=True,
            timeout=timeout,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"[{address}] command failed ({proc.returncode}): "
                f"{cmd}\n{proc.stderr[-2000:]}")
        return proc.stdout


class SSHCommandRunner(CommandRunner):
    """ssh into each host (the reference's default transport)."""

    def __init__(self, ssh_user: Optional[str] = None,
                 ssh_private_key: Optional[str] = None,
                 ssh_options: Optional[List[str]] = None):
        self.ssh_user = ssh_user
        self.ssh_private_key = ssh_private_key
        self.ssh_options = list(ssh_options or [
            "-o", "StrictHostKeyChecking=no",
            "-o", "ConnectTimeout=15",
        ])

    def run(self, address: str, cmd: str, timeout: float = 300.0) -> str:
        target = f"{self.ssh_user}@{address}" if self.ssh_user else address
        argv = ["ssh", *self.ssh_options]
        if self.ssh_private_key:
            argv += ["-i", self.ssh_private_key]
        argv += [target, cmd]
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=timeout)
        if proc.returncode != 0:
            raise RuntimeError(
                f"[{address}] ssh command failed ({proc.returncode}): "
                f"{cmd}\n{proc.stderr[-2000:]}")
        return proc.stdout


def _runner_for(config: Dict[str, Any]) -> CommandRunner:
    provider = (config.get("provider") or {}).get("type", "ssh")
    if provider == "local":
        return LocalCommandRunner()
    if provider == "ssh":
        auth = config.get("auth") or {}
        return SSHCommandRunner(
            ssh_user=auth.get("ssh_user"),
            ssh_private_key=auth.get("ssh_private_key"),
            ssh_options=auth.get("ssh_options"),
        )
    raise ValueError(f"unknown provider type {provider!r} (local|ssh)")


def _node_flags(node: Dict[str, Any]) -> str:
    parts = []
    if node.get("num_cpus") is not None:
        parts += ["--num-cpus", str(node["num_cpus"])]
    if node.get("num_tpus") is not None:
        parts += ["--num-tpus", str(node["num_tpus"])]
    return " ".join(parts)


def load_cluster_config(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        config = yaml.safe_load(f)
    if not isinstance(config, dict) or "head_node" not in config:
        raise ValueError(f"{path}: cluster YAML needs at least a head_node")
    return config


def up(config: Dict[str, Any], runner: Optional[CommandRunner] = None,
       python: str = sys.executable) -> Dict[str, Any]:
    """Start the head, read its session record, join every worker host.
    Returns {"address", "authkey", "workers": [...]} for status/down."""
    runner = runner or _runner_for(config)
    head = config["head_node"]
    head_addr = head.get("address", "127.0.0.1")
    name = config.get("cluster_name", "cluster")

    remote_head = head_addr not in ("127.0.0.1", "localhost")
    # a multi-host head must bind its control plane on all interfaces, or
    # remote workers' dials are refused (the default bind is loopback)
    env_prefix = "RAY_TPU_HOST=0.0.0.0 " if remote_head else ""
    head_cmd = (
        # clear any stale session record first — the poll below must see
        # THIS head's record, not a dead predecessor's
        f"rm -f /tmp/ray_tpu/last_session.json; "
        f"{env_prefix}nohup {shlex.quote(python)} -m ray_tpu start --head "
        f"{_node_flags(head)} {config.get('head_start_extra', '')} "
        f"> /tmp/ray_tpu_{name}_head.log 2>&1 & echo started"
    )
    runner.run(head_addr, head_cmd)

    # the head writes its tcp:// address + authkey to the session record
    session = None
    deadline = time.time() + float(config.get("start_timeout_s", 120))
    while time.time() < deadline:
        try:
            out = runner.run(
                head_addr, "cat /tmp/ray_tpu/last_session.json", timeout=30)
            session = json.loads(out)
            break
        except Exception:
            time.sleep(1.0)
    if session is None:
        raise RuntimeError(
            f"head on {head_addr} did not write a session record; see "
            f"/tmp/ray_tpu_{name}_head.log there")
    address = session["address"]
    if remote_head and (address.startswith("tcp://127.")
                        or address.startswith("tcp://0.0.0.0")):
        # the record names a non-routable bind; workers dial the head host
        address = f"tcp://{head_addr}:{address.rsplit(':', 1)[1]}"

    joined = []
    for i, node in enumerate(config.get("worker_nodes") or []):
        addr = node["address"]
        join_cmd = (
            f"nohup {shlex.quote(python)} -m ray_tpu._private.node_agent "
            f"--address {shlex.quote(address[len('tcp://'):])} "
            f"--authkey {session['authkey']} {_node_flags(node)} "
            f"--node-id node-{name}-{i} "
            f"> /tmp/ray_tpu_{name}_worker{i}.log 2>&1 & echo joined"
        )
        runner.run(addr, join_cmd)
        joined.append({"address": addr, "node_id": f"node-{name}-{i}"})
    return {"address": address, "authkey": session["authkey"],
            "workers": joined, "head_address": head_addr}


def down(config: Dict[str, Any], runner: Optional[CommandRunner] = None) -> None:
    """Stop agents and the head on every host in the YAML.  Patterns use
    the ``[.]`` char-class trick so the kill command's own shell never
    matches them; the head is killed by the pid in its session record."""
    runner = runner or _runner_for(config)
    name = config.get("cluster_name", "cluster")
    # scope the kill to THIS cluster's agents (up() names them
    # node-<cluster>-<i>) so co-hosted clusters survive a neighbor's down
    kill_agents = (
        f"pkill -f 'ray_tpu[.]_private[.]node_agent.*node-{name}-' || true"
    )
    # kill by the session-record pid, but ONLY if that pid's cmdline is
    # really a launched head — a stale record can name an unrelated (or
    # the calling!) process, and `ray down` must never kill those
    kill_head = (
        "kill $(python3 - <<'PYEOF'\n"
        "import json\n"
        "try:\n"
        "    pid = json.load(open('/tmp/ray_tpu/last_session.json'))['pid']\n"
        "    cmd = open(f'/proc/{pid}/cmdline', 'rb').read().decode()\n"
        "    cmd = cmd.replace(chr(0), ' ')\n"
        "    if 'ray_tpu' in cmd and '--head' in cmd:\n"
        "        print(pid)\n"
        "except Exception:\n"
        "    pass\n"
        "PYEOF\n"
        ") 2>/dev/null; pkill -f 'ray_tpu start [-][-]head' || true"
    )
    for node in config.get("worker_nodes") or []:
        try:
            runner.run(node["address"], kill_agents, timeout=60)
        except Exception:
            pass
    head_addr = config["head_node"].get("address", "127.0.0.1")
    try:
        runner.run(head_addr, f"{kill_agents}; {kill_head}", timeout=60)
    except Exception:
        pass
