"""ray_tpu.autoscaler — demand- and trend-driven cluster scaling.

Analog of ``python/ray/autoscaler``: ``StandardAutoscaler`` reconcile loop
(``_private/autoscaler.py:167``) over pluggable ``NodeProvider``s
(``autoscaler/node_provider.py:13``), including a local provider (real
node_agent subprocesses, with multi-host emulated TPU slices) and a GCP
TPU provider mirroring the reference's ``GCPTPUNode``
(``_private/gcp/node.py:187``).  ``TrendAutoscaler`` adds TSDB-trend
decisions (scale before doctor flags an incident) and slice-atomic
replacement of degraded slices (``policy.py``).
"""

from ray_tpu.autoscaler.autoscaler import (
    AutoscalingConfig,
    Monitor,
    StandardAutoscaler,
)
from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.autoscaler.local_node_provider import LocalNodeProvider
from ray_tpu.autoscaler.policy import (
    Decision,
    TrendAutoscaler,
    TrendPolicy,
    TrendPolicyConfig,
)

__all__ = [
    "AutoscalingConfig", "StandardAutoscaler", "Monitor", "NodeProvider",
    "LocalNodeProvider",
    "TrendAutoscaler", "TrendPolicy", "TrendPolicyConfig", "Decision",
]
