"""ray_tpu.autoscaler — demand-driven cluster scaling.

Analog of ``python/ray/autoscaler``: ``StandardAutoscaler`` reconcile loop
(``_private/autoscaler.py:167``) over pluggable ``NodeProvider``s
(``autoscaler/node_provider.py:13``), including a local provider (real
node_agent subprocesses) and a GCP TPU provider skeleton mirroring the
reference's ``GCPTPUNode`` (``_private/gcp/node.py:187``).
"""

from ray_tpu.autoscaler.autoscaler import Monitor, StandardAutoscaler
from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.autoscaler.local_node_provider import LocalNodeProvider

__all__ = ["StandardAutoscaler", "Monitor", "NodeProvider", "LocalNodeProvider"]
