"""NodeProvider interface (``python/ray/autoscaler/node_provider.py:13``).

A provider owns the lifecycle of worker nodes for one cluster: create,
terminate, enumerate.  Providers are dumb — all scaling *decisions* live
in :class:`~ray_tpu.autoscaler.autoscaler.StandardAutoscaler`.

Slice semantics: a provider node MAY be a whole TPU pod slice (one create
call = N hosts that live and die together).  ``slice_members`` exposes
the member host ids and ``replace_slice`` swaps a degraded slice
atomically — the replacement is created BEFORE the old slice is
terminated, so fleet capacity never dips below N−1 healthy slices, and a
failed creation leaves the old slice untouched.
"""

from __future__ import annotations

from typing import Dict, List, Optional


class NodeProvider:
    def __init__(self, provider_config: Optional[dict] = None,
                 cluster_name: str = "default"):
        self.provider_config = provider_config or {}
        self.cluster_name = cluster_name

    def non_terminated_nodes(self) -> List[str]:
        """IDs of nodes that are launching or running."""
        raise NotImplementedError

    def is_running(self, node_id: str) -> bool:
        raise NotImplementedError

    def create_node(self, node_config: Dict, count: int = 1) -> List[str]:
        """Launch ``count`` nodes; returns their ids (async startup).

        Must be all-or-nothing per node: a partial provision (some hosts
        of a slice up, the rest failed) is rolled back and raised — a
        half slice can never serve a gang and would leak otherwise."""
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def slice_members(self, node_id: str) -> List[str]:
        """Cluster-level node ids of the hosts behind one provider node.
        Single-host providers return ``[node_id]``; slice providers
        return every member host (what the autoscaler's idle reasoning
        and slice repair iterate over)."""
        return [node_id]

    def replace_slice(self, node_id: str,
                      node_config: Optional[Dict] = None) -> str:
        """Atomically swap one (degraded) slice for a fresh one.

        Ordering is the contract: the replacement is provisioned FIRST —
        only once it exists is the old slice terminated.  If creation
        fails (quota, partial provision), the old slice is left exactly
        as it was and the error propagates; there is no state in which
        the fleet holds fewer slices than it started with."""
        created = self.create_node(dict(node_config or {}), 1)
        if not created:
            raise RuntimeError(
                f"replace_slice: provider created no replacement for {node_id}")
        self.terminate_node(node_id)
        return created[0]

    def shutdown(self) -> None:
        for nid in list(self.non_terminated_nodes()):
            self.terminate_node(nid)
