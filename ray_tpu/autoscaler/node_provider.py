"""NodeProvider interface (``python/ray/autoscaler/node_provider.py:13``).

A provider owns the lifecycle of worker nodes for one cluster: create,
terminate, enumerate.  Providers are dumb — all scaling *decisions* live
in :class:`~ray_tpu.autoscaler.autoscaler.StandardAutoscaler`.
"""

from __future__ import annotations

from typing import Dict, List, Optional


class NodeProvider:
    def __init__(self, provider_config: Optional[dict] = None,
                 cluster_name: str = "default"):
        self.provider_config = provider_config or {}
        self.cluster_name = cluster_name

    def non_terminated_nodes(self) -> List[str]:
        """IDs of nodes that are launching or running."""
        raise NotImplementedError

    def is_running(self, node_id: str) -> bool:
        raise NotImplementedError

    def create_node(self, node_config: Dict, count: int = 1) -> List[str]:
        """Launch ``count`` nodes; returns their ids (async startup)."""
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        for nid in list(self.non_terminated_nodes()):
            self.terminate_node(nid)
