"""StandardAutoscaler: the demand-driven reconcile loop.

Analog of ``python/ray/autoscaler/_private/autoscaler.py:167`` +
``ResourceDemandScheduler`` (``resource_demand_scheduler.py:103``) +
``Monitor`` (``monitor.py:126``): each pass reads the head's pending
resource demand and per-node utilization, bin-packs unmet demand onto the
worker node type, launches up to ``max_workers`` nodes through the
provider, and terminates nodes idle past ``idle_timeout_s``.
"""

from __future__ import annotations

import logging
import threading
import time
from itertools import islice
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu._private import events as _events
from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)


@dataclass
class AutoscalingConfig:
    min_workers: int = 0
    max_workers: int = 2
    idle_timeout_s: float = 30.0
    # resources of one worker node (the single node-type config)
    worker_node: Dict[str, float] = field(default_factory=lambda: {"num_cpus": 1})
    upscaling_speed: int = 2  # max launches per pass


def _fits(req: Dict[str, float], avail: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in req.items())


class StandardAutoscaler:
    def __init__(self, head_node, provider: NodeProvider,
                 config: Optional[AutoscalingConfig] = None):
        self.head = head_node
        self.provider = provider
        self.config = config or AutoscalingConfig()
        self._idle_since: Dict[str, float] = {}

    # -- demand / utilization views ------------------------------------
    # per-tick demand sample bound: to_launch is clamped by
    # upscaling_speed each pass, so any backlog sample big enough to
    # saturate that clamp yields the identical launch decision — and the
    # tick stays O(cap) under head.lock instead of O(backlog) (the 1M
    # queued-task envelope would otherwise copy a dict per parked task
    # while dispatch waits on the lock)
    DEMAND_SAMPLE_CAP = 1024

    def pending_demand(self) -> List[Dict[str, float]]:
        """Resource requests with no node that can fit them now (the
        LoadMetrics pending-demand feed)."""
        head = self.head
        cap_n = self.DEMAND_SAMPLE_CAP
        demands: List[Dict[str, float]] = []
        with head.lock:
            avail = {nid: dict(ns.available) for nid, ns in head.nodes.items()
                     if ns.alive}
            for spec in islice(head.pending_tasks, cap_n):
                demands.append(dict(spec.get("resources", {})))
            # resource-starved backlog: the scheduler parks unplaceable
            # shapes in per-shape queues (node._starved) — exactly the
            # demand that should trigger scale-up, so it MUST feed load
            # metrics (a TPU task waiting for a slice lives here within
            # one scheduler pass of submission).  Every shape gets one
            # representative OUTSIDE the cap (shape count is O(shapes) by
            # design) so a flood of one shape can't hide another's demand;
            # the rest of the budget then samples queue depth.
            starved = [q for q in getattr(head, "_starved", {}).values() if q]
            for q in starved:
                demands.append(dict(q[0].get("resources", {})))
            for q in starved:
                take = min(len(q) - 1, cap_n - len(demands))
                if take <= 0:
                    if len(demands) >= cap_n:
                        break
                    continue
                for spec in islice(q, 1, 1 + take):
                    demands.append(dict(spec.get("resources", {})))
            # tasks leased into a busy worker's pipeline are queued work
            # too (the reference reports lease BACKLOGS to load metrics —
            # resource_demand_scheduler feeds on them); without this, fast
            # worker dispatch hides all queued demand inside pipelines and
            # the autoscaler never sees a reason to scale
            for w in head.workers.values():
                if len(demands) >= cap_n:
                    break
                for spec in islice(w.pipeline, cap_n - len(demands)):
                    demands.append(dict(spec.get("resources", {})))
            for art in head.actors.values():
                if art.info.state == "PENDING_CREATION" and art.worker is None:
                    demands.append(dict(art.info.creation_spec.get("resources", {})))
        unmet = []
        for req in demands:
            placed = False
            for nid, a in avail.items():
                if _fits(req, a):
                    for k, v in req.items():
                        a[k] = a.get(k, 0.0) - v
                    placed = True
                    break
            if not placed:
                unmet.append(req)
        return unmet

    def _slice_members(self, provider_node_id: str) -> List[str]:
        """The cluster node ids behind one provider node (a TPU slice's
        member hosts; ``[provider_node_id]`` for single-host providers).

        When the provider can't map its node to member hosts (GCP: the
        TPU API knows VMs, not our node ids), fall back to the HEAD's
        slice index — hosts join tagged with ``slice_id`` set to the
        provider node name (``--slice-id`` / RAY_TPU_SLICE_ID in the
        startup script).  Without this, a multi-host slice's provider id
        has no head-side NodeState, every member check returns 'idle',
        and idle scale-down deletes a live slice out from under its gang."""
        members_fn = getattr(self.provider, "slice_members", None)
        if members_fn is not None:
            try:
                members = list(members_fn(provider_node_id))
                if members and members != [provider_node_id]:
                    return members
            except Exception:
                pass
        with self.head.lock:
            tagged = [ns.node_id for ns in self.head.nodes.values()
                      if ns.slice_id == provider_node_id]
        return tagged or [provider_node_id]

    def _node_is_idle(self, node_id: str) -> bool:
        """Idle means EVERY member host of the provider node is idle.

        A slice is one failure domain AND one lease unit: scale-down may
        terminate the whole slice or nothing — it must never shrink a
        slice below its gang size.  Reasoning per-host here (the old
        behavior) would have called a slice 'idle' whenever its id had no
        head-side NodeState (the slice id is not a host id!) and killed
        all N hosts under a running gang."""
        return all(self._member_is_idle(m) for m in self._slice_members(node_id))

    def _member_is_idle(self, member_id: str) -> bool:
        head = self.head
        with head.lock:
            ns = head.nodes.get(member_id)
            if ns is None or not ns.alive:
                return True
            if ns.ready_queue:
                return False
            if any(abs(ns.available.get(k, 0.0) - v) > 1e-9
                   for k, v in ns.total.items()):
                return False
            return True

    # -- one reconcile pass --------------------------------------------
    def update(self) -> None:
        cfg = self.config
        nodes = self.provider.non_terminated_nodes()

        # scale up: unmet demand -> bin-pack onto new worker nodes
        unmet = self.pending_demand()
        to_launch = 0
        if unmet:
            # one provider node may be a whole slice: its capacity is
            # slice_hosts x one host's resources, or the bin-pack
            # over-launches slices by up to slice_hosts x
            hosts = max(1, int(cfg.worker_node.get("slice_hosts", 1)))
            node_res = {
                "CPU": float(cfg.worker_node.get("num_cpus", 1)) * hosts,
                "TPU": float(cfg.worker_node.get("num_tpus", 0)) * hosts,
            }
            cap: Dict[str, float] = {}
            for req in unmet:
                if not _fits(req, cap):
                    to_launch += 1
                    for k, v in node_res.items():
                        cap[k] = cap.get(k, 0.0) + v
                for k, v in req.items():
                    cap[k] = cap.get(k, 0.0) - v
        want = max(cfg.min_workers - len(nodes), 0)
        to_launch = max(to_launch, want)
        to_launch = min(to_launch, cfg.upscaling_speed,
                        cfg.max_workers - len(nodes))
        if to_launch > 0:
            logger.info("autoscaler: launching %d worker node(s) for %d unmet "
                        "demands", to_launch, len(unmet))
            created = self.provider.create_node(dict(cfg.worker_node), to_launch)
            _events.emit("autoscaler", "scale up: launched nodes",
                         count=to_launch, nodes=list(created or ()),
                         unmet_demands=len(unmet), reason="pending_demand")

        # scale down: nodes idle past the timeout (never below min_workers).
        # A multi-host slice terminates as ONE unit — and is marked
        # draining at the head first so its member deaths read as a
        # deliberate scale-down, not a degraded slice.
        now = time.time()
        removable = len(nodes) - cfg.min_workers
        for nid in nodes:
            if not self._node_is_idle(nid):
                self._idle_since.pop(nid, None)
                continue
            first = self._idle_since.setdefault(nid, now)
            if removable > 0 and now - first >= cfg.idle_timeout_s:
                logger.info("autoscaler: terminating idle node %s", nid)
                members = self._slice_members(nid)
                if len(members) > 1 and hasattr(self.head, "mark_slice_draining"):
                    self.head.mark_slice_draining(nid)
                self.provider.terminate_node(nid)
                _events.emit("autoscaler", "scale down: terminated idle node",
                             entity_id=nid, idle_s=round(now - first, 1),
                             member_hosts=len(members))
                self._idle_since.pop(nid, None)
                removable -= 1


class Monitor:
    """Background reconcile loop (``_private/monitor.py:126`` analog)."""

    def __init__(self, autoscaler: StandardAutoscaler, interval_s: float = 5.0):
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Monitor":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler-monitor")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.autoscaler.update()
            except Exception:
                logger.exception("autoscaler pass failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
