"""Local provider: worker "nodes" are node_agent subprocesses on this host.

The testing role of the reference's ``FakeMultiNodeProvider``
(``autoscaler/_private/fake_multi_node/node_provider.py:237``) — but the
nodes are *real* processes joining over TCP with private shm namespaces,
so the whole autoscaler loop runs against the production join path.
"""

from __future__ import annotations

import itertools
import os
import subprocess
import sys
import tempfile
from typing import Dict, List

from ray_tpu.autoscaler.node_provider import NodeProvider


class LocalNodeProvider(NodeProvider):
    def __init__(self, head_node, provider_config=None, cluster_name="default"):
        super().__init__(provider_config, cluster_name)
        self.head = head_node
        self._counter = itertools.count(1)
        self.procs: Dict[str, subprocess.Popen] = {}
        self._dirs: List[str] = []

    def non_terminated_nodes(self) -> List[str]:
        return [nid for nid, p in self.procs.items() if p.poll() is None]

    def is_running(self, node_id: str) -> bool:
        p = self.procs.get(node_id)
        return p is not None and p.poll() is None

    def create_node(self, node_config: Dict, count: int = 1) -> List[str]:
        out = []
        host, port = self.head.tcp_address
        for _ in range(count):
            node_id = f"auto-{self.cluster_name}-{next(self._counter)}"
            shm_sub = tempfile.mkdtemp(prefix=f"rtpu-{node_id}-", dir="/dev/shm")
            self._dirs.append(shm_sub)
            env = dict(os.environ)
            env["RAY_TPU_AUTHKEY"] = self.head.authkey.hex()
            cmd = [
                sys.executable, "-m", "ray_tpu._private.node_agent",
                "--address", f"{host}:{port}",
                "--node-id", node_id,
                "--num-cpus", str(int(node_config.get("num_cpus", 1))),
                "--num-tpus", str(int(node_config.get("num_tpus", 0))),
                "--shm-dir", shm_sub,
            ]
            self.procs[node_id] = subprocess.Popen(cmd, env=env)
            out.append(node_id)
        return out

    def terminate_node(self, node_id: str) -> None:
        p = self.procs.pop(node_id, None)
        if p is not None:
            try:
                p.kill()
            except Exception:
                pass

    def shutdown(self) -> None:
        super().shutdown()
        import shutil

        for d in self._dirs:
            shutil.rmtree(d, ignore_errors=True)
        self._dirs.clear()
