"""Local provider: worker "nodes" are node_agent subprocesses on this host.

The testing role of the reference's ``FakeMultiNodeProvider``
(``autoscaler/_private/fake_multi_node/node_provider.py:237``) — but the
nodes are *real* processes joining over TCP with private shm namespaces,
so the whole autoscaler loop runs against the production join path.

Slice mode (``provider_config={"slice_hosts": N}`` or per-call
``node_config["slice_hosts"]``): one provider node is a whole emulated
TPU pod slice — N agent processes sharing a ``slice_id``, provisioned
and terminated as ONE unit.  A spawn failure mid-slice rolls back the
hosts already started (a half slice can never hold a gang) and raises.
"""

from __future__ import annotations

import itertools
import os
import subprocess
import sys
import tempfile
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider


class LocalNodeProvider(NodeProvider):
    def __init__(self, head_node, provider_config=None, cluster_name="default"):
        super().__init__(provider_config, cluster_name)
        self.head = head_node
        self._counter = itertools.count(1)
        self.procs: Dict[str, subprocess.Popen] = {}  # host node id -> proc
        self.slices: Dict[str, List[str]] = {}        # slice id -> host ids
        self._dirs: List[str] = []

    def non_terminated_nodes(self) -> List[str]:
        plain = [nid for nid, p in self.procs.items()
                 if p.poll() is None and not self._slice_of(nid)]
        # a slice counts as non-terminated while ANY member lives: a
        # degraded slice still holds fleet capacity (and is exactly what
        # replace_slice exists for) — it vanishes only when terminated
        slices = [sid for sid, members in self.slices.items()
                  if any(self.procs[m].poll() is None
                         for m in members if m in self.procs)]
        return plain + slices

    def _slice_of(self, host_id: str) -> Optional[str]:
        for sid, members in self.slices.items():
            if host_id in members:
                return sid
        return None

    def is_running(self, node_id: str) -> bool:
        members = self.slice_members(node_id)
        return bool(members) and all(
            m in self.procs and self.procs[m].poll() is None
            for m in members)

    def slice_members(self, node_id: str) -> List[str]:
        return list(self.slices.get(node_id, [node_id]))

    def create_node(self, node_config: Dict, count: int = 1) -> List[str]:
        out = []
        hosts = int(node_config.get(
            "slice_hosts", self.provider_config.get("slice_hosts", 1)))
        for _ in range(count):
            n = next(self._counter)
            if hosts <= 1:
                node_id = f"auto-{self.cluster_name}-{n}"
                self.procs[node_id] = self._spawn_agent(node_id, node_config)
                out.append(node_id)
                continue
            # one provider node = one slice of `hosts` agents that live
            # and die together
            slice_id = f"slice-{self.cluster_name}-{n}"
            members: List[str] = []
            try:
                for h in range(hosts):
                    host_id = f"{slice_id}-h{h}"
                    self.procs[host_id] = self._spawn_agent(
                        host_id, node_config, slice_id=slice_id)
                    members.append(host_id)
            except OSError:
                # partial provision: a half slice can never hold the
                # gang — roll the started hosts back and surface the
                # failure instead of leaking a useless fragment
                for host_id in members:
                    self._kill_host(host_id)
                raise
            self.slices[slice_id] = members
            out.append(slice_id)
        return out

    def _spawn_agent(self, node_id: str, node_config: Dict,
                     slice_id: Optional[str] = None) -> subprocess.Popen:
        host, port = self.head.tcp_address
        shm_sub = tempfile.mkdtemp(prefix=f"rtpu-{node_id}-", dir="/dev/shm")
        self._dirs.append(shm_sub)
        env = dict(os.environ)
        env["RAY_TPU_AUTHKEY"] = self.head.authkey.hex()
        cmd = [
            sys.executable, "-m", "ray_tpu._private.node_agent",
            "--address", f"{host}:{port}",
            "--node-id", node_id,
            "--num-cpus", str(int(node_config.get("num_cpus", 1))),
            "--num-tpus", str(int(node_config.get("num_tpus", 0))),
            "--shm-dir", shm_sub,
        ]
        if slice_id:
            cmd += ["--slice-id", slice_id]
        return subprocess.Popen(cmd, env=env)

    def _kill_host(self, host_id: str) -> None:
        p = self.procs.pop(host_id, None)
        if p is not None:
            try:
                p.kill()
            except Exception:
                pass

    def terminate_node(self, node_id: str) -> None:
        # slice-atomic: ALL member hosts die together, never a subset
        for host_id in self.slice_members(node_id):
            self._kill_host(host_id)
        self.slices.pop(node_id, None)

    def shutdown(self) -> None:
        super().shutdown()
        import shutil

        for d in self._dirs:
            shutil.rmtree(d, ignore_errors=True)
        self._dirs.clear()
